"""Socket — the central transport object.

Capability parity with the reference's Socket
(/root/reference/src/brpc/socket.h:353,361 and socket.cpp:1575-1750):

- **Versioned-id addressing**: sockets live in a ResourcePool and are
  addressed by SocketId; a stale id resolves to None instead of a
  use-after-free. ``set_failed`` bumps the version so every pending
  reference observes the failure.
- **Ordered write queue + keep-write draining**: ``write`` appends to the
  queue; exactly one writer at a time becomes the *drainer* (the
  reference's wait-free CAS chain, socket.cpp:1649; here a flag under a
  short lock — CPython atomics), tries an inline non-blocking send, and
  hands leftovers to a KeepWrite task (socket.cpp:1750) that blocks on
  writability so callers never do.
- **id_wait error propagation**: each queued write may carry a
  correlation id; on socket failure the id is notified through the
  IdPool error path, which is how in-flight RPCs learn their connection
  died (socket.cpp:927 SetFailed).
- **Health-check revival**: a failed socket with ``health_check_interval``
  set is periodically re-connected and revived (details/health_check.cpp).

Fresh-design notes: connection types (single/pooled/short) are managed by
SocketMap at a layer above, as in the reference; the "app_connect"
two-phase connect is merged into ``connect_if_not``.
"""

from __future__ import annotations

import errno as _errno
import socket as _socket
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, List, Optional, Tuple

from ..butil.endpoint import EndPoint
from ..butil.iobuf import IOBuf, IOPortal
from ..butil.logging_util import LOG
from ..butil.resource_pool import ResourcePool
from ..butil.status import Errno
from ..bvar.reducer import Adder
from ..fiber import runtime as fiber_runtime
from ..fiber.versioned_id import global_id_pool

_write_errors = Adder("socket_write_error_count")
_sockets_created = Adder("socket_count")


def encode_ack_frame(ids) -> bytes:
    """"TICI" credit-return frames: [TICI][u32 count][count × u64 id].
    The single encoder for every Python-side producer (the parser lives
    in ici/endpoint.py, the native one in native/src/engine.cpp).
    Chunks at 4096 ids per frame — safely under the native readers'
    8000-id sanity cap — emitting several frames back to back when a
    burst of redemptions queued more."""
    import struct as _struct
    ids = list(ids)
    out = []
    for i in range(0, len(ids), 4096):
        chunk = ids[i:i + 4096]
        out.append(b"TICI" + _struct.pack("<I", len(chunk))
                   + b"".join(_struct.pack("<Q", d) for d in chunk))
    return b"".join(out)


class SocketOptions:
    __slots__ = ("fd", "remote_side", "on_edge_triggered_events", "user",
                 "health_check_interval_s", "connect_timeout_s", "app_data",
                 "ssl_context")

    def __init__(self, fd: Optional[_socket.socket] = None,
                 remote_side: Optional[EndPoint] = None,
                 on_edge_triggered_events: Optional[Callable] = None,
                 user: Any = None,
                 health_check_interval_s: float = 0.0,
                 connect_timeout_s: float = 1.0,
                 ssl_context: Any = None):
        self.fd = fd
        self.remote_side = remote_side
        self.on_edge_triggered_events = on_edge_triggered_events
        self.user = user
        self.health_check_interval_s = health_check_interval_s
        self.connect_timeout_s = connect_timeout_s
        self.app_data = None
        self.ssl_context = ssl_context   # client: wrap on connect (TLS)


_pool: ResourcePool["Socket"] = ResourcePool()


def socket_pool() -> ResourcePool["Socket"]:
    return _pool


class Socket:
    """One connection (or listener). Create via :meth:`create`; address via
    :meth:`address`; never hold a Socket across blocking regions without
    re-addressing if failure matters."""

    __slots__ = (
        "id", "fd", "remote_side", "local_side", "user",
        "on_edge_triggered_events", "app_data",
        "_write_lock", "_write_queue", "_draining", "_drain_epoch",
        "_failed", "_error_code", "_error_text",
        "_nevent", "_nevent_lock",
        "_epollout_event", "_dispatcher",
        "_read_portal", "_avg_msg_size", "_last_protocol",
        "health_check_interval_s", "connect_timeout_s",
        "_pooled_home", "correlation_id",
        "stream_map", "_stream_lock", "tag",
        "ici_endpoint", "ici_peer_domain", "ici_conn_token",
        "direct_read", "_dispatch_lock", "h2_conn", "ssl_context",
        "_pending_acks", "_ack_flush_scheduled",
        "_inflight_ids", "_inflight_lock",
        "_reconnect_lock", "_last_reconnect_at",
        "_cntl_tails", "shm",
        "lane_token", "_lane_pref",
    )

    # -- lifecycle ---------------------------------------------------------

    def __init__(self):
        self.id = 0
        self.fd: Optional[_socket.socket] = None
        self.remote_side: Optional[EndPoint] = None
        self.local_side: Optional[EndPoint] = None
        self.user: Any = None
        self.on_edge_triggered_events: Optional[Callable] = None
        self.app_data: Any = None
        self._write_lock = threading.Lock()
        self._write_queue: Deque[Tuple[IOBuf, int]] = deque()
        self._draining = False
        self._drain_epoch = 0
        self._failed = False
        self._error_code = 0
        self._error_text = ""
        self._nevent = 0
        self._nevent_lock = threading.Lock()
        self._epollout_event = threading.Event()
        self._dispatcher = None
        self._read_portal = IOPortal()
        self._avg_msg_size = 0.0
        self._last_protocol = None
        self.health_check_interval_s = 0.0
        self.connect_timeout_s = 1.0
        self._pooled_home = None          # SocketPool that owns this conn
        self.correlation_id = 0           # single-connection id_wait hint
        self.stream_map = {}              # stream_id -> Stream (streaming RPC)
        self._stream_lock = threading.Lock()
        self.tag = None                   # acceptor tag ("internal" port etc.)
        self.ici_endpoint = None          # lazy IciEndpoint (device payloads)
        self.ici_peer_domain = None       # peer's fabric domain (from meta)
        self.ici_conn_token = None        # conn nonce for descriptor binding
                                          # (client: generated; server: pinned
                                          # from the first frame carrying it)
        # direct-read: the socket is NOT registered with the dispatcher;
        # the synchronous caller reads its responses itself (pooled/short
        # sync fast path — saves a dispatcher wake + fiber spawn + butex
        # wake per call).  ensure_dispatched() converts one-way to the
        # dispatcher-driven mode for async use.
        self.direct_read = False
        self._dispatch_lock = threading.Lock()
        self.h2_conn = None               # server-side HTTP/2 session state
        self.ssl_context = None           # TLS: wrap on connect
        self._pending_acks = []           # ICI desc ids awaiting piggyback
        self._ack_flush_scheduled = False
        # multiplexed in-flight correlation ids awaiting responses on
        # this connection: socket death must error every one of them —
        # without this, a request already flushed to a dying single
        # connection learns of the failure only from its own deadline
        # (≈ the reference's Socket id wait list, socket.cpp:927)
        self._inflight_ids = set()
        self._inflight_lock = threading.Lock()
        self._reconnect_lock = threading.Lock()
        self._last_reconnect_at = 0.0
        self.shm = None                   # lazy ShmSockState (shm data plane)
        # native client completion lane (transport/client_lane.py): a
        # non-zero token means the engine's ClientDemux owns this
        # socket's reads; _lane_pref makes revival re-attach
        self.lane_token = 0
        self._lane_pref = False

    @staticmethod
    def create(options: SocketOptions) -> int:
        """≈ Socket::Create (socket.h:353). Returns SocketId."""
        sid, s = _pool.acquire(Socket())
        s.id = sid
        s.fd = options.fd
        s.remote_side = options.remote_side
        s.user = options.user
        s.on_edge_triggered_events = options.on_edge_triggered_events
        s.app_data = options.app_data
        s.health_check_interval_s = options.health_check_interval_s
        s.connect_timeout_s = options.connect_timeout_s
        s.ssl_context = options.ssl_context
        if s.fd is not None:
            s.fd.setblocking(False)
        _sockets_created << 1
        return sid

    @staticmethod
    def address(sid: int) -> Optional["Socket"]:
        """≈ Socket::Address (socket.h:361): None if the id is stale."""
        return _pool.address(sid)

    @property
    def failed(self) -> bool:
        return self._failed

    def error(self) -> Tuple[int, str]:
        return self._error_code, self._error_text

    # -- connect -----------------------------------------------------------

    def connect_if_not(self) -> int:
        """Ensure self.fd is a connected socket to remote_side
        (≈ Socket::ConnectIfNot, socket.cpp:1373). Returns 0 or errno."""
        if self.fd is not None:
            return 0
        if self.remote_side is None:
            return int(Errno.EINTERNAL)
        try:
            fd = _socket.create_connection(
                self.remote_side.to_sockaddr(),
                timeout=self.connect_timeout_s)
            fd.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            if self.ssl_context is not None:
                # blocking bounded handshake, then the normal
                # non-blocking event-driven life (≈ ssl_helper.cpp's
                # SSL_do_handshake loop on the DCN path)
                fd.settimeout(self.connect_timeout_s + 4.0)
                fd = self.ssl_context.wrap_socket(
                    fd, server_hostname=str(self.remote_side.host))
            fd.setblocking(False)
            self.fd = fd
            self.pin_local_side()
            return 0
        except OSError as e:
            self.set_failed(Errno.EFAILEDSOCKET,
                            f"connect to {self.remote_side}: {e}")
            return e.errno or int(Errno.EFAILEDSOCKET)

    def pin_local_side(self) -> Optional[EndPoint]:
        """Resolve and cache the local address of ``self.fd``.  Called
        eagerly when the fd is installed (connect/accept): resolving it
        lazily can fail on a concurrently-failed fd, and a missing
        conn-pair key silently degrades device attachments to
        host-staged bytes (ici/endpoint.py conn_key_of)."""
        if self.local_side is not None:
            return self.local_side
        if self.fd is None:
            return None
        try:
            name = self.fd.getsockname()
            self.local_side = EndPoint(host=name[0], port=name[1])
        except (OSError, IndexError) as e:
            LOG.warning("socket %s: local address unresolvable (%s); "
                        "device attachments will go host-staged", self.id, e)
        return self.local_side

    # -- failure & revival -------------------------------------------------

    def set_failed(self, code: int = Errno.EFAILEDSOCKET,
                   text: str = "") -> bool:
        """≈ Socket::SetFailed (socket.cpp:927). First caller wins; drains
        the write queue notifying every id_wait; schedules health check."""
        with self._write_lock:
            if self._failed:
                return False
            self._failed = True
            self._error_code = int(code)
            self._error_text = text
            pending = list(self._write_queue)
            self._write_queue.clear()
            # reset the drainer role: any running keep-write task belongs
            # to the old epoch and will observe the bump and exit
            self._draining = False
            self._drain_epoch += 1
        self._epollout_event.set()   # unblock a parked drainer
        if self._dispatcher is not None and self.fd is not None:
            try:
                self._dispatcher.remove_consumer(self.fd)
            except Exception:
                pass
        if self.lane_token:
            # release the native demux's dup'd fd and routing state
            from .client_lane import global_client_lane
            lane = global_client_lane(create=False)
            if lane is not None:
                try:
                    lane.detach(self)
                except Exception:
                    pass
            self.lane_token = 0
        if self.fd is not None:
            try:
                self.fd.close()
            except OSError:
                pass
            self.fd = None
        idp = global_id_pool()
        notified = set()
        for _, id_wait in pending:
            if id_wait and id_wait not in notified:
                notified.add(id_wait)
                idp.error(id_wait, int(code), text)
        # NOTE: correlation_id (the HTTP response-routing hint) is NOT
        # separately notified — HTTP attempts register in the inflight
        # set like everyone else; a second channel would double-error
        # a live id and double-spend its retry budget
        with self._inflight_lock:
            inflight = list(self._inflight_ids)
            self._inflight_ids.clear()
        for cid in inflight:
            # exactly-once per id: queued-write ids were notified above.
            # Finished ids are version-bumped in the pool, so erroring a
            # stale entry is a no-op — over-notification of OLD ids is
            # safe, double-notification of a LIVE id is not (it would
            # double-spend the retry budget)
            if cid not in notified:
                idp.error(cid, int(code), text)
        with self._stream_lock:
            broken_streams = list(self.stream_map.values())
            self.stream_map.clear()
        for stream in broken_streams:
            # receive-only streams would otherwise never learn the
            # connection died; off-thread, user on_closed may block
            fiber_runtime.spawn(stream._on_conn_broken,
                                name="stream_conn_broken")
        if self.ici_endpoint is not None:
            # reclaim device payloads posted on this connection (≈ QP
            # teardown reclaiming posted work requests)
            from ..ici.fabric import in_process_fabric
            in_process_fabric().release_socket(self.id)
        if self.health_check_interval_s > 0:
            from .health_check import start_health_check
            start_health_check(self.id, self.health_check_interval_s)
        return True

    def reconnect_now(self) -> bool:
        """The revival recipe — ONE implementation shared by the health
        checker and the fail-fast path: fresh connect, TLS wrap when
        configured (same as connect_if_not), then reset_connection.
        Serialized by ``_reconnect_lock``: concurrent revivers must not
        each install an fd — the loser's would leak, still registered
        with the dispatcher.  Returns True when the socket is usable."""
        with self._reconnect_lock:
            if not self._failed:
                return True
            if self.remote_side is None:
                return False
            try:
                fd = _socket.create_connection(
                    self.remote_side.to_sockaddr(),
                    timeout=self.connect_timeout_s)
                fd.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
                if self.ssl_context is not None:
                    fd.settimeout(self.connect_timeout_s + 4.0)
                    fd = self.ssl_context.wrap_socket(
                        fd, server_hostname=str(self.remote_side.host))
                self.reset_connection(fd)
                return True
            except OSError:
                return False

    def try_reconnect_now(self) -> bool:
        """Fail-fast revival: the health checker's action without
        waiting for its tick.  The SocketMap's shared "single"
        connection uses this so the first retry after a server restart
        (same address — ephemeral port reuse, a bounced production
        server) reconnects inline instead of failing for up to a whole
        health-check interval.  Rate-limited to one attempt per 500ms;
        a caller that loses the lock race reports the current state
        instead of piling up.  Deliberately NON-blocking: this path
        runs inline on the global timer thread for backup-request
        dispatch (controller._on_id_error -> _issue_rpc), where any
        wait would delay every scheduled deadline in the process."""
        if not self._failed:
            return True
        if self.remote_side is None:
            return False
        if not self._reconnect_lock.acquire(blocking=False):
            return not self._failed
        try:
            if not self._failed:
                return True
            now = time.monotonic()
            if now - self._last_reconnect_at < 0.5:
                return False
            self._last_reconnect_at = now
        finally:
            self._reconnect_lock.release()
        return self.reconnect_now()

    def revive(self) -> None:
        """≈ Socket::Revive (socket.cpp:852): back in business after a
        successful health check re-connect."""
        with self._write_lock:
            self._failed = False
            self._error_code = 0
            self._error_text = ""
        LOG.info("Revived socket %d to %s", self.id, self.remote_side)

    def reset_connection(self, fd: _socket.socket) -> None:
        """Install a fresh connected fd after a failure (health-check
        revival): clears stale read state and re-registers read interest
        so responses flow again."""
        fd.setblocking(False)
        self.fd = fd
        self._read_portal.clear()
        self._last_protocol = None
        self.revive()
        if self._dispatcher is not None:
            self._dispatcher.add_consumer(fd, self.start_input_event)
        elif self._lane_pref:
            # the old fd rode the native client lane: re-attach the
            # fresh one (dispatcher-managed reads are the fallback —
            # a revived socket must never be read by nobody)
            from .client_lane import global_client_lane
            lane = global_client_lane()
            if lane is None or not lane.attach(self):
                from .event_dispatcher import global_dispatcher
                disp = global_dispatcher()
                self.attach_dispatcher(disp)
                disp.add_consumer(fd, self.start_input_event)

    def release(self) -> None:
        """Destroy the socket id (returns slot to pool, bumps version)."""
        self.set_failed(Errno.ECLOSE, "released")
        if self.shm is not None:
            # this conn consumed peer-visible shm slots whose release
            # TLVs will never arrive now: sweep by owner key
            from . import shm_ring
            shm_ring.on_socket_closed(("resp", self.id))
            shm_ring.on_socket_closed(("req", self.id))
            self.shm = None
        # KV pages exported for this connection's sessions (kv/ handoff
        # in flight when the client died): same sweep discipline
        from ..kv import pages as _kv_pages
        _kv_pages.on_socket_closed(("kv", self.id))
        _pool.release(self.id)

    # -- ICI ack piggybacking ----------------------------------------------
    #
    # Redeeming a device descriptor owes the poster a "TICI" credit-return
    # frame.  Request/response traffic means the redeemer almost always
    # writes on this same connection within microseconds — so instead of
    # paying a standalone write (+ an extra epoll wake at the poster) per
    # ack, acks queue here and ride in front of the next outgoing frame.
    # A timer flush bounds the credit-return delay when the connection
    # goes quiet — EXCEPT on direct-read sockets, whose exclusive owner
    # writes to the raw fd outside the write queue (sync fast lane): a
    # timer-thread write could interleave bytes into the middle of an
    # in-flight request frame there, so those acks wait for the owner's
    # next call (the fast lane prepends them to its request parts) or
    # the poster's TTL sweep — the window is 256MB, the delay harmless.

    _ACK_FLUSH_DELAY_S = 0.002

    def queue_ack(self, desc_ids) -> None:
        """Queue ICI ack ids to piggyback on the next write (or a timer
        flush).  Failed socket ⇒ drop: the poster's TTL sweep reclaims."""
        if self._failed:
            return
        schedule = False
        with self._write_lock:
            self._pending_acks.extend(desc_ids)
            if not self._ack_flush_scheduled:
                self._ack_flush_scheduled = True
                schedule = True
        if schedule:
            from ..fiber.timer_thread import global_timer_thread
            global_timer_thread().schedule(self._flush_acks,
                                           self._ACK_FLUSH_DELAY_S)

    def flush_pending_acks(self) -> None:
        """Write queued acks now.  Caller must own the connection (its
        exclusive checkout, or a non-direct-read socket where queued
        writes are always safe)."""
        frame = self._take_ack_frame()
        if frame is not None and not self._failed:
            self.write(IOBuf(frame))

    def add_inflight(self, cid: int) -> None:
        """Track a multiplexed in-flight correlation id; must be called
        BEFORE the request write so a failure racing the flush still
        finds the id."""
        if cid:
            with self._inflight_lock:
                self._inflight_ids.add(cid)

    def remove_inflight(self, cid: int) -> bool:
        """Remove ``cid`` from the in-flight set.  True ⇒ the caller
        CLAIMED it and owns its one notification/completion; False ⇒
        someone else (set_failed's drain, a response, call teardown)
        already did — exactly-once by set ownership."""
        if not cid:
            return False
        with self._inflight_lock:
            if cid in self._inflight_ids:
                self._inflight_ids.remove(cid)
                return True
            return False

    @property
    def error_text(self) -> str:
        return self._error_text

    def write_path_idle(self) -> bool:
        """True when no queued write is pending or draining — the only
        state in which a raw-fd writer (sync fast lane) may bypass the
        write queue without interleaving into a half-sent frame (an
        ack flush that hit EAGAIN keeps a keep-write fiber draining
        after the socket returns to its pool)."""
        return not self._draining and not self._write_queue

    def _take_ack_frame(self) -> Optional[bytes]:
        """Pop queued acks as one encoded TICI frame (caller holds no
        locks).  None when nothing is pending."""
        with self._write_lock:
            if not self._pending_acks:
                return None
            ids = self._pending_acks
            self._pending_acks = []
        return encode_ack_frame(ids)

    def _flush_acks(self) -> None:
        with self._write_lock:
            self._ack_flush_scheduled = False
        if self._failed or not self._pending_acks:
            return
        if not self.direct_read:
            self.flush_pending_acks()
            return
        # direct-read: the exclusive owner writes to the raw fd outside
        # the write queue, so only flush while holding the checkout —
        # take the connection from its pool if it is idle there.  If it
        # is checked out, the owner flushes: the fast lane prepends
        # pending acks to its next request, and SocketPool.put flushes
        # on return.  Short (unpooled) sockets release soon anyway —
        # the poster's TTL sweep reclaims.
        home = self._pooled_home
        if home is not None and home.try_take(self.id):
            try:
                self.flush_pending_acks()
            finally:
                home.put(self.id)

    # -- write path --------------------------------------------------------

    def write(self, buf: IOBuf, id_wait: int = 0) -> int:
        """≈ Socket::Write (socket.cpp:1575): ordered, failure notifies
        ``id_wait`` (exactly once — either here or by set_failed draining
        the queue). Returns 0 on accept (not necessarily flushed)."""
        became_drainer = False
        failed_code = 0
        epoch = 0
        ack_frame = self._take_ack_frame() if self._pending_acks else None
        with self._write_lock:
            if self._failed:
                failed_code = self._error_code or int(Errno.EFAILEDSOCKET)
                failed_text = self._error_text
            else:
                if ack_frame is not None:
                    # merge into the same queue entry: one vectored send
                    buf.prepend_user_data(ack_frame)
                    ack_frame = None
                self._write_queue.append((buf, id_wait))
                if not self._draining:
                    self._draining = True
                    became_drainer = True
                epoch = self._drain_epoch
        if failed_code:
            # enqueue was refused, so set_failed could not have seen this
            # id_wait — notifying here is the exactly-once path
            if id_wait:
                global_id_pool().error(id_wait, failed_code, failed_text)
            return failed_code
        if became_drainer:
            # Inline attempt: most writes complete without a context
            # switch (socket.cpp:1649 "write once before KeepWrite").
            if not self._drain_once(epoch):
                fiber_runtime.spawn(self._keep_write, epoch,
                                    name="keep_write")
        return 0

    def write_parts(self, parts, id_wait: int = 0) -> int:
        """Queue pre-framed byte parts for write (fast response path —
        skips per-part IOBuf assembly on transports that can scatter-
        gather natively; here it wraps the parts zero-copy)."""
        buf = IOBuf()
        for p in parts:
            if len(p):
                buf.append_user_data(p)
        return self.write(buf, id_wait)

    def _drain_once(self, epoch: int) -> bool:
        """Try to flush the queue without blocking. Returns True when done
        with the drainer role (queue empty, socket failed, or the role was
        revoked by a newer epoch), False if keep-write must park."""
        while True:
            with self._write_lock:
                if self._drain_epoch != epoch:
                    return True          # set_failed revoked this drainer
                if self._failed or not self._write_queue:
                    self._draining = False
                    return True
                head, id_wait = self._write_queue[0]
            sent = self._try_send(head, epoch)
            if sent < 0:
                return False            # EAGAIN: keep-write must park
            with self._write_lock:
                if self._drain_epoch != epoch:
                    return True
                if not head.empty():
                    continue
                if self._write_queue and self._write_queue[0][0] is head:
                    self._write_queue.popleft()

    def _try_send(self, buf: IOBuf, epoch: int) -> int:
        """Send as much of ``buf`` as the kernel takes. Returns bytes sent
        or -1 on EAGAIN. Failure marks the socket failed — unless this
        drainer's epoch is stale (a revival installed a fresh fd; a stale
        drainer must not kill the new connection)."""
        if self.fd is None:
            rc = self.connect_if_not()
            if rc != 0:
                return 0   # set_failed already ran; queue was drained
        total = 0
        try:
            while not buf.empty():
                n = buf.cut_into_socket(self.fd)
                if n == 0:
                    return -1
                total += n
            return total
        except BlockingIOError:
            return -1
        except (OSError, ValueError) as e:
            if isinstance(e, OSError) and e.errno in (_errno.EAGAIN,
                                                      _errno.EWOULDBLOCK):
                return -1
            with self._write_lock:
                stale = self._drain_epoch != epoch
            if not stale:
                self.set_failed(Errno.EFAILEDSOCKET, f"send: {e}")
                _write_errors << 1
            return total

    def _keep_write(self, epoch: int) -> None:
        """≈ KeepWrite bthread (socket.cpp:1750): drain until empty,
        parking on writability instead of spinning."""
        while True:
            if self._drain_once(epoch):
                return
            if self._failed or self._drain_epoch != epoch:
                return
            if not self._wait_epollout(timeout=60.0):
                self.set_failed(Errno.EFAILEDSOCKET,
                                "writability wait timed out")
                return

    def _wait_epollout(self, timeout: float) -> bool:
        """≈ Socket::WaitEpollOut (socket.cpp:1224). Registers one-shot
        write interest with the dispatcher and parks the fiber."""
        if self.fd is None:
            return False
        self._epollout_event.clear()
        disp = self._dispatcher
        if disp is None:
            from .event_dispatcher import global_dispatcher
            disp = global_dispatcher()
        disp.add_epollout(self.fd, self._epollout_event.set)
        with fiber_runtime.blocking():
            ok = self._epollout_event.wait(timeout)
        return ok and not self._failed

    # -- read path ---------------------------------------------------------

    def attach_dispatcher(self, dispatcher) -> None:
        self._dispatcher = dispatcher

    def ensure_dispatched(self) -> None:
        """One-way conversion of a direct-read socket to dispatcher-driven
        mode (an async/backup/stream call landed on a pooled connection
        created for sync fast-path reads)."""
        with self._dispatch_lock:
            if not self.direct_read:
                return
            self.direct_read = False
        if self.fd is not None and not self._failed:
            from .event_dispatcher import global_dispatcher
            disp = global_dispatcher()
            self.attach_dispatcher(disp)
            disp.add_consumer(self.fd, self.start_input_event)

    def ensure_client_lane(self) -> None:
        """One-way conversion of a direct-read socket to NATIVE-LANE
        demuxed reads (transport/client_lane.py): the engine's
        ClientDemux parses + correlates responses and delivers batched
        completions.  Falls back to :meth:`ensure_dispatched` whenever
        the lane is unavailable (no native module, TLS, flag off)."""
        attached = False
        with self._dispatch_lock:
            if not self.direct_read:
                return
            if self.fd is not None and not self._failed:
                from .client_lane import global_client_lane
                lane = global_client_lane()
                if lane is not None and lane.attach(self):
                    attached = True
            if attached:
                self.direct_read = False
        if not attached:
            self.ensure_dispatched()

    def start_input_event(self) -> None:
        """≈ Socket::StartInputEvent (socket.cpp:2111): first event spawns
        a consumer task; further events while it runs just bump a counter
        the consumer observes before exiting."""
        with self._nevent_lock:
            self._nevent += 1
            if self._nevent > 1:
                return
        fiber_runtime.spawn(self._process_events, urgent=True,
                            name="input_event")

    def _process_events(self) -> None:
        while True:
            cb = self.on_edge_triggered_events
            if cb is not None and not self._failed:
                try:
                    cb(self)
                except Exception:
                    LOG.exception("edge-triggered callback failed on %s",
                                  self.remote_side)
                    self.set_failed(Errno.EINTERNAL, "event callback raised")
            with self._nevent_lock:
                # consume every event observed while we ran
                if self._nevent <= 1 or self._failed:
                    self._nevent = 0
                    break
                self._nevent = 1
        # drained to EAGAIN: re-enable read interest (one-shot arming —
        # the poller must not spin while this task was working)
        if not self._failed and self.fd is not None \
                and self._dispatcher is not None:
            try:
                self._dispatcher.rearm_read(self.fd.fileno())
            except (OSError, ValueError):
                pass

    def read_into_portal(self, suggested: int = 0) -> int:
        """≈ Socket::DoRead (socket.cpp:1994): one readv-ish gulp into the
        socket's IOPortal. Returns bytes read; 0 on EOF; -1 on EAGAIN."""
        if self.fd is None:
            return 0
        size = suggested or self.suggested_read_size()
        try:
            n = self._read_portal.append_from_socket(self.fd, size)
        except BlockingIOError:
            return -1
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            import ssl as _ssl
            if isinstance(e, (_ssl.SSLWantReadError, _ssl.SSLWantWriteError)):
                return -1               # TLS needs more wire bytes first
            if isinstance(e, OSError) and e.errno in (_errno.EAGAIN,
                                                      _errno.EWOULDBLOCK):
                return -1
            self.set_failed(Errno.EFAILEDSOCKET, f"recv: {e}")
            return 0
        return n

    @property
    def read_portal(self) -> IOPortal:
        return self._read_portal

    def suggested_read_size(self) -> int:
        """Adaptive read sizing: average message size × 16, clamped —
        the reference's trick to amortize syscalls without hogging blocks
        (input_messenger.cpp:352-358)."""
        avg = self._avg_msg_size or 1024.0
        return max(4096, min(int(avg * 16), 1024 * 1024))

    def note_msg_size(self, n: int) -> None:
        # EMA with the same intent as the reference's running average
        self._avg_msg_size = (self._avg_msg_size * 0.875 + n * 0.125
                              if self._avg_msg_size else float(n))

    @property
    def last_protocol(self):
        return self._last_protocol

    @last_protocol.setter
    def last_protocol(self, p) -> None:
        self._last_protocol = p

    def __repr__(self) -> str:
        state = "failed" if self._failed else "ok"
        return f"Socket(id={self.id}, remote={self.remote_side}, {state})"
