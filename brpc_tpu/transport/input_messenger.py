"""InputMessenger — protocol-agnostic message ingestion.

Capability parity with /root/reference/src/brpc/input_messenger.cpp:329-410:
read a gulp (adaptive size) into the socket's portal, then repeatedly cut
messages by trying the connection's last-successful protocol first and
falling back to every registered handler (the PARSE_ERROR_TRY_OTHERS
loop). Each cut message is processed in its own fiber task except the
last, which runs inline on the reading task — the reference's
batching trick that saves one context switch per gulp.
"""

from __future__ import annotations

from typing import Any, List, Optional

from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..bvar.reducer import Adder
from ..fiber import runtime as fiber_runtime
from ..protocol.base import ParseError, Protocol
from .socket import Socket

_messages_in = Adder("input_messenger_messages")
_parse_failures = Adder("input_messenger_parse_error")


class InputMessenger:
    """One per Server (and one global instance for client traffic); holds
    the ordered list of protocol handlers tried during detection."""

    def __init__(self, handlers: Optional[List[Protocol]] = None,
                 arg: Any = None):
        self._handlers: List[Protocol] = list(handlers or [])
        self._arg = arg      # the Server on server side; None on client

    def add_handler(self, proto: Protocol) -> None:
        """≈ InputMessenger::AddHandler (input_messenger.cpp:410)."""
        if proto not in self._handlers:
            self._handlers.append(proto)

    @property
    def handlers(self) -> List[Protocol]:
        return self._handlers

    # The socket's on_edge_triggered_events callback.
    def on_new_messages(self, sock: Socket) -> None:
        """≈ OnNewMessages (input_messenger.cpp:329). Runs on a fiber task
        woken by the dispatcher; reads+parses until EAGAIN."""
        while not sock.failed:
            nread = sock.read_into_portal()
            if nread < 0:
                return                      # EAGAIN: wait for next event
            if nread == 0:
                sock.set_failed(Errno.EEOF, "remote closed connection")
                return
            self._cut_and_process(sock)

    def process_buffered(self, sock: Socket) -> None:
        """Cut + dispatch whatever is already in ``sock.read_portal``.
        The native bridge's passthrough lane feeds gulps the C++ engine
        does not cut (h2/gRPC, redis, thrift, ...) through the same
        registry the Python transport uses."""
        self._cut_and_process(sock)

    def _cut_and_process(self, sock: Socket) -> None:
        source = sock.read_portal
        pending = []
        while not source.empty():
            before = len(source)
            result, proto = self._cut_one(sock)
            if result is None:
                break                       # not enough data
            if not result.ok:
                _parse_failures << 1
                sock.set_failed(
                    Errno.EREQUEST,
                    f"unparsable message (first bytes {source.fetch(16)!r})")
                return
            sock.note_msg_size(before - len(source))
            _messages_in << 1
            pending.append((proto, result.message))
        if not pending:
            return
        # Ordered protocols (streams) process inline on the reading task
        # in arrival order. Non-inline messages get their own task —
        # except the final message of the gulp, which runs inline to save
        # a context switch (input_messenger.cpp:377-394 batching). A
        # non-inline message is NEVER run inline when messages follow it:
        # a blocking RPC handler must not delay its own stream's frames.
        for i, (proto, msg) in enumerate(pending):
            if proto.process_inline or i == len(pending) - 1:
                self._process(proto, msg, sock)
            else:
                fiber_runtime.spawn(self._process, proto, msg, sock,
                                    name=f"process_{proto.name}")

    def _cut_one(self, sock: Socket):
        """Try last-used protocol, then all handlers. Returns
        (ParseResult|None, Protocol|None); None result = need more data."""
        source = sock.read_portal
        tried_last = None
        if sock.last_protocol is not None:
            tried_last = sock.last_protocol
            r = tried_last.parse(source, sock, False, self._arg)
            if r.error == ParseError.OK:
                return r, tried_last
            if r.error == ParseError.NOT_ENOUGH_DATA:
                return None, None
            if r.error in (ParseError.ABSOLUTELY_WRONG,
                           ParseError.TOO_BIG_DATA):
                return r, tried_last
            # TRY_OTHERS falls through to the detection loop
        for proto in self._handlers:
            if proto is tried_last:
                continue
            r = proto.parse(source, sock, False, self._arg)
            if r.error == ParseError.OK:
                sock.last_protocol = proto
                return r, proto
            if r.error == ParseError.NOT_ENOUGH_DATA:
                sock.last_protocol = proto
                return None, None
            if r.error in (ParseError.ABSOLUTELY_WRONG,
                           ParseError.TOO_BIG_DATA):
                return r, proto
        # nobody claims these bytes
        from ..protocol.base import ParseResult
        return ParseResult.absolutely_wrong(), None

    def _process(self, proto: Protocol, msg: Any, sock: Socket) -> None:
        try:
            if self._arg is not None and proto.process_request is not None:
                proto.process_request(msg, sock, self._arg)
            elif proto.process_response is not None:
                proto.process_response(msg, sock)
            else:
                LOG.error("protocol %s has no processor for this side",
                          proto.name)
        except Exception:
            LOG.exception("processing %s message failed", proto.name)


_client_messenger: Optional[InputMessenger] = None


def client_messenger() -> InputMessenger:
    """The process-wide messenger for client-side connections (responses).
    Protocols register themselves here on import."""
    global _client_messenger
    if _client_messenger is None:
        _client_messenger = InputMessenger(arg=None)
    return _client_messenger
