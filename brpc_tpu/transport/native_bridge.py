"""Bridge between the native C++ IO engine and the Python RPC stack.

The engine (brpc_tpu/native) owns connections, framing and writes; this
module gives each native connection a :class:`NativeSocket` (a Socket
living in the same versioned-id pool, so controllers/streams/ICI acks
address it exactly like a Python-transport socket) and routes engine
events into the existing dispatch layers:

    EV_MESSAGE -> server.rpc_dispatch.process_rpc_request (on a fiber)
    EV_ACK     -> ici fabric release (descriptor ownership enforced)
    EV_STREAM  -> protocol.streaming dispatch (socket-binding checked)
    EV_UNKNOWN -> connection failed (native ports speak the framed
                  protocols; the full multi-protocol port — HTTP portal
                  etc. — is the Python path / the internal port)

Zero-copy discipline: a message's payload IOBuf wraps the engine's
NativeBuf (buffer protocol) — no Python-side copy on ingest; responses
hand the engine the IOBuf's backing views, which the engine pins
(Py_buffer) until written.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Optional

from ..butil.endpoint import EndPoint
from ..butil.iobuf import IOBuf
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..fiber import runtime as fiber_runtime
from ..protocol.meta import RpcMeta
from ..protocol.tpu_std import RpcMessage
from .socket import Socket, SocketOptions, socket_pool


class NativeSocket(Socket):
    """Socket whose write path is the native engine (no fd on the
    Python side).  Lives in the regular socket pool: Socket.address()
    resolves it, streams bind to it, ICI endpoints hang off it."""

    __slots__ = ("engine", "conn_id")

    def __init__(self):
        super().__init__()
        self.engine = None
        self.conn_id = 0

    def write_parts(self, parts, id_wait: int = 0) -> int:
        if self._failed:
            code = self._error_code or int(Errno.EFAILEDSOCKET)
            if id_wait:
                from ..fiber.versioned_id import global_id_pool
                global_id_pool().error(id_wait, code, self._error_text)
            return code
        try:
            ack = self._take_ack_frame() if self._pending_acks else None
            if ack is not None:
                parts = (ack, *parts)
            self.engine.send(self.conn_id, parts)
            return 0
        except ConnectionError as e:
            self.set_failed(Errno.EFAILEDSOCKET, str(e))
            if id_wait:
                from ..fiber.versioned_id import global_id_pool
                global_id_pool().error(id_wait, int(Errno.EFAILEDSOCKET),
                                       str(e))
            return int(Errno.EFAILEDSOCKET)

    def write(self, buf: IOBuf, id_wait: int = 0) -> int:
        if self._failed:
            code = self._error_code or int(Errno.EFAILEDSOCKET)
            if id_wait:
                from ..fiber.versioned_id import global_id_pool
                global_id_pool().error(id_wait, code, self._error_text)
            return code
        try:
            parts = tuple(buf.backing_views())
            ack = self._take_ack_frame() if self._pending_acks else None
            if ack is not None:
                parts = (ack, *parts)
            self.engine.send(self.conn_id, parts)
            return 0
        except ConnectionError as e:
            self.set_failed(Errno.EFAILEDSOCKET, str(e))
            if id_wait:
                from ..fiber.versioned_id import global_id_pool
                global_id_pool().error(id_wait, int(Errno.EFAILEDSOCKET),
                                       str(e))
            return int(Errno.EFAILEDSOCKET)


class NativeBridge:
    def __init__(self, server, engine_module, loops: int = 2):
        self._server = server
        self._m = engine_module
        self.engine = engine_module.Engine(self._dispatch, loops=loops)
        self._conns: Dict[int, int] = {}      # engine conn_id -> socket id

    def listen(self, listen_socket) -> None:
        listen_socket.setblocking(False)
        # the bridge owns the fd's lifetime alongside the engine
        self._listen_socket = listen_socket
        name = listen_socket.getsockname()
        self._local_ep = EndPoint(host=name[0], port=name[1])
        self.engine.listen(listen_socket.fileno())

    def stop(self) -> None:
        self.engine.stop()
        for sid in list(self._conns.values()):
            s = Socket.address(sid)
            if s is not None:
                s.release()
        self._conns.clear()

    def connection_count(self) -> int:
        return self.engine.stats()["connections"]

    # -- engine event entry (runs on engine loop threads, GIL held) -----

    def _dispatch(self, event: int, conn_id: int, obj: Any,
                  extra: int) -> None:
        m = self._m
        try:
            if event == m.EV_MESSAGE:
                self._on_message(conn_id, obj, extra)
            elif event == m.EV_ACK:
                self._on_ack(conn_id, obj, extra)
            elif event == m.EV_STREAM:
                self._on_stream(conn_id, obj)
            elif event == m.EV_OPEN:
                self._on_open(conn_id, obj, extra)
            elif event == m.EV_CLOSE:
                self._on_close(conn_id)
            elif event == m.EV_UNKNOWN:
                LOG.warning("non-framed bytes on native port from conn %d "
                            "(%d bytes); closing — use the Python/internal "
                            "port for HTTP", conn_id, len(obj))
        except Exception:
            LOG.exception("native dispatch raised (event=%d)", event)

    def _on_open(self, conn_id: int, ip: str, port: int) -> None:
        sid, s = socket_pool().acquire(NativeSocket())
        s.id = sid
        s.engine = self.engine
        s.conn_id = conn_id
        s.remote_side = EndPoint(host=str(ip), port=int(port))
        s.local_side = self._local_ep    # conn-pair key for ICI binding
        s.tag = None
        self._conns[conn_id] = sid

    def _on_close(self, conn_id: int) -> None:
        sid = self._conns.pop(conn_id, None)
        if sid is None:
            return
        s = Socket.address(sid)
        if s is not None:
            s.release()      # set_failed (streams/ici cleanup) + free slot

    def _sock(self, conn_id: int) -> Optional[Socket]:
        sid = self._conns.get(conn_id)
        return Socket.address(sid) if sid is not None else None

    def _on_message(self, conn_id: int, buf, meta_size: int) -> None:
        sock = self._sock(conn_id)
        if sock is None:
            return
        mv = memoryview(buf)
        meta = RpcMeta.decode(bytes(mv[:meta_size]))
        if meta is None:
            self.engine.close_conn(conn_id)
            return
        payload = IOBuf()
        if len(buf) > meta_size:
            payload.append_user_data(mv[meta_size:])   # zero-copy ingest
        msg = RpcMessage(meta, payload, sock.id)
        from ..server.rpc_dispatch import process_rpc_request
        if self._server.options.usercode_inline:
            # run user code on the IO loop thread: zero handoffs between
            # frame cut and response write (the latency fast path; any
            # blocking handler stalls this loop — that's the contract)
            process_rpc_request(msg, sock, self._server)
            return
        # service code runs on the fiber pool, never on the IO loop
        # (≈ InputMessenger starting a bthread per message batch)
        fiber_runtime.spawn(process_rpc_request, msg, sock, self._server,
                            name="native_rpc")

    def _on_ack(self, conn_id: int, buf, count: int) -> None:
        sock = self._sock(conn_id)
        if sock is None:
            return
        from ..ici.fabric import in_process_fabric
        fabric = in_process_fabric()
        ids = struct.unpack(f"<{count}Q", bytes(buf))
        for desc_id in ids:
            fabric.release(desc_id, only_socket=sock.id)

    def _on_stream(self, conn_id: int, buf) -> None:
        sock = self._sock(conn_id)
        if sock is None:
            return
        mv = memoryview(buf)
        flags = mv[0]
        (dest,) = struct.unpack_from("<Q", mv, 1)
        payload = bytes(mv[13:])
        from ..protocol.streaming import _dispatch as stream_dispatch
        stream_dispatch((flags, dest, payload), sock)
