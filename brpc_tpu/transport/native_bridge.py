"""Bridge between the native C++ IO engine and the Python RPC stack.

The engine (brpc_tpu/native) owns connections, framing and writes; this
module gives each native connection a :class:`NativeSocket` (a Socket
living in the same versioned-id pool, so controllers/streams/ICI acks
address it exactly like a Python-transport socket) and routes engine
events into the existing dispatch layers:

    EV_MESSAGE -> server.rpc_dispatch.process_rpc_request (on a fiber)
    EV_ACK     -> ici fabric release (descriptor ownership enforced)
    EV_STREAM  -> protocol.streaming dispatch (socket-binding checked)
    EV_HTTP    -> one complete HTTP/1.x message cut by the engine;
                  protocol.http parses, server dispatch routes (RPC
                  bridge + restful + builtin portal on the native port)
    EV_BYTES   -> passthrough gulp for protocols the engine does not
                  cut (h2/gRPC, redis, thrift, ...); the server's
                  InputMessenger registry cuts + dispatches
    EV_UNKNOWN -> connection failed (malformed sniffed-HTTP — every
                  well-formed registered protocol is served)

Zero-copy discipline: a message's payload IOBuf wraps the engine's
NativeBuf (buffer protocol) — no Python-side copy on ingest; responses
hand the engine the IOBuf's backing views, which the engine pins
(Py_buffer) until written.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Optional

from time import monotonic_ns as _mono_ns
from struct import unpack_from as _struct_unpack_from

_bytes = bytes

from ..butil.endpoint import EndPoint
from ..butil.flags import define_flag, get_flag, watch_flag
from ..butil.iobuf import IOBuf
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..protocol.meta import (RpcMeta, TLV_ATTACHMENT, TLV_CORRELATION)
from ..fiber import runtime as fiber_runtime
from ..protocol.tpu_std import RpcMessage
from .socket import Socket, SocketOptions, socket_pool

_CID_TLV = TLV_CORRELATION
_ATT_TLV = TLV_ATTACHMENT


class NativeSocket(Socket):
    """Socket whose write path is the native engine (no fd on the
    Python side).  Lives in the regular socket pool: Socket.address()
    resolves it, streams bind to it, ICI endpoints hang off it."""

    __slots__ = ("engine", "conn_id")

    def __init__(self):
        super().__init__()
        self.engine = None
        self.conn_id = 0

    def write_parts(self, parts, id_wait: int = 0) -> int:
        if self._failed:
            code = self._error_code or int(Errno.EFAILEDSOCKET)
            if id_wait:
                from ..fiber.versioned_id import global_id_pool
                global_id_pool().error(id_wait, code, self._error_text)
            return code
        try:
            ack = self._take_ack_frame() if self._pending_acks else None
            if ack is not None:
                parts = (ack, *parts)
            self.engine.send(self.conn_id, parts)
            return 0
        except ConnectionError as e:
            self.set_failed(Errno.EFAILEDSOCKET, str(e))
            if id_wait:
                from ..fiber.versioned_id import global_id_pool
                global_id_pool().error(id_wait, int(Errno.EFAILEDSOCKET),
                                       str(e))
            return int(Errno.EFAILEDSOCKET)

    def write(self, buf: IOBuf, id_wait: int = 0) -> int:
        if self._failed:
            code = self._error_code or int(Errno.EFAILEDSOCKET)
            if id_wait:
                from ..fiber.versioned_id import global_id_pool
                global_id_pool().error(id_wait, code, self._error_text)
            return code
        try:
            parts = tuple(buf.backing_views())
            ack = self._take_ack_frame() if self._pending_acks else None
            if ack is not None:
                parts = (ack, *parts)
            self.engine.send(self.conn_id, parts)
            return 0
        except ConnectionError as e:
            self.set_failed(Errno.EFAILEDSOCKET, str(e))
            if id_wait:
                from ..fiber.versioned_id import global_id_pool
                global_id_pool().error(id_wait, int(Errno.EFAILEDSOCKET),
                                       str(e))
            return int(Errno.EFAILEDSOCKET)


_NATIVE_KINDS = {"echo": 0, "const": 1}

# -- multi-core engine knobs (ISSUE 11) -------------------------------------

define_flag("engine_busy_poll_us", 0,
            "spin this many microseconds on zero-timeout polls before "
            "each blocking epoll_wait in every engine loop (latency-"
            "tail knob; 0 = off).  Burns the loop's core while armed — "
            "only worth it with a core per loop",
            validator=lambda v: isinstance(v, int) and 0 <= v <= 1000000)
define_flag("engine_reuseport", True,
            "shard the native engine's accept across loops with one "
            "SO_REUSEPORT listener per loop (connections pinned to "
            "their accepting loop for life); off = single shared "
            "listener with round-robin adopt handoff",
            validator=lambda v: isinstance(v, bool))
define_flag("rpc_native_stream_lane", True,
            "kind-5 native streaming lane: stream opens dispatch "
            "through the stream shim, chunk bursts enter Python once, "
            "write credit is accounted in C++.  Off = every stream "
            "rides the Python lane (the A/B switch; live-flippable — "
            "already-adopted streams keep their lane)",
            validator=lambda v: isinstance(v, bool))


def default_engine_loops() -> int:
    """Placement-aware loops= default: one loop per core up to 4 (the
    GIL serializes the shim lanes anyway — loops beyond the low single
    digits only buy contention on small boxes; big boxes should set
    ServerOptions.native_loops explicitly)."""
    import os
    return max(1, min(4, os.cpu_count() or 1))

# Closed fallback reason-name mirror — MUST match engine.cpp's kFbNames
# order exactly (the static contract checker, tools/check, pins it).
# Pre-seeds the native_engine_fallback_total family so every reason row
# exists in /vars and /metrics from the first scrape, fallback traffic
# or not — the same eager-registration discipline as client_lane's
# REASONS tuple.
FB_REASON_NAMES = (
    "rpc_dispatch_off", "rpc_meta_tag", "rpc_no_method",
    "rpc_att_over_cap", "rpc_large_frame", "rpc_trace_raw_lane",
    "rpc_shm_lane",
    "http_slim_off", "http_malformed_line", "http_version",
    "http_no_route", "http_expect", "http_upgrade", "http_connection",
    "http_transfer_encoding", "http_bad_header", "http_large_body",
    "http_chunk_stream", "http_lame_duck",
)

# kind-5 streaming-lane reasons ride the same engine fallback family;
# the authoritative mirror of kStreamFbNames lives next to the lane
# (server/stream_slim.STREAM_FB_NAMES, machine-checked by
# tools/check/contracts) — the fallback_total pre-seed below pulls it
# lazily so every stream reason row exists from the first scrape


# ---------------------------------------------------------------------------
# Engine telemetry plumbing: ONE engine.telemetry() snapshot per
# sampling interval serves every native_engine_* bvar read (/vars,
# /metrics, bvar dump and the /native portal page all walk many vars
# back-to-back — per-var engine calls each paid their own GIL crossing,
# and the round-7 per-route PassiveStatus even called http_slim_stats
# TWICE per read).
# ---------------------------------------------------------------------------

import threading as _threading
from time import monotonic as _mono_s


class _TelemetryCache:
    """Short-TTL cache over ``engine.telemetry()``.  ``get()`` returns
    the current snapshot (refreshing at most once per TTL); the
    previous snapshot is retained so windowed reads (busy ratio,
    per-second rates) have an interval to diff against."""

    def __init__(self, engine, ttl_s: float = 0.25):
        self._engine = engine
        self._ttl = ttl_s
        self._lock = _threading.Lock()
        self._snap = None
        self._t = 0.0
        self._prev = None
        self._prev_t = 0.0

    def _refresh_locked(self) -> None:
        now = _mono_s()
        if self._snap is None or now - self._t >= self._ttl:
            snap = self._engine.telemetry()
            self._prev, self._prev_t = self._snap, self._t
            self._snap, self._t = snap, now

    def get(self) -> dict:
        with self._lock:
            self._refresh_locked()
            return self._snap

    def window(self):
        """(prev_snapshot_or_None, current_snapshot, dt_seconds) under
        ONE lock hold — a concurrent refresh between a get() and a
        separate prev read could otherwise pair a snapshot with the
        wrong interval (transient zero rates)."""
        with self._lock:
            self._refresh_locked()
            return (self._prev, self._snap,
                    max(self._t - self._prev_t, 1e-9))

    def busy_ratio(self) -> float:
        """Engine-loop busy fraction (callback time vs epoll_wait) over
        the last snapshot window — the C++ loops' /hotspots answer.
        SUMS across loops: a per-loop view (imbalance!) is
        :meth:`per_loop_busy_ratios`."""
        prev, cur, _dt = self.window()

        def _tot(s):
            return (sum(l["busy_ns"] for l in s["loops"]),
                    sum(l["idle_ns"] for l in s["loops"]))

        busy, idle = _tot(cur)
        if prev is not None:
            pb, pi = _tot(prev)
            busy, idle = busy - pb, idle - pi
        denom = busy + idle
        return busy / denom if denom > 0 else 0.0

    def per_loop_busy_ratios(self) -> list:
        """Windowed busy fraction of EACH loop — the aggregate above
        masks imbalance (one pegged loop + three idle ones reads as
        25% busy); the scaling work keys on the spread."""
        prev, cur, _dt = self.window()
        out = []
        for i, lo in enumerate(cur["loops"]):
            busy, idle = lo["busy_ns"], lo["idle_ns"]
            if prev is not None and i < len(prev["loops"]):
                busy -= prev["loops"][i]["busy_ns"]
                idle -= prev["loops"][i]["idle_ns"]
            denom = busy + idle
            out.append(busy / denom if denom > 0 else 0.0)
        return out

    def loop_busy_imbalance(self) -> float:
        """max − min of the per-loop windowed busy ratios (0 on a
        one-loop engine): the flat-scaling smoking gun — high qps
        plateau + high imbalance = placement problem, not a lock."""
        ratios = self.per_loop_busy_ratios()
        return (max(ratios) - min(ratios)) if len(ratios) > 1 else 0.0


from ..bvar.multi_dimension import PassiveDimension as _PassiveDim


def bucket_label(i: int, nbuckets: int) -> str:
    """Exclusive upper-bound label for log2 bucket i of the engine's
    Hist layout (bucket 0 holds zeros, bucket i covers [2^(i-1), 2^i)).
    Deliberately NOT named ``le``: these are per-bucket counts, not the
    cumulative series Prometheus reserves ``le`` for — ``bin`` keeps
    histogram_quantile() from silently mis-reading them."""
    return "+Inf" if i >= nbuckets - 1 else str(1 << i)

# live bridges with native dispatch configured — the rpc_dump flag
# watcher flips their engines' dispatch switch (capture must see every
# request, so natively-answered methods fall back to Python while on)
import weakref as _weakref

_native_bridges: "_weakref.WeakSet" = _weakref.WeakSet()
_watcher_installed = False


def _install_dump_watcher() -> None:
    global _watcher_installed
    if _watcher_installed:
        return
    _watcher_installed = True
    from ..butil.flags import watch_flag

    def _on_dump_flip(enabled) -> None:
        for bridge in list(_native_bridges):
            bridge.engine.set_native_dispatch(
                bridge._native_ok and not bool(enabled))

    watch_flag("rpc_dump", _on_dump_flip)


class NativeBridge:
    def __init__(self, server, engine_module, loops: int = 0):
        self._server = server
        self._m = engine_module
        if loops <= 0:
            loops = default_engine_loops()   # placement-aware default
        # external_loops: the event loops run on Python-created threads
        # (run_loop below).  A C-created thread pays an mmap + page
        # fault on EVERY cold eval entry (CPython frees the datastack
        # chunk when the last frame pops — measured ~14us/dispatch on
        # this box); a Python thread's resident frames pin the chunk.
        self.engine = engine_module.Engine(self._dispatch, loops=loops,
                                           external_loops=True)
        self._nloops = loops
        self._loop_threads: list = []
        self._listen_socket = None
        self._shard_sockets: list = []
        self._inherited_shards: list = []
        self._conns: Dict[int, int] = {}      # engine conn_id -> socket id
        self._socks: Dict[int, Any] = {}      # engine conn_id -> NativeSocket
        self._pt_queues: Dict[int, Any] = {}  # per-conn dispatch serializers
        self._native_ok = False
        self._stream_capable = False          # kind-5 shims registered
        self._native_vars = []                # PassiveStatus keep-alives
        # one engine.telemetry() snapshot per sampling interval feeds
        # every native_engine_* var, the /native portal and /hotspots
        self.telemetry = _TelemetryCache(self.engine)

    def _register_native_methods(self) -> None:
        """Hand eligible methods to the C++ engine:

        - @raw_method(native=...) echo/const semantics (kind 0/1):
          answered GIL-free — no Python per request at all.
        - plain @raw_method (kind 2): the engine calls the handler in
          burst-batched GIL entries and builds the frame natively.
        - plain (cntl, request) methods (kind 3, the SLIM SERVER LANE):
          the engine scans the meta and calls a shim that runs
          admission, MethodStatus accounting, rpcz sampling and the
          user method in ONE batched GIL entry per burst; the response
          frame is built natively (server/slim_dispatch.py).

        Gating: auth/interceptor-bearing servers keep the full Python
        path for everything (verify-on-first / per-request admission
        must observe every call).  Kinds 2 and 3 run user code on the
        engine loop, so they additionally require usercode_inline — on
        a non-inline server raw and full methods keep the fiber-pool
        path (ADVICE r5 #1/#2: a blocking handler must never freeze a
        loop).  Kinds 0/1/2 bypass server/method concurrency caps and
        are skipped when one is set; the slim shim ENFORCES both caps,
        so kind 3 registers regardless.  Counters surface as
        PassiveStatus bvars (rpc_server_<m>_native_{requests,errors});
        kind-2/3 requests additionally keep full MethodStatus."""
        opts = self._server.options
        if opts.auth is not None or opts.interceptor is not None:
            return
        inline = bool(opts.usercode_inline)
        server_cap = bool(getattr(opts, "max_concurrency", 0))
        from ..bvar.passive_status import PassiveStatus
        from ..tools.rpc_dump import dump_enabled
        registered = False
        for (svc, mth), entry in self._server._methods.items():
            if entry.raw_fn is not None:
                if server_cap:
                    continue      # kinds 0/1/2 bypass server admission
                kind = _NATIVE_KINDS.get(entry.native_kind or "")
                if kind is None:
                    if entry.native_kind:
                        continue  # unknown native= tag: Python path
                    # plain @raw_method: the engine calls the handler
                    # directly (kind 2) — burst-batched GIL entry,
                    # response frame built natively
                    kind = 2
                if kind == 2 and not inline:
                    continue      # user code stays off the IO loop
                if entry.status.max_concurrency or entry.status.limiter:
                    continue      # admission must stay in Python
                data = b""
                if kind == 1:
                    # capture the const response once (behavioral spec)
                    out = entry.raw_fn(b"", None)
                    data = bytes(out[0] if type(out) is tuple else out)
                if kind == 2:
                    # accounting shim: the Python raw lane keeps its
                    # FULL MethodStatus observability (request/error
                    # counts, inflight gauge, latency recorder) —
                    # @raw_method promises "per-method stats still
                    # apply".  ~2us on a warm frame.
                    def _observed(payload, att, _fn=entry.raw_fn,
                                  _st=entry.status, _ns=_mono_ns):
                        _st.on_requested()
                        t0 = _ns()
                        code = 0
                        try:
                            return _fn(payload, att)
                        except BaseException:
                            code = int(Errno.EINTERNAL)
                            raise
                        finally:
                            _st.on_responded(code, (_ns() - t0) // 1000)
                    self.engine.register_native_method(svc, mth, 2, b"",
                                                       _observed)
                else:
                    self.engine.register_native_method(svc, mth, kind,
                                                       data)
            else:
                # slim server lane (kind 3): unary (cntl, request)
                # methods only — streaming shapes keep the full path
                if not inline or entry.grpc_streaming:
                    continue
                from ..server.slim_dispatch import make_slim_handler
                shim = make_slim_handler(self, self._server, entry,
                                         svc, mth)
                self.engine.register_native_method(svc, mth, 3, b"",
                                                   shim)
                # kind-5 STREAMING lane: the same method's stream-open
                # variant — requests carrying the stream TLVs dispatch
                # to the stream shim (interceptor-chain binding) and
                # accepted streams are adopted onto the engine's
                # credit-accounted transport
                if bool(get_flag("rpc_native_stream_lane", True)):
                    from ..server.stream_slim import make_stream_handler
                    self.engine.set_stream_shim(
                        svc, mth,
                        make_stream_handler(self, self._server, entry,
                                            svc, mth))
                    self._stream_capable = True
            safe = f"{svc}_{mth}".lower()
            cache = self.telemetry

            def _mstat(key, _n=f"{svc}.{mth}", _c=cache):
                return _c.get()["methods"].get(_n, {}).get(key, 0)

            self._native_vars.append(PassiveStatus(
                lambda _s=_mstat: _s("handled"),
                name=f"rpc_server_{safe}_native_requests"))
            self._native_vars.append(PassiveStatus(
                lambda _s=_mstat: _s("errors"),
                name=f"rpc_server_{safe}_native_errors"))
            registered = True
        if registered:
            self._native_ok = True
            _native_bridges.add(self)
            _install_dump_watcher()
            self.engine.set_native_dispatch(not dump_enabled())

    def _register_http_routes(self) -> None:
        """Hand eligible HTTP routes to the C++ engine — the SLIM HTTP
        LANE (kind 4, the HTTP analogue of the kind-3 tpu_std lane):
        the engine parses the request line + headers of eligible
        HTTP/1.1 messages itself, batches a read burst's worth, and
        enters Python once per burst calling a per-route shim
        (server/http_slim.py) that keeps admission, MethodStatus and
        rpcz; the response is serialized natively and coalesced into
        the burst's single writev.

        Gating mirrors the tpu_std slim lane: auth/interceptor servers
        keep the full Python path (every request must be observable),
        and the shim runs user code on the engine loop so
        ``usercode_inline`` is required.  Raw/streaming entries and
        everything the engine's header scan rejects (chunked, Expect,
        Upgrade, Connection: close, HTTP/1.0, unregistered paths —
        restful, builtin portal, dotted or slash-suffixed forms) fall
        back to the classic EV_HTTP path byte-identically.  The shim
        enforces both concurrency caps, so capped methods register."""
        opts = self._server.options
        if opts.auth is not None or opts.interceptor is not None:
            return
        if not opts.usercode_inline:
            return
        from ..bvar.passive_status import PassiveStatus
        from ..server.http_slim import make_http_slim_handler
        registered = False
        for (svc, mth), entry in self._server._methods.items():
            if entry.grpc_streaming or entry.raw_fn is not None \
                    or entry.fn is None:
                continue
            path = f"/{svc}/{mth}"
            for http_method in ("POST", "GET"):
                shim = make_http_slim_handler(self, self._server, entry,
                                              svc, mth, http_method)
                self.engine.register_http_route(http_method, path, shim)
            safe = f"{svc}_{mth}".lower()
            cache = self.telemetry

            def _sum(key, _p=path, _c=cache):
                # ONE snapshot per sample covers every HTTP method
                # registered for this path (derived from the live route
                # table, not hard-coded) — the round-7 version called
                # http_slim_stats twice (POST+GET) per var per sample
                routes = _c.get()["routes"]
                return sum(v.get(key, 0) for k, v in routes.items()
                           if k.partition(" ")[2] == _p)

            self._native_vars.append(PassiveStatus(
                lambda _s=_sum: _s("handled"),
                name=f"rpc_server_{safe}_http_slim_requests"))
            self._native_vars.append(PassiveStatus(
                lambda _s=_sum: _s("errors"),
                name=f"rpc_server_{safe}_http_slim_errors"))
            registered = True
        if registered:
            self.engine.set_http_slim(True)

    def _register_engine_vars(self) -> None:
        """Expose the engine's always-on telemetry as ``native_engine_*``
        bvars: every family reads the SAME cached snapshot (one
        engine.telemetry() GIL crossing per sampling interval), appears
        in /vars, and renders as labeled Prometheus exposition lines in
        /metrics.  First native server wins a contended name; stop()
        hides this bridge's vars."""
        from ..bvar.passive_status import PassiveStatus
        cache = self.telemetry
        add = self._native_vars.append
        add(PassiveStatus(
            lambda c=cache: round(c.busy_ratio(), 4),
            name="native_engine_loop_busy_ratio"))
        # the aggregate above sums busy/idle across loops and masks
        # imbalance — the per-loop family plus the max−min spread is
        # what the multi-core scaling work actually watches
        add(_PassiveDim(
            ("loop",),
            lambda c=cache: {str(i): round(r, 4) for i, r
                             in enumerate(c.per_loop_busy_ratios())},
            name="native_engine_loop_busy_ratio_by_loop"))
        add(PassiveStatus(
            lambda c=cache: round(c.loop_busy_imbalance(), 4),
            name="native_engine_loop_busy_imbalance"))
        add(_PassiveDim(
            ("loop",),
            lambda c=cache: {str(i): lo["handoffs"] for i, lo
                             in enumerate(c.get()["loops"])},
            name="native_engine_loop_handoffs"))
        add(PassiveStatus(lambda c=cache: c.get()["wq_hwm"],
                          name="native_engine_wq_hwm"))
        add(PassiveStatus(lambda c=cache: c.get()["inbuf_hwm"],
                          name="native_engine_inbuf_hwm"))
        from ..server.stream_slim import STREAM_FB_NAMES
        add(_PassiveDim(("reason",),
                        lambda c=cache, _sfb=STREAM_FB_NAMES: {
                            **{r: 0 for r in FB_REASON_NAMES},
                            **{r: 0 for r in _sfb},
                            **c.get()["fallbacks"]},
                        name="native_engine_fallback_total"))
        # kind-5 streaming lane: streams open, chunk flow, credit
        # stalls (the /native "streaming" section reads the same
        # snapshot's streams dict)
        add(PassiveStatus(
            lambda c=cache: c.get().get("streams", {}).get("open", 0),
            name="native_stream_open"))
        add(PassiveStatus(
            lambda c=cache: c.get().get("streams", {}).get(
                "chunks_in", 0),
            name="native_stream_chunks_in"))
        add(PassiveStatus(
            lambda c=cache: c.get().get("streams", {}).get(
                "chunks_out", 0),
            name="native_stream_chunks_out"))
        add(PassiveStatus(
            lambda c=cache: c.get().get("streams", {}).get(
                "credit_stalls", 0),
            name="native_stream_credit_stalls"))

        def _chunk_burst(_c=cache):
            bks = _c.get().get("streams", {}).get("chunk_burst", [])
            return {bucket_label(i, len(bks)): n
                    for i, n in enumerate(bks)}

        add(_PassiveDim(("bin",), _chunk_burst,
                        name="native_stream_chunk_burst"))
        add(_PassiveDim(("stage",),
                        lambda c=cache: c.get().get("data_plane_copies",
                                                    {}),
                        name="native_engine_data_plane_copies"))
        add(_PassiveDim(("stage",),
                        lambda c=cache: c.get().get(
                            "data_plane_copy_bytes", {}),
                        name="native_engine_data_plane_copy_bytes"))
        add(_PassiveDim(("lane",), lambda c=cache: {
            ln: d["handled"]
            for ln, d in c.get()["lanes"].items()},
            name="native_engine_lane_requests"))
        add(_PassiveDim(("lane",), lambda c=cache: {
            ln: d["errors"]
            for ln, d in c.get()["lanes"].items()},
            name="native_engine_lane_errors"))

        def _lane_qps(_c=cache):
            # windowed per-second view over the snapshot interval (the
            # Window/PerSecond shape without a sampler thread)
            prev, cur, dt = _c.window()
            out = {}
            for ln, d in cur["lanes"].items():
                base = (prev["lanes"][ln]["handled"]
                        if prev is not None else 0)
                out[ln] = round((d["handled"] - base) / dt, 1) \
                    if prev is not None else 0.0
            return out

        add(_PassiveDim(("lane",), _lane_qps,
                        name="native_engine_lane_qps"))

        def _latency_buckets(_c=cache):
            out = {}
            for ln, d in _c.get()["lanes"].items():
                for stage in ("queue", "shim", "resid"):
                    bks = d[f"{stage}_us"]
                    for i, n in enumerate(bks):
                        out[(ln, stage, bucket_label(i, len(bks)))] = n
            return out

        add(_PassiveDim(("lane", "stage", "bin"), _latency_buckets,
                        name="native_engine_latency_us"))

        def _size_hist(key, _c=cache):
            bks = _c.get()[key]
            return {bucket_label(i, len(bks)): n
                    for i, n in enumerate(bks)}

        add(_PassiveDim(("bin",), lambda _s=_size_hist: _s("burst"),
                        name="native_engine_burst_size"))
        add(_PassiveDim(("bin",), lambda _s=_size_hist: _s("writev_iov"),
                        name="native_engine_writev_iov"))

    def _shard_listen_sockets(self, listen_socket):
        """SO_REUSEPORT sharded accept: one extra listener per loop
        beyond the first, bound to the same (host, port).  Returns the
        full per-loop socket list (index i = loop i) or None when the
        platform/config keeps the single-fd rr-handoff fallback.
        Requires the PRIMARY socket to already carry SO_REUSEPORT
        (server.py sets it pre-bind when the option exists) — the
        kernel refuses mixed-mode binds."""
        import socket as _pysock
        if self._nloops < 2:
            return None
        if not bool(get_flag("engine_reuseport", True)):
            return None
        if not hasattr(_pysock, "SO_REUSEPORT"):
            return None
        try:
            if not listen_socket.getsockopt(_pysock.SOL_SOCKET,
                                            _pysock.SO_REUSEPORT):
                return None
        except OSError:
            return None
        name = listen_socket.getsockname()
        shards = [listen_socket]
        try:
            for _ in range(self._nloops - 1):
                s = _pysock.socket(_pysock.AF_INET, _pysock.SOCK_STREAM)
                try:
                    s.setsockopt(_pysock.SOL_SOCKET,
                                 _pysock.SO_REUSEADDR, 1)
                    s.setsockopt(_pysock.SOL_SOCKET,
                                 _pysock.SO_REUSEPORT, 1)
                    s.bind((name[0], name[1]))
                    s.listen(1024)
                    s.setblocking(False)
                except BaseException:
                    s.close()
                    raise
                shards.append(s)
        except OSError as e:
            LOG.warning("SO_REUSEPORT shard bind failed (%s); falling "
                        "back to single-listener rr placement", e)
            for s in shards[1:]:
                s.close()
            return None
        return shards

    def listen(self, listen_socket, inherited_shards=None) -> None:
        listen_socket.setblocking(False)
        # the bridge owns the fd's lifetime alongside the engine
        self._listen_socket = listen_socket
        self._shard_sockets = []
        self._inherited_shards = list(inherited_shards or [])
        name = listen_socket.getsockname()
        self._local_ep = EndPoint(host=name[0], port=name[1])
        self._register_native_methods()
        self._register_http_routes()
        self._register_engine_vars()
        # kind-5 streaming lane: batched chunk delivery (pre-listen)
        # and the lane mode — mode 2 NAMES the non-inline decline,
        # mode 0 the no-capability one (closed StreamFb enum); the
        # lane flag is live-flippable for the native-vs-Python A/B
        # (already-adopted streams keep their lane)
        from ..server.stream_slim import slim_chunks
        self.engine.set_stream_chunks(slim_chunks)

        def _stream_mode(enabled, _self=self) -> int:
            if not _self._server.options.usercode_inline:
                return 2
            return 1 if (_self._stream_capable and bool(enabled)) else 0

        self.engine.set_stream_mode(
            _stream_mode(get_flag("rpc_native_stream_lane", True)))
        watch_flag("rpc_native_stream_lane",
                   lambda v, _e=self.engine, _m=_stream_mode:
                   _e.set_stream_mode(_m(v)))
        from ..protocol.base import max_body_size
        self.engine.set_http_max_body(int(max_body_size()))
        # kind-3 domain-exchange answers: the local ici-domain TLV is a
        # per-process constant (empty when ici is off) — cache it in
        # the engine so slim responses carry it natively
        from ..server.rpc_dispatch import _domain_tlv
        self.engine.set_domain_tlv(_domain_tlv())
        # per-burst accounting epilogue: the slim fast template
        # aggregates admitted-verdict counts per engine read burst and
        # this hook flushes them under one lock per burst
        from ..server.slim_dispatch import flush_burst_accounting
        self.engine.set_burst_end(flush_burst_accounting)
        # busy-poll spin for the latency tail (live-flippable: the
        # engine reads a relaxed atomic per loop iteration)
        self.engine.set_busy_poll_us(int(get_flag("engine_busy_poll_us")))
        watch_flag("engine_busy_poll_us",
                   lambda v, _e=self.engine: _e.set_busy_poll_us(int(v)))
        # SO_REUSEPORT sharded accept: one listener per loop, each loop
        # accepts and pins its own connections (brpc's per-core
        # EventDispatcher discipline); single-fd rr handoff otherwise.
        # Hot restart: a predecessor's shard listeners (fd-passed, with
        # their kernel queues) are reused when the count fits — one per
        # loop beyond the primary; a mismatched handoff (different loop
        # count across versions) closes the extras and re-shards fresh.
        shards = None
        if self._inherited_shards:
            if len(self._inherited_shards) >= self._nloops - 1 \
                    and self._nloops > 1:
                shards = [listen_socket] \
                    + self._inherited_shards[:self._nloops - 1]
                for s in shards:
                    s.setblocking(False)
                leftovers = self._inherited_shards[self._nloops - 1:]
            else:
                leftovers = self._inherited_shards
            for s in leftovers:
                s.close()
            if leftovers:
                LOG.warning("hot restart: closed %d inherited shard "
                            "listener(s) beyond this server's %d "
                            "loop(s)", len(leftovers), self._nloops)
            self._inherited_shards = []
        if shards is None:
            shards = self._shard_listen_sockets(listen_socket)
        if shards is not None:
            self._shard_sockets = shards[1:]
            self.engine.listen_sharded([s.fileno() for s in shards])
        else:
            self.engine.listen(listen_socket.fileno())
        import threading
        for i in range(self._nloops):
            t = threading.Thread(target=self.engine.run_loop, args=(i,),
                                 name=f"native-loop-{i}", daemon=True)
            t.start()
            self._loop_threads.append(t)

    # -- operability plane: drain / lame duck / hot restart -------------

    def enter_lame_duck(self, signal: bool = True) -> None:
        """Drain mode: disarm the engine's listeners (fds stay open for
        a hot-restart successor) and — when ``signal`` — start stamping
        the lame-duck TLV on natively-built responses; new kind-4 HTTP
        matches decline to the classic lane, whose serializer owns the
        x-lame-duck / Connection: close headers.  A prebuilt engine
        without the hook degrades to accept-pause via the admission
        rejection alone."""
        try:
            self.engine.set_lame_duck(2 if signal else 1)
        except AttributeError:
            LOG.warning("native engine lacks set_lame_duck; drain "
                        "relies on admission rejections only")

    def listener_sockets(self):
        """The bound listening sockets this bridge serves (primary +
        SO_REUSEPORT shards): the hot-restart exporter passes their fds
        to the successor binary."""
        out = []
        if self._listen_socket is not None:
            out.append(self._listen_socket)
        out.extend(self._shard_sockets)
        return out

    def force_close_all(self, reason: str) -> int:
        """Drain-grace expiry: force-close every live native connection
        with the named reason.  Returns the count."""
        n = 0
        for conn_id, sock in list(self._socks.items()):
            try:
                sock.set_failed(Errno.ELOGOFF, reason)
            except Exception:
                pass
            try:
                self.engine.close_conn(conn_id)
            except (ConnectionError, OSError):
                pass
            n += 1
        return n

    def stop(self) -> None:
        for v in self._native_vars:
            v.hide()
        self._native_vars.clear()
        _native_bridges.discard(self)
        self.engine.stop()
        for t in self._loop_threads:
            t.join(timeout=5.0)
        self._loop_threads.clear()
        # close the listen fd: the engine no longer accepts, but the
        # KERNEL still completes handshakes into the backlog of an open
        # listener — clients (health checks!) would "connect" to a
        # server that never serves them and hang until their deadlines
        ls = getattr(self, "_listen_socket", None)
        if ls is not None:
            try:
                ls.close()
            except OSError:
                pass
            self._listen_socket = None
        for s in getattr(self, "_shard_sockets", []):
            try:
                s.close()
            except OSError:
                pass
        self._shard_sockets = []
        for sid in list(self._conns.values()):
            s = Socket.address(sid)
            if s is not None:
                s.release()
        self._conns.clear()
        self._socks.clear()

    def connection_count(self) -> int:
        return self.engine.stats()["connections"]

    # -- engine event entry (runs on engine loop threads, GIL held) -----

    def _dispatch(self, event: int, conn_id: int, obj: Any,
                  extra: int) -> None:
        m = self._m
        try:
            if event == m.EV_MESSAGE:
                self._on_message(conn_id, obj, extra)
            elif event == m.EV_ACK:
                self._on_ack(conn_id, obj, extra)
            elif event == m.EV_STREAM:
                self._on_stream(conn_id, obj)
            elif event == getattr(m, "EV_HTTP", -1):
                self._on_http(conn_id, obj)
            elif event == getattr(m, "EV_BYTES", -1):
                self._on_bytes(conn_id, obj)
            elif event == m.EV_OPEN:
                self._on_open(conn_id, obj, extra)
            elif event == m.EV_CLOSE:
                self._on_close(conn_id)
            elif event == m.EV_UNKNOWN:
                LOG.warning("malformed HTTP on native port from conn %d "
                            "(%d bytes); closing (well-formed requests of "
                            "any registered protocol are served here)",
                            conn_id, len(obj))
        except Exception:
            LOG.exception("native dispatch raised (event=%d)", event)

    def _on_open(self, conn_id: int, ip: str, port: int) -> None:
        sid, s = socket_pool().acquire(NativeSocket())
        s.id = sid
        s.engine = self.engine
        s.conn_id = conn_id
        s.remote_side = EndPoint(host=str(ip), port=int(port))
        s.local_side = self._local_ep    # conn-pair key for ICI binding
        s.tag = None
        self._conns[conn_id] = sid
        self._socks[conn_id] = s         # slim-lane lookup (one dict hit)

    def _on_close(self, conn_id: int) -> None:
        q = self._pt_queues.pop(conn_id, None)
        if q is not None:
            q.stop()
        self._socks.pop(conn_id, None)
        sid = self._conns.pop(conn_id, None)
        if sid is None:
            return
        s = Socket.address(sid)
        if s is not None:
            s.release()      # set_failed (streams/ici cleanup) + free slot

    def _sock(self, conn_id: int) -> Optional[Socket]:
        sid = self._conns.get(conn_id)
        return Socket.address(sid) if sid is not None else None

    @staticmethod
    def _scan_request_meta(data):
        """Minimal TLV walk for the raw lane: (cid, service, method,
        att_size, timeout_ms, ici_domain, ici_conn, timeout_present,
        tenant) —
        or None when the
        meta carries any controller-tier tag (compress=2, error=6/7,
        auth=8, trace=9, span=10/11 — raw handlers have no span
        machinery, so traced requests take the full path; the NATIVE
        slim lanes carry trace context through their shims instead —
        stream=12/14, ici desc=16) or is malformed, meaning the full
        RpcMeta path must run.  The tenant tag (22) is tolerated like
        the deadline tag: raw handlers ignore it, the full/slim-meta
        path forwards it to the admission stage.  ~3x cheaper
        than RpcMeta.decode for the echo-class frame; a successful scan
        also lets the FULL method path build its RpcMeta from these
        fields without re-walking (slim-meta path in _on_message)."""
        cid = 0
        svc = mth = None
        att = tmo = 0
        tmo_seen = False
        dom = nonce = ten = b""
        off, end = 0, len(data)
        try:
            while off < end:
                tag = data[off]
                (ln,) = _struct_unpack_from("<I", data, off + 1)
                off += 5
                if off + ln > end:
                    return None
                if tag == 1:
                    (cid,) = _struct_unpack_from("<Q", data, off)
                elif tag == 4:
                    svc = _bytes(data[off:off + ln]).decode()
                elif tag == 5:
                    mth = _bytes(data[off:off + ln]).decode()
                elif tag == 3:
                    (att,) = _struct_unpack_from("<I", data, off)
                elif tag == 13:
                    (tmo,) = _struct_unpack_from("<I", data, off)
                    tmo_seen = True
                elif tag == 15:
                    dom = _bytes(data[off:off + ln])
                elif tag == 17:
                    nonce = _bytes(data[off:off + ln])
                elif tag == 22:
                    ten = _bytes(data[off:off + ln])
                else:
                    return None   # controller-tier tag: full path
                off += ln
        except (struct.error, IndexError, UnicodeDecodeError):
            return None
        if svc is None or mth is None:
            return None
        return cid, svc, mth, att, tmo, dom, nonce, tmo_seen, ten

    def _on_message(self, conn_id: int, buf, meta_size: int) -> None:
        sock = self._sock(conn_id)
        if sock is None:
            return
        mv = memoryview(buf)
        server = self._server
        scan = None
        if server.options.usercode_inline \
                and server.options.auth is None \
                and server.options.interceptor is None:
            # raw latency lane: frame → handler → flat-TLV response on
            # this loop thread, no RpcMeta/ServerController/IOBuf/span
            # in the path (the handler opted into the bytes-in/bytes-
            # out contract via @raw_method)
            scan = self._scan_request_meta(mv[:meta_size])
            if scan is not None:
                entry = server.find_method(scan[1], scan[2])
                if entry is not None and entry.raw_fn is not None \
                        and self._raw_dispatch(scan[0], scan[3], mv,
                                               meta_size, sock, entry):
                    return
        if scan is not None:
            # slim-meta path: the scan proved no controller-tier tags —
            # build the RpcMeta from its fields, skip the full decode
            meta = RpcMeta()
            (meta.correlation_id, meta.service_name, meta.method_name,
             meta.attachment_size, meta.timeout_ms, meta.ici_domain,
             meta.ici_conn, meta.timeout_present, meta.tenant) = scan
        else:
            meta = RpcMeta.decode(bytes(mv[:meta_size]))
        if meta is None:
            self.engine.close_conn(conn_id)
            return
        payload = IOBuf()
        if len(buf) > meta_size:
            payload.append_user_data(mv[meta_size:])   # zero-copy ingest
        msg = RpcMessage(meta, payload, sock.id)
        from ..server.rpc_dispatch import process_rpc_request
        if server.options.usercode_inline:
            # run user code on the IO loop thread: zero handoffs between
            # frame cut and response write (the latency fast path; any
            # blocking handler stalls this loop — that's the contract)
            process_rpc_request(msg, sock, server)
            return
        # service code runs on the fiber pool, never on the IO loop
        # (≈ InputMessenger starting a bthread per message batch)
        fiber_runtime.spawn(process_rpc_request, msg, sock, server,
                            name="native_rpc")

    def _raw_dispatch(self, cid: int, na: int, mv, meta_size: int, sock,
                      entry) -> bool:
        """Slim turnaround for @raw_method handlers.  Returns False when
        the request needs the full path after all (live traffic capture
        — the dump observer must see the RpcMessage).  Passive rpcz
        SAMPLING deliberately skips raw methods and explicitly traced
        requests never reach here (the meta scan rejects tag 9; the
        native engine mirrors this as the named `rpc_trace_raw_lane`
        fallback) — that is the lane's contract (documented on
        @raw_method)."""
        from ..tools.rpc_dump import dump_enabled
        if dump_enabled():
            return False
        server = self._server
        if not server.on_request_in():
            self._raw_error(sock, cid, int(Errno.ELIMIT),
                            "server max_concurrency")
            return True
        status = entry.status
        if not status.on_requested():
            server.on_request_out()
            self._raw_error(sock, cid, int(Errno.ELIMIT),
                            f"{status.full_name} max_concurrency")
            return True
        t0 = _mono_ns()
        payload = mv[meta_size:]
        att = None
        if na:
            if na > len(payload):
                # malformed frame: an attachment-size TLV exceeding the
                # body must be rejected, not silently fused into payload
                status.on_responded(int(Errno.EREQUEST), 0)
                server.on_request_out()
                self._raw_error(sock, cid, int(Errno.EREQUEST),
                                "attachment size exceeds body")
                return True
            att = payload[len(payload) - na:]
            payload = payload[:len(payload) - na]
        code = 0
        try:
            # handler AND response build/send under one guard: a bad
            # return value (None, wrong arity, non-buffer) must release
            # the admission slots and answer the client, not leak them
            try:
                out = entry.raw_fn(payload, att)
                resp, ratt = out if type(out) is tuple else (out, None)
                nr = len(ratt) if ratt is not None else 0
                mb = _CID_TLV + struct.pack("<Q", cid)
                if nr:
                    mb += _ATT_TLV + struct.pack("<I", nr)
                head = (b"TRPC"
                        + struct.pack("<II", len(mb) + len(resp) + nr,
                                      len(mb))
                        + mb)
                if nr:
                    self.engine.send(sock.conn_id, (head, resp, ratt))
                else:
                    self.engine.send(sock.conn_id, (head, resp))
            except ConnectionError as e:
                sock.set_failed(Errno.EFAILEDSOCKET, str(e))
            except Exception as e:
                LOG.exception("raw method %s failed", status.full_name)
                code = int(Errno.EINTERNAL)
                self._raw_error(sock, cid, code,
                                f"{type(e).__name__}: {e}")
        finally:
            status.on_responded(code, (_mono_ns() - t0) // 1000)
            server.on_request_out()
        return True

    def _raw_error(self, sock, cid: int, code: int, text: str) -> None:
        m = RpcMeta()
        m.correlation_id = cid
        m.error_code = code
        m.error_text = text
        body = m.encode()
        try:
            self.engine.send(sock.conn_id,
                             (b"TRPC" + struct.pack("<II", len(body),
                                                    len(body)), body))
        except ConnectionError:
            pass

    def _process_http(self, conn_id: int, sock, buf) -> None:
        """One COMPLETE raw HTTP/1.x message cut by the engine: parse
        headers in Python (protocol/http.py — the single source of HTTP
        semantics) and route through the normal server dispatch
        (RPC bridge, restful routes, builtin portal).  This is the
        native port serving every protocol, like the reference's C++
        core does (input_messenger.cpp:329)."""
        from ..protocol import http as http_mod

        source = IOBuf()
        source.append_user_data(memoryview(buf))
        res = http_mod.parse(source, sock, False, None)
        if not res.ok or res.message is None \
                or not res.message.is_request:
            self.engine.close_conn(conn_id)
            return
        http_mod._process_request(res.message, sock, self._server)
        if not res.message.keep_alive:
            # HTTP/1.0 (or explicit Connection: close): the SERVER ends
            # the connection after the response — 1.0 clients may wait
            # for EOF as the message delimiter.  The engine's
            # close-after-flush linger drains the queued response first.
            self.engine.close_conn(conn_id)

    def _conn_queue(self, conn_id: int, sock):
        """Per-connection dispatch serializer for non-inline servers:
        user code stays OFF the engine loop (the bridge's EV_MESSAGE
        contract — a blocking handler must never freeze a loop) while
        per-connection FIFO order is preserved, which is exactly what
        HTTP/1.1 pipelining (no correlation id — responses must leave
        in request order) and the passthrough portal's single-consumer
        discipline need.  Items are ("http", buf) messages or
        ("bytes", buf) passthrough gulps."""
        q = self._pt_queues.get(conn_id)
        if q is not None:
            return q
        from ..fiber.execution_queue import ExecutionQueue

        def executor(it, _cid=conn_id, _sock=sock):
            for kind, chunk in it:
                if kind == "http":
                    try:
                        self._process_http(_cid, _sock, chunk)
                    except Exception:
                        LOG.exception("native HTTP dispatch failed")
                        _sock.set_failed(Errno.EREQUEST,
                                         "http dispatch error")
                        # close the engine conn too (mirrors
                        # _pump_passthrough): the client must see EOF,
                        # not hang until its own timeout
                        self.engine.close_conn(_cid)
                else:
                    messenger = getattr(self._server, "_messenger", None)
                    if messenger is None:
                        self.engine.close_conn(_cid)
                        break
                    _sock.read_portal.append_user_data(memoryview(chunk))
                    self._pump_passthrough(_cid, _sock, messenger)
                if _sock.failed:
                    break

        q = self._pt_queues[conn_id] = ExecutionQueue(
            executor, name=f"native_pt_{conn_id}")
        return q

    def _on_http(self, conn_id: int, buf) -> None:
        """Inline servers process on the loop thread (zero handoffs —
        the usercode_inline contract: handlers never block).  Otherwise
        the message runs on the per-connection ExecutionQueue, keeping
        user dispatch off the shared IO loop while preserving the
        request-order response discipline (ADVICE r5 #1)."""
        sock = self._sock(conn_id)
        if sock is None:
            return
        if self._server.options.usercode_inline:
            self._process_http(conn_id, sock, buf)
            return
        self._conn_queue(conn_id, sock).execute(("http", buf))

    def _on_bytes(self, conn_id: int, buf) -> None:
        """Passthrough gulp: the engine recognized none of its natively-
        cut protocols on this connection, so every read lands here whole
        and the server's InputMessenger registry (h2/gRPC, redis,
        thrift, streams — the same table the Python transport uses)
        cuts and dispatches it.  This makes the native port speak EVERY
        registered protocol (≈ input_messenger.cpp:329's all-protocols
        loop), with tpu_std and HTTP/1.x still cut in C++.

        Inline servers process on the loop thread; otherwise the gulps
        ride the per-connection ExecutionQueue (see _conn_queue)."""
        sock = self._sock(conn_id)
        if sock is None:
            return
        messenger = getattr(self._server, "_messenger", None)
        if messenger is None:
            self.engine.close_conn(conn_id)
            return
        if self._server.options.usercode_inline:
            sock.read_portal.append_user_data(memoryview(buf))
            self._pump_passthrough(conn_id, sock, messenger)
            return
        self._conn_queue(conn_id, sock).execute(("bytes", buf))

    def _pump_passthrough(self, conn_id: int, sock, messenger) -> None:
        try:
            messenger.process_buffered(sock)
        except Exception:
            LOG.exception("passthrough processing failed")
            sock.set_failed(Errno.EREQUEST, "passthrough dispatch error")
        if sock.failed:
            self.engine.close_conn(conn_id)

    def _on_ack(self, conn_id: int, buf, count: int) -> None:
        sock = self._sock(conn_id)
        if sock is None:
            return
        from ..ici.fabric import in_process_fabric
        fabric = in_process_fabric()
        ids = struct.unpack(f"<{count}Q", bytes(buf))
        for desc_id in ids:
            fabric.release(desc_id, only_socket=sock.id)

    def _on_stream(self, conn_id: int, buf) -> None:
        sock = self._sock(conn_id)
        if sock is None:
            return
        mv = memoryview(buf)
        flags = mv[0]
        (dest,) = struct.unpack_from("<Q", mv, 1)
        payload = bytes(mv[13:])
        from ..protocol.streaming import _dispatch as stream_dispatch
        stream_dispatch((flags, dest, payload), sock)
