"""Same-host shared-memory block ring — the zero-copy tensor data plane.

Role parity with the reference's RDMA data path (rdma/rdma_endpoint.cpp
+ rdma/block_pool.cpp): large attachments should ride *registered
memory* referenced by descriptor, not bytes squeezed through the
message path.  No RDMA NIC here, but the discipline ports to co-located
processes: each side owns a file-backed **ring** of fixed-size slots
(the "registered region"), advertises it once at connection handshake
(meta TLV capability exchange riding the first frame, like the ici
domain exchange), and from then on ships attachments ≥ a size threshold
as a 24-byte ``(ring_id, slot, offset, len)`` descriptor while the
payload bytes move through exactly ONE staging memcpy into shared
memory — against the 2×(user→kernel→user) copies of the TCP lane.

Design notes (fresh, not a port):

- **Named segment, not SCM_RIGHTS.**  The control frames ride the
  existing TCP/loopback connection, which cannot carry an fd; the ring
  is a named file under ``/dev/shm`` (tmpfs) the peer opens by path.
  This is the descriptor-passing limitation vs a UDS fd-pass design —
  it requires a shared filesystem view (same host / same mount ns) and
  filesystem permissions stand in for memory registration keys.  The
  spec carries the owner's hostname + boot nonce; attach refuses
  foreign-host specs, and a failed open simply declines the capability
  (the byte lane remains correct).
- **Ownership & credit**: the *sender* owns its ring.  Request slots
  are freed by the client when the response arrives (a sync unary
  response proves the server is done with the request attachment — the
  same invariant the ici credit-return relies on).  Response slots
  (server ring) are freed by a release TLV piggybacked on the client's
  next request on that connection, and reclaimed wholesale when the
  consuming connection closes — the RDMA-style "credit returns ride
  the connection".
- **Echo by reference**: a response attachment that still aliases a
  request's ring slot (echo-class handlers) is re-described instead of
  re-staged — zero data motion for the whole server half.
- **Byte-identical fallback**: every ineligible shape (peer without
  the capability, attachment under threshold, ring exhausted, slot too
  small, device-descriptor combo) takes the classic byte lane and
  increments exactly one NAMED counter — ``shm_fallback_counters()``
  has no "unknown" bucket (the round-8 fallback discipline).
"""

from __future__ import annotations

import mmap
import os
import socket as _socket_mod
import struct
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ..butil.flags import define_flag, get_flag
from ..butil.logging_util import LOG

define_flag("rpc_shm_data_plane", True,
            "pass same-host attachments >= rpc_shm_threshold by "
            "shared-memory descriptor instead of bytes",
            validator=lambda v: isinstance(v, bool))
define_flag("rpc_shm_threshold", 256 * 1024,
            "minimum attachment size (bytes) for the shm lane",
            validator=lambda v: isinstance(v, int) and v > 0)
define_flag("rpc_shm_slot_bytes", 2 * 1024 * 1024,
            "shm ring slot size (attachments above it fall back)",
            validator=lambda v: isinstance(v, int) and v >= 4096)
define_flag("rpc_shm_slots", 16, "slots per shm ring",
            validator=lambda v: isinstance(v, int) and 0 < v <= 4096)
define_flag("rpc_shm_shards", 0,
            "slot-allocator shards of the process tx ring (0 = auto: "
            "one per core up to 4).  Each engine loop binds to a home "
            "shard by thread id, so per-loop staging never contends on "
            "one allocator lock; empty shards steal from neighbours",
            validator=lambda v: isinstance(v, int) and 0 <= v <= 64)


def _auto_shards() -> int:
    return max(1, min(4, os.cpu_count() or 1))

_SPEC_MAGIC = b"SHMR"
_SPEC_VER = 1

# ---------------------------------------------------------------------------
# Named fallback counters (no "unknown" bucket — every branch that keeps
# an attachment OFF the shm lane increments exactly one of these).
# ---------------------------------------------------------------------------

FALLBACK_REASONS = (
    "shm_disabled",          # rpc_shm_data_plane flag off
    "shm_unavailable",       # no tmpfs/mmap support in this sandbox
    "shm_under_threshold",   # attachment below rpc_shm_threshold
    "shm_over_slot",         # attachment larger than a ring slot
    "shm_peer_no_cap",       # peer never accepted the capability TLV
    "shm_handshake",         # offer in flight; this call rides bytes
    "shm_ring_exhausted",    # all slots in use (sender backpressure)
    "shm_multi_attempt",     # backup/retry attempt while an earlier
    #                          attempt's descriptor may still be live
    "shm_attach_failed",     # peer ring could not be opened/mapped
    "shm_peer_remote",       # spec came from a different host
    "shm_device_combo",      # frame also carries an ici device tail
    "shm_compressed",        # compressed payload: bytes are the shape
)

_fb_lock = threading.Lock()
_fallbacks: Dict[str, int] = {r: 0 for r in FALLBACK_REASONS}


class ShmDescriptorError(Exception):
    """A peer named a shm descriptor this process cannot resolve — a
    protocol violation, not a fallback shape.  Surfaced as ERESPONSE by
    every client lane (the server-side mirror answers EREQUEST)."""


def count_fallback(reason: str) -> None:
    assert reason in _fallbacks, f"unnamed shm fallback {reason!r}"
    with _fb_lock:
        _fallbacks[reason] += 1


def shm_fallback_counters() -> Dict[str, int]:
    with _fb_lock:
        return dict(_fallbacks)


# stats the bench/tests read: staged copies are the ONE copy this lane
# admits to (client bytes -> ring slot); resolves are zero-copy views
_stats_lock = threading.Lock()
_stats = {"staged": 0, "staged_bytes": 0, "resolved": 0,
          "resolved_bytes": 0, "desc_reused": 0, "spilled": 0}


def _stat(key: str, n: int = 1, nbytes: int = 0) -> None:
    with _stats_lock:
        _stats[key] += n
        if nbytes:
            _stats[key + "_bytes"] = _stats.get(key + "_bytes", 0) + nbytes


def shm_stats() -> Dict[str, int]:
    with _stats_lock:
        return dict(_stats)


# ---------------------------------------------------------------------------
# Availability probe
# ---------------------------------------------------------------------------

_avail: Optional[bool] = None
_avail_lock = threading.Lock()


def _ring_dir() -> Optional[str]:
    for d in ("/dev/shm", os.environ.get("TMPDIR") or "/tmp"):
        if d and os.path.isdir(d) and os.access(d, os.W_OK):
            return d
    return None


def shm_supported() -> bool:
    """True when this sandbox can create + map a file-backed ring (the
    tier-1 skipif probe — gVisor images without tmpfs decline)."""
    global _avail
    with _avail_lock:
        if _avail is not None:
            return _avail
        try:
            d = _ring_dir()
            if d is None:
                _avail = False
                return False
            fd, path = _mkstemp(d)
            try:
                os.ftruncate(fd, mmap.PAGESIZE)
                mm = mmap.mmap(fd, mmap.PAGESIZE)
                mm[0:4] = b"ok!\n"
                mm.close()
            finally:
                os.close(fd)
                try:
                    os.unlink(path)
                except OSError:
                    pass
            _avail = True
        except (OSError, ValueError) as e:
            LOG.info("shm data plane unavailable: %s", e)
            _avail = False
        return _avail


def _mkstemp(d: str) -> Tuple[int, str]:
    import tempfile
    return tempfile.mkstemp(prefix="brpc_tpu_ring_", dir=d)


def _host_token() -> bytes:
    return _socket_mod.gethostname().encode()[:64]


# ---------------------------------------------------------------------------
# Descriptor / spec codecs
# ---------------------------------------------------------------------------

def encode_desc(ring_id: bytes, slot: int, offset: int, length: int) -> bytes:
    """(ring_id, slot, offset, len) -> 24-byte wire descriptor.
    ``offset`` is ring-absolute (slot base + intra-slot offset) so a
    re-described sub-slice (echo of a cut attachment) needs no slot
    arithmetic on the receiver."""
    return ring_id + struct.pack("<IQI", slot, offset, length)


def decode_desc(data: bytes) -> Optional[Tuple[bytes, int, int, int]]:
    if len(data) != 24:
        return None
    slot, offset, length = struct.unpack_from("<IQI", data, 8)
    return data[:8], slot, offset, length


def encode_release(ring_id: bytes, slots: List[int]) -> bytes:
    return ring_id + struct.pack("<H", len(slots)) \
        + b"".join(struct.pack("<I", s) for s in slots)


def decode_release(data: bytes) -> Optional[Tuple[bytes, List[int]]]:
    try:
        (n,) = struct.unpack_from("<H", data, 8)
        slots = [struct.unpack_from("<I", data, 10 + 4 * i)[0]
                 for i in range(n)]
        if len(data) != 10 + 4 * n:
            return None
        return data[:8], slots
    except struct.error:
        return None


# ---------------------------------------------------------------------------
# The ring
# ---------------------------------------------------------------------------

class ShmRing:
    """A file-backed slot ring this process OWNS (its tx data plane).

    Slots are fixed-size; ``alloc`` tags each slot with an owner key so
    a dying consumer connection can be swept (``free_owner``).  The
    backing file stays linked while the ring lives (peers attach by
    path) and is unlinked on close.
    """

    def __init__(self, slot_bytes: int, nslots: int, shards: int = 1):
        d = _ring_dir()
        if d is None:
            raise OSError("no writable tmpfs/tmp dir for shm ring")
        self.slot_bytes = slot_bytes
        self.nslots = nslots
        self.size = slot_bytes * nslots
        self.fd, self.path = _mkstemp(d)
        os.ftruncate(self.fd, self.size)
        self.mm = mmap.mmap(self.fd, self.size)
        self.ring_id = os.urandom(8)
        # SHARDED allocator (ISSUE 11): ONE mapping, ONE ring_id, ONE
        # wire spec — but the slot free-lists split into per-shard
        # pools, each under its own lock, and every allocating thread
        # (one engine loop per core in the sharded-accept world) binds
        # to a home shard by thread id.  Hot-path allocs never meet
        # another loop's lock; an empty home shard steals from
        # neighbours (correctness over affinity).  slot -> shard is
        # slot % nshards, so free()/gen_of() know their lock without
        # any registry.  Descriptors and the attach protocol are
        # UNCHANGED: sharding is allocator-internal.
        self.nshards = max(1, min(int(shards), nslots))
        self._locks = [threading.Lock() for _ in range(self.nshards)]
        self._free: List[List[int]] = [[] for _ in range(self.nshards)]
        for slot in range(nslots):
            self._free[slot % self.nshards].append(slot)
        self._owners: List[Dict[int, Any]] = \
            [{} for _ in range(self.nshards)]  # shard -> {slot: owner}
        self._steals = 0                       # cross-shard allocs
        self._tls = threading.local()          # per-thread home shard
        import itertools
        self._next_home = itertools.count()    # GIL-atomic rr counter
        # per-slot allocation generation: a free() that raced a
        # free_owner() sweep (dead socket) + re-alloc must not free the
        # NEW tenant's slot — stale settles carry the generation they
        # allocated under and are ignored on mismatch
        self._gen: List[int] = [0] * nslots
        self._closed = False
        self._closed_lock = threading.Lock()
        # pre-touch every page once: first-touch soft faults otherwise
        # land in the first requests' latency (measured 2.4x slower
        # staging on cold slots on this box)
        mv = memoryview(self.mm)
        step = mmap.PAGESIZE
        zero = b"\0"
        for off in range(0, self.size, step):
            mv[off:off + 1] = zero

    # -- slot lifecycle (sharded: see __init__) -----------------------------

    def _home_shard(self) -> int:
        # round-robin per-thread shard binding via a thread-local.
        # NOT hash(thread id): pthread idents are pointer-aligned
        # addresses whose low bits (and even their stride — 8MB stack
        # spacing) are constant, so any modulus collapses every thread
        # onto one shard
        idx = getattr(self._tls, "shard", None)
        if idx is None:
            idx = next(self._next_home) % self.nshards
            self._tls.shard = idx
        return idx

    def alloc(self, owner: Any = None) -> Optional[int]:
        home = self._home_shard()
        for i in range(self.nshards):
            sh = (home + i) % self.nshards
            with self._locks[sh]:
                if not self._free[sh]:
                    continue
                slot = self._free[sh].pop()
                self._owners[sh][slot] = owner
                self._gen[slot] += 1
                if i:
                    self._steals += 1   # racy += is fine (diagnostic)
                return slot
        return None

    def gen_of(self, slot: int) -> int:
        with self._locks[slot % self.nshards]:
            return self._gen[slot]

    def free(self, slot: int, gen: Optional[int] = None) -> None:
        """Return ``slot`` to the ring.  ``gen`` (from :meth:`gen_of` at
        alloc time) makes the free generation-checked: a stale settle —
        e.g. a timed-out call whose slot was already swept by
        ``free_owner`` and re-allocated to a live call — is a no-op
        instead of freeing the new tenant's slot."""
        sh = slot % self.nshards
        with self._locks[sh]:
            if slot in self._owners[sh] and (gen is None
                                             or self._gen[slot] == gen):
                del self._owners[sh][slot]
                self._free[sh].append(slot)

    def free_owner(self, owner: Any) -> int:
        """Reclaim every slot tagged with ``owner`` (consumer conn died
        before sending its release TLV).  Walks shard by shard — each
        under its OWN lock, so a loop sweeping a dead conn never stalls
        another loop's allocations (the per-loop sweep path)."""
        n = 0
        for sh in range(self.nshards):
            with self._locks[sh]:
                for slot, ow in list(self._owners[sh].items()):
                    if ow == owner:
                        del self._owners[sh][slot]
                        self._free[sh].append(slot)
                        n += 1
        return n

    def free_count(self) -> int:
        n = 0
        for sh in range(self.nshards):
            with self._locks[sh]:
                n += len(self._free[sh])
        return n

    def shard_stats(self) -> Dict[str, int]:
        """Allocator-shard diagnostics: shard count, per-shard free
        slots, cross-shard steals (high steals = imbalanced staging)."""
        out: Dict[str, int] = {"shards": self.nshards,
                               "steals": self._steals}
        for sh in range(self.nshards):
            with self._locks[sh]:
                out[f"shard_{sh}_free"] = len(self._free[sh])
        return out

    # -- data ---------------------------------------------------------------

    def write(self, slot: int, data) -> Tuple[int, int]:
        """Stage ``data`` into ``slot`` (the lane's ONE copy).  Accepts
        bytes-likes or an IOBuf (chained blocks gather straight into the
        slot — no intermediate join).  Returns (ring_offset, length) for
        the descriptor."""
        n = len(data)
        base = slot * self.slot_bytes
        mv = memoryview(self.mm)
        views = data.backing_views() if hasattr(data, "backing_views") \
            else (data,)
        pos = base
        for v in views:
            mv[pos:pos + len(v)] = v
            pos += len(v)
        _stat("staged", 1, n)
        from ..butil import copy_audit as _audit
        if _audit.enabled and n >= _audit.AUDIT_FLOOR:
            _audit.record("stage_shm", n)
        return base, n

    def view(self, offset: int, length: int) -> Optional[memoryview]:
        if offset + length > self.size or length < 0:
            return None
        return memoryview(self.mm)[offset:offset + length]

    def slot_of(self, offset: int) -> int:
        return offset // self.slot_bytes

    def spec(self) -> bytes:
        """Capability-TLV payload advertising this ring."""
        host = _host_token()
        path = self.path.encode()
        return (_SPEC_MAGIC + bytes([_SPEC_VER]) + self.ring_id
                + struct.pack("<IIH", self.slot_bytes, self.nslots,
                              len(host))
                + host + struct.pack("<H", len(path)) + path)

    def sendfile_spill(self, sock_fd: int, offset: int, length: int,
                       headers: bytes = b"") -> int:
        """Ship a staged slot over TCP with ``os.sendfile`` — the spill
        path when a staged block must ride the byte lane after all (the
        fallback TCP path never re-reads the mmap through userspace).
        Blocking-socket helper; returns bytes sent (== length)."""
        if headers:
            sent = 0
            while sent < len(headers):
                sent += os.write(sock_fd, headers[sent:])
        done = 0
        while done < length:
            n = os.sendfile(sock_fd, self.fd, offset + done, length - done)
            if n == 0:
                raise ConnectionError("sendfile: peer closed")
            done += n
        _stat("spilled", 1, length)
        return done

    def close(self) -> None:
        with self._closed_lock:
            if self._closed:
                return
            self._closed = True
        try:
            self.mm.close()
        except (BufferError, ValueError):
            pass            # exported views still alive; mapping leaks
        try:                # until process exit, file still unlinks
            os.close(self.fd)
        except OSError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


def decode_spec(data: bytes):
    """spec bytes -> (ring_id, slot_bytes, nslots, host, path) or None."""
    try:
        if data[:4] != _SPEC_MAGIC or data[4] != _SPEC_VER:
            return None
        ring_id = bytes(data[5:13])
        slot_bytes, nslots, hlen = struct.unpack_from("<IIH", data, 13)
        off = 23
        host = bytes(data[off:off + hlen])
        off += hlen
        (plen,) = struct.unpack_from("<H", data, off)
        off += 2
        path = bytes(data[off:off + plen]).decode()
        if len(data) != off + plen:
            return None
        return ring_id, slot_bytes, nslots, host, path
    except (struct.error, IndexError, UnicodeDecodeError):
        return None


class AttachedRing:
    """A read-only mapping of a PEER's ring (resolve descriptors into
    zero-copy views)."""

    def __init__(self, ring_id: bytes, path: str, size: int):
        self.ring_id = ring_id
        self.path = path
        # fd kept open: sendfile spills (a resolved slot forwarded onto
        # a TCP byte lane) read straight from it
        self.fd = os.open(path, os.O_RDONLY)
        try:
            self.mm = mmap.mmap(self.fd, size, prot=mmap.PROT_READ)
        except BaseException:
            os.close(self.fd)
            raise
        self.size = size

    def view(self, offset: int, length: int) -> Optional[memoryview]:
        if offset + length > self.size or length < 0:
            return None
        return memoryview(self.mm)[offset:offset + length]

    def close(self) -> None:
        try:
            self.mm.close()
        except (BufferError, ValueError):
            pass
        try:
            os.close(self.fd)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Process-wide registries
# ---------------------------------------------------------------------------

_reg_lock = threading.Lock()
_tx_ring: Optional[ShmRing] = None
_tx_failed = False
_attached: Dict[bytes, Optional[AttachedRing]] = {}   # None = attach failed


def process_tx_ring() -> Optional[ShmRing]:
    """This process's send-side ring, created lazily (None when shm is
    unsupported here)."""
    global _tx_ring, _tx_failed
    with _reg_lock:
        if _tx_ring is not None or _tx_failed:
            return _tx_ring
        if not shm_supported():
            _tx_failed = True
            return None
        try:
            shards = int(get_flag("rpc_shm_shards")) or _auto_shards()
            _tx_ring = ShmRing(int(get_flag("rpc_shm_slot_bytes")),
                               int(get_flag("rpc_shm_slots")),
                               shards=shards)
        except (OSError, ValueError) as e:
            LOG.warning("shm tx ring creation failed: %s", e)
            _tx_failed = True
            return None
        import atexit
        atexit.register(_tx_ring.close)
        return _tx_ring


def attach_spec(spec: bytes) -> Optional[bytes]:
    """Map a peer's advertised ring.  Returns its ring_id on success,
    None on decline (counted with a named reason)."""
    parsed = decode_spec(spec)
    if parsed is None:
        count_fallback("shm_attach_failed")
        return None
    ring_id, slot_bytes, nslots, host, path = parsed
    with _reg_lock:
        if ring_id in _attached:
            return ring_id if _attached[ring_id] is not None else None
        local = _tx_ring
    if local is not None and ring_id == local.ring_id:
        return ring_id                     # our own ring (same process)
    if host != _host_token():
        count_fallback("shm_peer_remote")
        with _reg_lock:
            _attached[ring_id] = None
        return None
    try:
        att = AttachedRing(ring_id, path, slot_bytes * nslots)
    except (OSError, ValueError) as e:
        # transient failure (EMFILE, momentary unlink race): decline
        # this offer but do NOT cache the decline — a later handshake
        # retries once the condition clears.  (Foreign-host specs above
        # ARE cached: that decline is deterministic.)
        LOG.info("shm attach of %s failed: %s", path, e)
        count_fallback("shm_attach_failed")
        return None
    with _reg_lock:
        # the open/mmap above ran unlocked: a concurrent offer for the
        # same ring may have won — keep the published mapping and close
        # ours (the loser's fd+mmap must not leak for process lifetime)
        prior = _attached.get(ring_id)
        if prior is None:
            _attached[ring_id] = att
            att = None
    if att is not None:
        att.close()
    return ring_id


def resolve(ring_id: bytes, offset: int, length: int
            ) -> Optional[memoryview]:
    """Descriptor -> zero-copy view (local tx ring or an attached peer
    ring).  None when the ring is unknown or the span is out of
    bounds."""
    r = resolve_ex(ring_id, offset, length)
    return r[0] if r is not None else None


def resolve_ex(ring_id: bytes, offset: int, length: int):
    """Like :func:`resolve` but returns ``(view, file_ref)`` where
    ``file_ref = (fd, abs_offset)`` lets an IOBuf spill the span via
    sendfile if it ever rides a TCP byte lane."""
    with _reg_lock:
        local = _tx_ring
        att = _attached.get(ring_id)
    v = fd = None
    if local is not None and ring_id == local.ring_id:
        v = local.view(offset, length)
        fd = local.fd
    elif att is not None:
        v = att.view(offset, length)
        fd = att.fd
    if v is None:
        return None
    _stat("resolved", 1, length)
    return v, (fd, offset)


def local_ring_for(ring_id: bytes) -> Optional[ShmRing]:
    with _reg_lock:
        local = _tx_ring
    if local is not None and ring_id == local.ring_id:
        return local
    return None


def on_socket_closed(owner: Any) -> None:
    """Sweep tx-ring slots consumed by a dead connection (its release
    TLVs will never arrive)."""
    with _reg_lock:
        ring = _tx_ring
    if ring is not None:
        ring.free_owner(owner)


def outstanding_tx_slots() -> int:
    """Slots of this process's tx ring currently staged or leased —
    the drain plane's "every descriptor on the wire has settled"
    gauge (0 when the lane never engaged)."""
    with _reg_lock:
        ring = _tx_ring
    if ring is None or ring._closed:
        return 0
    return ring.nslots - ring.free_count()


def drain_settle(deadline_mono_s: float) -> int:
    """Operability plane: wait — bounded by the caller's drain-grace
    deadline (``time.monotonic()`` seconds) — for every outstanding
    tx-ring slot to settle (peers return credits when they drop their
    response views; dead-conn sweeps run from the transport close
    path).  Returns the slots still outstanding at the deadline (0 =
    fully settled; the process may exit without stranding a peer's
    mapped descriptor)."""
    import time as _time
    ev = threading.Event()
    while True:
        n = outstanding_tx_slots()
        if n == 0:
            return 0
        if _time.monotonic() >= deadline_mono_s:
            return n
        ev.wait(0.005)     # timed: the drain path stays deadline-bound


def _reset_for_tests() -> None:
    """Drop process-wide state (tests re-negotiate from scratch)."""
    global _tx_ring, _tx_failed
    with _reg_lock:
        ring, _tx_ring, _tx_failed = _tx_ring, None, False
        _attached.clear()
    if ring is not None:
        ring.close()
    with _fb_lock:
        for k in _fallbacks:
            _fallbacks[k] = 0
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0


# ---------------------------------------------------------------------------
# Per-socket negotiation state + lane helpers (shared by the raw lane,
# the Controller lane, and both server dispatch paths — ONE protocol
# implementation, four call sites).
# ---------------------------------------------------------------------------

# eligible calls to let pass (each falling back under shm_handshake)
# before a still-unanswered offer is re-sent: the offer-carrying call
# may have died a transport death that proved nothing about the peer's
# capability, and a one-shot offer would disable the lane for the
# connection's whole life
_REOFFER_AFTER = 8


class ShmSockState:
    """Negotiation + credit state hanging off a Socket (both ends)."""

    __slots__ = ("offered", "tx_ok", "peer_refused", "peer_ring_id",
                 "peer_ring_acked", "pending_release", "resp_desc_ok",
                 "offer_waits", "deferred_settles", "lock")

    def __init__(self):
        self.offered = False          # we advertised our tx ring
        self.tx_ok = False            # peer confirmed mapping our ring
        self.peer_refused = False     # peer answered without accepting
        self.peer_ring_id = None      # peer's tx ring we mapped (reader)
        self.peer_ring_acked = False  # we told the peer we mapped it
        self.pending_release = []     # [(ring_id, slot)] to piggyback
        self.resp_desc_ok = False     # (server) peer mapped OUR ring
        self.offer_waits = 0          # eligible calls since the offer
        # settle actions deferred to the next request on this socket
        # (raw pinned lane: one thread per socket, so "next request"
        # can only come from the thread that holds the view)
        self.deferred_settles = []
        self.lock = threading.Lock()


def sock_state(sock) -> ShmSockState:
    st = getattr(sock, "shm", None)
    if st is None:
        st = ShmSockState()
        sock.shm = st
    return st


def lane_enabled() -> bool:
    return bool(get_flag("rpc_shm_data_plane")) and shm_supported()


def take_release_tlvs(st: ShmSockState) -> bytes:
    """Drain pending slot releases into TLV-20 payloads (grouped by
    ring id), plus the one-shot peer-ring mapping ack (TLV 19 with an
    empty spec).  Returns pre-encoded meta TLV bytes."""
    from ..protocol.meta import (TAG_SHM_ACCEPT, TAG_SHM_RELEASE,
                                 encode_tlv)
    out = b""
    with st.lock:
        pending, st.pending_release = st.pending_release, []
        ack_ring = None
        if st.peer_ring_id is not None and not st.peer_ring_acked:
            ack_ring = st.peer_ring_id
            st.peer_ring_acked = True
    if ack_ring is not None:
        out += encode_tlv(TAG_SHM_ACCEPT, ack_ring)
    if pending:
        by_ring: Dict[bytes, List[int]] = {}
        for rid, slot in pending:
            by_ring.setdefault(rid, []).append(slot)
        for rid, slots in by_ring.items():
            out += encode_tlv(TAG_SHM_RELEASE, encode_release(rid, slots))
    return out


def client_prepare(sock, att, device: bool = False,
                   multi_attempt: bool = False):
    """Client half, request side.  ``att`` is bytes-like, an IOBuf, or
    None; ``device`` flags a frame that also carries an ici device tail
    (the descriptor split relies on the byte tail riding the frame);
    ``multi_attempt`` flags a backup/retry attempt issued while an
    earlier attempt may still be in flight — such attempts stay on the
    byte lane (an early slot settle could recycle a slot an unread
    descriptor still points at).

    Returns ``(extra_meta_tlvs, wire_att, staged_slot, offered_now)``:
    ``wire_att`` is what must still ride the byte lane (None when the
    attachment went to shm), ``staged_slot`` is an opaque slot lease
    that must be settled via ``client_complete`` when the call ends,
    and ``offered_now`` flags that THIS frame carries the capability
    offer (its response decides accept vs refuse)."""
    from ..protocol.meta import TAG_SHM_DESC, TAG_SHM_OFFER, encode_tlv
    st = sock_state(sock)
    with st.lock:
        settles, st.deferred_settles = st.deferred_settles, []
    for s in settles:
        s()         # may queue pending_release entries: run BEFORE the
    extra = take_release_tlvs(st)      # TLV drain so they ride this frame
    na = len(att) if att is not None else 0
    if na == 0:
        return extra, att, None, False
    if not bool(get_flag("rpc_shm_data_plane")):
        count_fallback("shm_disabled")
        return extra, att, None, False
    if na < int(get_flag("rpc_shm_threshold")):
        count_fallback("shm_under_threshold")
        return extra, att, None, False
    if device:
        count_fallback("shm_device_combo")
        return extra, att, None, False
    if multi_attempt:
        count_fallback("shm_multi_attempt")
        return extra, att, None, False
    ring = process_tx_ring()
    if ring is None:
        count_fallback("shm_unavailable")
        return extra, att, None, False
    if na > ring.slot_bytes:
        count_fallback("shm_over_slot")
        return extra, att, None, False
    with st.lock:
        offered, tx_ok, refused = st.offered, st.tx_ok, st.peer_refused
        if not offered:
            st.offered = True
    if refused:
        count_fallback("shm_peer_no_cap")
        return extra, att, None, False
    if not offered:
        # capability exchange rides this frame; the attachment itself
        # stays on the byte lane until the peer confirms the mapping
        count_fallback("shm_handshake")
        return (extra + encode_tlv(TAG_SHM_OFFER, ring.spec()), att,
                None, True)
    if not tx_ok:
        # offer out, no accept yet.  An offer-carrying call that died a
        # transport death proved nothing about the peer — after enough
        # eligible calls pass unanswered, re-send the offer (the server
        # handles repeated offers idempotently; a capability-less peer
        # answers plainly and flips peer_refused for good)
        with st.lock:
            st.offer_waits += 1
            if st.offer_waits >= _REOFFER_AFTER and not st.peer_refused:
                st.offered = False
                st.offer_waits = 0
        count_fallback("shm_handshake")
        return extra, att, None, False
    slot = ring.alloc(owner=("req", getattr(sock, "id", 0)))
    if slot is None:
        count_fallback("shm_ring_exhausted")
        return extra, att, None, False
    off, n = ring.write(slot, att)
    desc = encode_desc(ring.ring_id, slot, off, n)
    return (extra + encode_tlv(TAG_SHM_DESC, desc), None,
            (slot, ring.gen_of(slot)), False)


def stage_page(data, owner: Any = None):
    """KV transfer plane: stage one page-sized blob into the process tx
    ring and return ``(desc_bytes, lease)`` — the 24-byte descriptor
    the handoff manifest carries plus the generation-checked slot lease
    to settle via :func:`client_complete` once the handoff RPC has an
    outcome (the sync response proves the importer is done reading).
    Returns None when the ring is unavailable or exhausted; callers
    screen page-vs-slot sizing themselves (their fallback reasons are
    theirs to name).  This is the shm lane's ONE staging memcpy."""
    ring = process_tx_ring()
    if ring is None or len(data) > ring.slot_bytes:
        return None
    slot = ring.alloc(owner=owner)
    if slot is None:
        return None
    off, n = ring.write(slot, data)
    return (encode_desc(ring.ring_id, slot, off, n),
            (slot, ring.gen_of(slot)))


def client_complete(staged_slot) -> None:
    """Settle the request slot lease once the call has an outcome (the
    sync response — or failure — proves the server is done reading
    it).  The free is generation-checked: a lease already swept by
    ``on_socket_closed`` and re-allocated is left alone."""
    if staged_slot is None:
        return
    ring = process_tx_ring()
    if ring is not None:
        slot, gen = staged_slot
        ring.free(slot, gen)


def _peer_release_settle(st: ShmSockState, rid: bytes, slot: int):
    """Settle action for a view into a PEER's ring: owe it a release
    TLV, piggybacked on the next request over the connection (or
    reclaimed by the peer's owner-sweep when the connection dies)."""
    def settle():
        with st.lock:
            st.pending_release.append((rid, slot))
    return settle


def _local_free_settle(ring: ShmRing, slot: int, gen: int):
    """Settle action for a view into our OWN ring (echo re-describe /
    same-process loopback): generation-checked direct free."""
    def settle():
        ring.free(slot, gen)
    return settle


def client_on_response_meta(sock, meta, offered_now: bool = False,
                            staged_slot=None, retired=None):
    """Client half, response side: learn accepts, resolve a response
    descriptor, and settle the request's staged slot lease
    (``staged_slot``) — the sync response proves the server is done
    with it, EXCEPT when the response re-describes that very slot (echo
    by reference): then the returned view still aliases it and its free
    is bound to the view's lifetime.  Callers must treat the lease as
    consumed after this returns (do not also call
    :func:`client_complete`).

    Returns ``(view, settle)``: ``view`` is the response attachment as
    a zero-copy view (None = it rides bytes) and ``settle`` is the
    slot-recycling action for that view — callers hand BOTH to
    :func:`wrap_view_iobuf` so the slot is recycled only when the
    wrapping buffer is dropped, never while a concurrent caller on the
    same connection is already issuing the next request.

    ``offered_now``: this response answers the offer-carrying request —
    a SUCCESS answer without an accept means the peer has no shm
    capability (callers pass False for error responses: they prove
    nothing).

    ``retired``: leases of EARLIER attempts of this call (backup/retry
    restages) the caller still plans to settle at call end.  A
    descriptor naming one of them (the earlier attempt's response won
    and echo-re-described its slot) transfers that lease's ownership to
    the returned view's settle — it is REMOVED from the list so the
    caller's call-end sweep cannot free a slot the response attachment
    still aliases.
    """
    st = sock_state(sock)
    if meta.shm_offer:
        # server advertised its tx ring (rides the accept response)
        rid = attach_spec(meta.shm_offer)
        with st.lock:
            st.peer_ring_id = rid
    if meta.shm_accept:
        ring = process_tx_ring()
        if ring is not None and meta.shm_accept == ring.ring_id:
            with st.lock:
                st.tx_ok = True
                st.offer_waits = 0
    elif offered_now:
        client_saw_plain_response(sock)
    view = None
    settle = None
    desc_local_slot = None
    if meta.shm_desc:
        d = decode_desc(meta.shm_desc)
        if d is not None:
            rid, slot, off, ln = d
            view = resolve(rid, off, ln)
            if view is not None:
                local = local_ring_for(rid)
                if local is None:
                    # a slot of the PEER's ring: release when the view's
                    # wrapping buffer dies
                    settle = _peer_release_settle(st, rid, slot)
                else:
                    desc_local_slot = slot
                    settle = _local_free_settle(local, slot,
                                                local.gen_of(slot))
            else:
                # delivering "success" with a silently empty attachment
                # would crash user code far from the cause — fail the
                # call loudly (mirrors the server's EREQUEST answer for
                # an unresolvable request descriptor)
                LOG.warning("unresolvable shm response descriptor")
                if staged_slot is not None:
                    client_complete(staged_slot)
                raise ShmDescriptorError(
                    "unresolvable shm response descriptor")
        else:
            if staged_slot is not None:
                client_complete(staged_slot)
            raise ShmDescriptorError("malformed shm response descriptor")
    if staged_slot is not None:
        if desc_local_slot == staged_slot[0]:
            # echo by reference: the view aliases our own request slot;
            # the settle above already frees it (generation-checked)
            # when the view's wrapping buffer dies
            pass
        else:
            client_complete(staged_slot)
    if retired and desc_local_slot is not None:
        # a backup/retry flow retired this lease, but the WINNING
        # response re-describes its slot: the view's settle owns the
        # free now — drop it from the caller's call-end sweep
        for lease in list(retired):
            if lease[0] == desc_local_slot:
                retired.remove(lease)
    return view, settle


def defer_settle(sock, settle) -> None:
    """Raw-lane deferral: run ``settle`` when the NEXT request is
    prepared on this socket.  Correct only on thread-pinned sockets
    (the raw pinned lane): there the next request can only be issued by
    the same thread that received — and documents consuming — the
    view, so the slot cannot recycle under a live reader."""
    if settle is None:
        return
    st = sock_state(sock)
    with st.lock:
        st.deferred_settles.append(settle)


def wrap_view_iobuf(view: memoryview, settle, file_ref=None):
    """Wrap a resolved response view into an IOBuf whose backing block
    carries ``settle`` as a finalizer: the ring slot is recycled when
    the buffer (and thus the user's response attachment) is dropped —
    not when the next request happens to go out on the connection.
    Zero-copy consumers that extract raw views (``backing_views()`` /
    ``as_contiguous``) must not let them outlive the IOBuf."""
    from ..butil.iobuf import IOBuf
    buf = IOBuf()
    buf.append_user_data(view, file_ref=file_ref)
    if settle is not None:
        blk = buf._refs[-1][0]
        weakref.finalize(blk, settle)
    return buf


def client_saw_plain_response(sock) -> None:
    """An offer went out but the response carried no accept: the peer
    has no shm capability — stop offering on this socket."""
    st = sock_state(sock)
    with st.lock:
        if st.offered and not st.tx_ok:
            st.peer_refused = True


# -- server half ------------------------------------------------------------

class _DescHandle:
    """Keeps (ring_id, offset_base, length, view, file_ref) of a
    resolved request descriptor so the response path can re-describe
    aliases of it and byte-lane spills can ride sendfile."""

    __slots__ = ("ring_id", "slot", "offset", "length", "view",
                 "file_ref", "__weakref__")

    def __init__(self, ring_id, slot, offset, length, view,
                 file_ref=None):
        self.ring_id = ring_id
        self.slot = slot
        self.offset = offset
        self.length = length
        self.view = view
        self.file_ref = file_ref


def server_on_request_meta(sock, meta):
    """Server half, request side: process offer/accept/release TLVs and
    resolve a request descriptor.

    Returns ``(att_view_or_None, desc_handle_or_None, accept_tlvs)``:
    ``att_view`` is the request attachment as a zero-copy view into the
    client's ring; ``desc_handle`` lets the response path re-describe an
    aliasing response attachment (and carries ``file_ref`` so the view
    can spill via sendfile if it ever rides a TCP byte lane);
    ``accept_tlvs`` are pre-encoded meta TLVs the response MUST carry
    (capability accept + our own spec)."""
    from ..protocol.meta import TAG_SHM_ACCEPT, TAG_SHM_OFFER, encode_tlv
    st = sock_state(sock)
    accept = b""
    if meta.shm_offer and lane_enabled():
        rid = attach_spec(meta.shm_offer)
        if rid is not None:
            with st.lock:
                st.peer_ring_id = rid
            # confirm the mapping AND advertise our own tx ring for
            # response descriptors (one round trip, both directions).
            # An offer arriving on an already-offered socket is the
            # client re-offering (its accept frame was lost): answer
            # with BOTH TLVs again — attach_spec is idempotent
            accept = encode_tlv(TAG_SHM_ACCEPT, rid)
            ring = process_tx_ring()
            with st.lock:
                st.offered = True
            if ring is not None:
                accept += encode_tlv(TAG_SHM_OFFER, ring.spec())
    if meta.shm_accept:
        # the client confirmed mapping OUR ring (rides its 2nd request)
        ring = process_tx_ring()
        if ring is not None and meta.shm_accept == ring.ring_id:
            with st.lock:
                st.resp_desc_ok = True
    if meta.shm_release:
        rel = decode_release(meta.shm_release)
        if rel is not None:
            ring = local_ring_for(rel[0])
            if ring is not None:
                for slot in rel[1]:
                    ring.free(slot)
    handle = None
    view = None
    if meta.shm_desc:
        d = decode_desc(meta.shm_desc)
        if d is not None:
            r = resolve_ex(d[0], d[2], d[3])
            if r is not None:
                view, file_ref = r
                handle = _DescHandle(d[0], d[1], d[2], d[3], view,
                                     file_ref)
    return view, handle, accept


def describe_response_att(sock, att_iobuf, req_handle):
    """Server half, response side.  Try to move the response attachment
    to the shm lane.  Returns ``(desc_tlv, wire_att_iobuf)`` — when
    ``desc_tlv`` is non-empty the attachment rides shm and
    ``wire_att_iobuf`` is empty.

    Order of preference: (1) re-describe an attachment that still
    aliases the request's ring slot (echo — zero data motion), (2)
    stage into our own tx ring when the client confirmed mapping it,
    (3) byte lane with a named fallback reason."""
    from ..protocol.meta import TAG_SHM_DESC, encode_tlv
    n = len(att_iobuf) if att_iobuf is not None else 0
    if n == 0:
        return b"", att_iobuf
    if not bool(get_flag("rpc_shm_data_plane")):
        if n >= int(get_flag("rpc_shm_threshold")):
            count_fallback("shm_disabled")
        return b"", att_iobuf
    # (1) echo by reference: every backing view aliases the request
    # slot's resolved view -> re-describe (sub-slices included)
    if req_handle is not None and n <= req_handle.length:
        base = req_handle.view
        refs = getattr(att_iobuf, "_refs", None)
        if refs is not None and len(refs) == 1:
            blk, off, ln = refs[0]
            if blk.data is base:
                # still backed by the request's ring slot block —
                # echo-class handlers, including IOBuf-LEVEL sub-slices
                # (cutn/append_iobuf share the Block with an offset).
                # A handler-made memoryview slice (att[1:]) wraps a NEW
                # buffer object and re-stages instead — identity is the
                # only safe alias proof here.  Re-describe: zero data
                # motion for the whole server half
                abs_off = req_handle.offset + off
                desc = encode_desc(req_handle.ring_id,
                                   req_handle.slot, abs_off, ln)
                _stat("desc_reused")
                return encode_tlv(TAG_SHM_DESC, desc), None
    if n < int(get_flag("rpc_shm_threshold")):
        count_fallback("shm_under_threshold")
        return b"", att_iobuf
    st = sock_state(sock)
    with st.lock:
        ok = st.resp_desc_ok
    if not ok:
        count_fallback("shm_peer_no_cap")
        return b"", att_iobuf
    ring = process_tx_ring()
    if ring is None:
        count_fallback("shm_unavailable")
        return b"", att_iobuf
    if n > ring.slot_bytes:
        count_fallback("shm_over_slot")
        return b"", att_iobuf
    slot = ring.alloc(owner=("resp", getattr(sock, "id", 0)))
    if slot is None:
        count_fallback("shm_ring_exhausted")
        return b"", att_iobuf
    base, n = ring.write(slot, att_iobuf)
    desc = encode_desc(ring.ring_id, slot, base, n)
    return encode_tlv(TAG_SHM_DESC, desc), None
