"""TransformerLM — the long-context flagship model family.

A decoder-only transformer built TPU-first:

- **bfloat16 matmuls on the MXU**: weights/activations cast to bf16 at
  the matmul boundary, accumulation in fp32;
- **sequence parallelism**: attention runs through
  :mod:`brpc_tpu.parallel.ring_attention` when a mesh axis is given —
  KV blocks rotate around the ring (ICI), so context length scales with
  the number of chips;
- **tensor parallelism**: MLP + attention projections shard on a ``tp``
  axis via ``NamedSharding`` specs (XLA inserts the collectives);
- **rematerialisation**: blocks are wrapped in ``jax.checkpoint`` to
  trade FLOPs for HBM on long sequences;
- static shapes; layers unrolled by default (tiny configs compile per
  depth), or ``scan_layers=True`` stacks the per-layer weights and runs
  one ``lax.scan`` over depth — compile time O(1) in depth for deep
  models.

The capability analogue in the reference is its flagship *service*
workloads (echo/PS); a TPU framework's flagship is a model — this plus
EmbeddingPS cover the dense-compute and sparse-lookup families.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple


class LMConfig:
    def __init__(self, vocab: int = 256, dim: int = 64, heads: int = 4,
                 depth: int = 2, mlp_mult: int = 4, max_seq: int = 256,
                 causal: bool = True, remat: bool = True,
                 lr: float = 0.05, moe_experts: int = 0,
                 moe_capacity: float = 2.0, moe_aux_weight: float = 0.01,
                 moe_top_k: int = 1, use_flash: bool = False,
                 scan_layers: bool = False, attn_impl: str = "auto"):
        assert dim % heads == 0
        assert (dim // heads) % 2 == 0, "head dim must be even for RoPE"
        self.vocab = vocab
        self.dim = dim
        self.heads = heads
        self.depth = depth
        self.mlp_mult = mlp_mult
        self.max_seq = max_seq
        self.causal = causal
        self.remat = remat
        self.lr = lr
        # moe_experts > 0 swaps the dense MLP for a Mixture-of-Experts
        # FFN (models/moe.py): sparse compute, experts shardable over
        # the tp axis (expert parallelism)
        self.moe_experts = moe_experts
        self.moe_capacity = moe_capacity
        self.moe_aux_weight = moe_aux_weight
        self.moe_top_k = moe_top_k
        # single-device attention: "auto" picks dense (XLA-fused) vs
        # the Pallas flash kernel by sequence length
        # (ops/flash_attention.py attention()); use_flash=True forces
        # the kernel (back-compat); the sp path keeps ring attention
        self.use_flash = use_flash
        self.attn_impl = attn_impl
        # scan_layers stacks per-layer weights and runs one lax.scan
        # over the depth axis: trace/compile time is O(1) in depth
        # instead of O(depth) — the XLA-idiomatic deep-model form
        self.scan_layers = scan_layers

    def moe_cfg(self):
        from .moe import MoEConfig
        return MoEConfig(dim=self.dim, hidden=self.dim * self.mlp_mult,
                         num_experts=self.moe_experts,
                         capacity_factor=self.moe_capacity,
                         aux_loss_weight=self.moe_aux_weight,
                         top_k=self.moe_top_k)


def init_params(rng, cfg: LMConfig) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    ks = jax.random.split(rng, 2 + cfg.depth)
    scale = 1.0 / math.sqrt(cfg.dim)
    params: Dict[str, Any] = {
        "embed": jax.random.normal(ks[0], (cfg.vocab, cfg.dim),
                                   jnp.float32) * scale,
        "unembed": jax.random.normal(ks[1], (cfg.dim, cfg.vocab),
                                     jnp.float32) * scale,
    }
    for i in range(cfg.depth):
        bk = jax.random.split(ks[2 + i], 6)
        h = cfg.dim * cfg.mlp_mult
        blk = {
            "wqkv": jax.random.normal(bk[0], (cfg.dim, 3 * cfg.dim),
                                      jnp.float32) * scale,
            "wo": jax.random.normal(bk[1], (cfg.dim, cfg.dim),
                                    jnp.float32) * scale,
            "ln1": jnp.ones((cfg.dim,), jnp.float32),
            "ln2": jnp.ones((cfg.dim,), jnp.float32),
        }
        if cfg.moe_experts > 0:
            from .moe import init_params as moe_init
            blk["moe"] = moe_init(bk[2], cfg.moe_cfg())
        else:
            blk["w1"] = jax.random.normal(bk[2], (cfg.dim, h),
                                          jnp.float32) * scale
            blk["w2"] = jax.random.normal(
                bk[3], (h, cfg.dim), jnp.float32) * (scale / cfg.mlp_mult)
        params[f"blk{i}"] = blk
    if cfg.scan_layers:
        # stack per-layer trees along a leading depth axis for lax.scan
        blks = [params.pop(f"blk{i}") for i in range(cfg.depth)]
        params["blocks"] = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *blks)
    return params


def _rmsnorm(x, g):
    import jax.numpy as jnp
    return x * g / jnp.sqrt(jnp.mean(x * x, axis=-1, keepdims=True) + 1e-6)


def _rope_tables(seq: int, head_dim: int):
    """sin/cos tables for rotary embedding, shaped (1, s, 1, d/2).
    Built once per forward and passed into every block so remat regions
    cover only the matmuls, not the table computation."""
    import jax.numpy as jnp
    half = head_dim // 2
    pos = jnp.arange(seq, dtype=jnp.float32)[None, :, None, None]
    freq = jnp.exp(-math.log(10000.0)
                   * jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos * freq[None, None, None, :]
    return jnp.sin(ang), jnp.cos(ang)


def _rope(x, sin, cos):
    """Rotary position embedding — static shapes, fused by XLA."""
    import jax.numpy as jnp
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1)


def make_forward(cfg: LMConfig, mesh=None, sp_axis: Optional[str] = None):
    """Forward fn: (params, ids[b, s]) -> logits[b, s, vocab].
    With ``mesh`` + ``sp_axis``, attention is ring attention over the
    mesh axis (sequence-parallel long context)."""
    import jax
    import jax.numpy as jnp

    if mesh is not None and sp_axis is not None:
        from ..parallel.ring_attention import make_ring_attention
        attend = make_ring_attention(mesh, sp_axis, causal=cfg.causal)
    else:
        from ..ops.flash_attention import attention
        impl = "flash" if cfg.use_flash else cfg.attn_impl

        def attend(q, k, v):
            # seq-adaptive: XLA-fused dense below the crossover, the
            # Pallas flash kernel above (each where it measures faster)
            return attention(q, k, v, causal=cfg.causal, impl=impl)

    if cfg.moe_experts > 0:
        from .moe import forward_grouped as moe_forward
        moe_cfg = cfg.moe_cfg()

    def block(bp, x, sin, cos):
        b, s, _ = x.shape
        h = _rmsnorm(x, bp["ln1"])
        qkv = (h.astype(jnp.bfloat16) @ bp["wqkv"].astype(jnp.bfloat16)
               ).astype(jnp.float32)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (b, s, cfg.heads, cfg.dim // cfg.heads)
        q, k = (_rope(t.reshape(shp), sin, cos) for t in (q, k))
        v = v.reshape(shp)
        att = attend(q, k, v).reshape(b, s, cfg.dim)
        x = x + (att.astype(jnp.bfloat16) @ bp["wo"].astype(jnp.bfloat16)
                 ).astype(jnp.float32)
        h = _rmsnorm(x, bp["ln2"])
        if cfg.moe_experts > 0:
            # grouped routing: each batch row routes independently, so
            # dispatch stays linear in tokens and dp-local (moe.py)
            out, aux = moe_forward(bp["moe"], h, moe_cfg)
            return x + out, aux
        up = (h.astype(jnp.bfloat16) @ bp["w1"].astype(jnp.bfloat16))
        return x + (jax.nn.gelu(up.astype(jnp.float32)).astype(jnp.bfloat16)
                    @ bp["w2"].astype(jnp.bfloat16)
                    ).astype(jnp.float32), jnp.float32(0.0)

    if cfg.remat:
        block = jax.checkpoint(block)

    def forward(params, ids, with_aux: bool = False):
        assert ids.shape[-1] <= cfg.max_seq, (
            f"seq {ids.shape[-1]} exceeds max_seq {cfg.max_seq}")
        x = params["embed"][ids]
        sin, cos = _rope_tables(ids.shape[-1], cfg.dim // cfg.heads)
        if cfg.scan_layers:
            def body(x, bp):
                x, aux = block(bp, x, sin, cos)
                return x, aux

            x, auxs = jax.lax.scan(body, x, params["blocks"])
            aux_total = auxs.sum()
        else:
            aux_total = jnp.float32(0.0)
            for i in range(cfg.depth):
                x, aux = block(params[f"blk{i}"], x, sin, cos)
                aux_total = aux_total + aux
        logits = (x.astype(jnp.bfloat16)
                  @ params["unembed"].astype(jnp.bfloat16)).astype(
                      jnp.float32)
        return (logits, aux_total) if with_aux else logits

    return forward


def _rope_at(x, pos, head_dim: int):
    """Rotary embedding for ONE position (traced scalar) — same math as
    the table path, built for a single position and fed to _rope so the
    rotation (and any future base/NTK change) has one home."""
    import jax.numpy as jnp
    half = head_dim // 2
    freq = jnp.exp(-math.log(10000.0)
                   * jnp.arange(half, dtype=jnp.float32) / half)
    ang = (pos.astype(jnp.float32) * freq)[None, None, None, :]
    return _rope(x, jnp.sin(ang), jnp.cos(ang))


def make_decode(cfg: LMConfig):
    """Autoregressive serving path: static-shape KV cache, one token per
    step — the jit-friendly inference loop (no dynamic shapes: the cache
    is (b, max_seq, heads, hd) from the start, positions masked).

    Returns ``(prefill, decode_step)``:
      - ``prefill(params, ids[b, s]) -> (cache, logits[b, vocab])`` —
        runs the prompt once, fills the cache, returns last-position
        logits;
      - ``decode_step(params, cache, token[b]) -> (cache, logits)`` —
        appends one token (rope at its true position) and attends over
        the cached prefix.  Donate the cache at the jit boundary for
        in-place updates."""
    import jax
    import jax.numpy as jnp

    hd = cfg.dim // cfg.heads
    if cfg.scan_layers and cfg.moe_experts > 0:
        raise NotImplementedError(
            "scanned decode does not support MoE blocks — use "
            "scan_layers=False for MoE serving")
    if cfg.moe_experts > 0:
        from .moe import forward_grouped as moe_forward
        moe_cfg = cfg.moe_cfg()

    # every weight matmul goes through qmatmul: plain arrays take the
    # usual bf16 path, QuantTensors (quantize_lm_params) stream int8
    # weights — the serving win, since single-token decode is bound by
    # weight bytes read per step, not FLOPs (ops/quant.py)
    from ..ops.quant import qmatmul

    def mlp(bp, h):
        if cfg.moe_experts > 0:
            out, _ = moe_forward(bp["moe"], h, moe_cfg)
            return out
        up = qmatmul(h, bp["w1"])
        return qmatmul(jax.nn.gelu(up), bp["w2"])

    def unembed(params, x_last):
        return qmatmul(x_last, params["unembed"])

    def prefill_layer(bp, x, sin, cos):
        """One block of prompt processing; returns (x, k, v) with k/v
        written into fresh max_seq caches."""
        b, s = x.shape[0], x.shape[1]
        h = _rmsnorm(x, bp["ln1"])
        qkv = qmatmul(h, bp["wqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (b, s, cfg.heads, hd)
        q, k = (_rope(t.reshape(shp), sin, cos) for t in (q, k))
        v = v.reshape(shp)
        kc = jnp.zeros((b, cfg.max_seq, cfg.heads, hd), jnp.float32)
        vc = jnp.zeros((b, cfg.max_seq, cfg.heads, hd), jnp.float32)
        kc = jax.lax.dynamic_update_slice(kc, k, (0, 0, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, v, (0, 0, 0, 0))
        # seq-adaptive: long prompts prefill through the flash kernel
        # (O(s) memory) instead of materializing (s, s) scores per
        # layer — honoring the same impl override as make_forward
        from ..ops.flash_attention import attention
        impl = "flash" if cfg.use_flash else cfg.attn_impl
        att = attention(q, k, v, causal=cfg.causal, impl=impl)
        x = x + qmatmul(att.reshape(b, s, cfg.dim), bp["wo"])
        x = x + mlp(bp, _rmsnorm(x, bp["ln2"]))
        return x, kc, vc

    def decode_layer(bp, x, kc, vc, pos):
        """One block of single-token decode; returns (x, kc, vc) with
        this token's k/v written at ``pos``."""
        b = x.shape[0]
        h = _rmsnorm(x, bp["ln1"])
        qkv = qmatmul(h, bp["wqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (b, 1, cfg.heads, hd)
        q = _rope_at(q.reshape(shp), pos, hd)
        k = _rope_at(k.reshape(shp), pos, hd)
        v = v.reshape(shp)
        kc = jax.lax.dynamic_update_slice(kc, k, (0, pos, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, v, (0, pos, 0, 0))
        # attend the single query over the cached prefix
        s_mat = jnp.einsum("bqhd,bkhd->bhqk", q, kc,
                           preferred_element_type=jnp.float32
                           ) / (hd ** 0.5)
        live = jnp.arange(cfg.max_seq) <= pos        # prefix + self
        s_mat = jnp.where(live[None, None, None, :], s_mat, -1e30)
        p = jax.nn.softmax(s_mat, axis=-1)
        att = jnp.einsum("bhqk,bkhd->bqhd", p, vc,
                         preferred_element_type=jnp.float32)
        x = x + qmatmul(att.reshape(b, 1, cfg.dim), bp["wo"])
        x = x + mlp(bp, _rmsnorm(x, bp["ln2"]))
        return x, kc, vc

    def prefill(params, ids):
        b, s = ids.shape
        assert s <= cfg.max_seq
        x = params["embed"][ids]
        sin, cos = _rope_tables(s, hd)
        if cfg.scan_layers:
            # one compiled layer body regardless of depth — the serving
            # answer to compile-time scaling (the train path's story,
            # make_forward): caches come back stacked (depth, ...)
            def body(x, bp):
                x, kc, vc = prefill_layer(bp, x, sin, cos)
                return x, (kc, vc)

            x, (kcs, vcs) = jax.lax.scan(body, x, params["blocks"])
            cache = {"len": jnp.int32(s), "k": kcs, "v": vcs}
            return cache, unembed(params, x[:, -1])
        cache = {"len": jnp.int32(s)}
        for i in range(cfg.depth):
            x, kc, vc = prefill_layer(params[f"blk{i}"], x, sin, cos)
            cache[f"k{i}"], cache[f"v{i}"] = kc, vc
        return cache, unembed(params, x[:, -1])

    def decode_step(params, cache, token):
        cache = dict(cache)      # never mutate the caller's dict (an
                                 # eager caller may fork it — beam/retry)
        pos = cache["len"]                           # traced scalar
        x = params["embed"][token][:, None, :]       # (b, 1, d)
        if cfg.scan_layers:
            def body(x, layer):
                bp, kc, vc = layer
                x, kc, vc = decode_layer(bp, x, kc, vc, pos)
                return x, (kc, vc)

            x, (kcs, vcs) = jax.lax.scan(
                body, x, (params["blocks"], cache["k"], cache["v"]))
            cache["k"], cache["v"] = kcs, vcs
            cache["len"] = pos + 1
            return cache, unembed(params, x[:, 0])
        for i in range(cfg.depth):
            x, kc, vc = decode_layer(params[f"blk{i}"], x,
                                     cache[f"k{i}"], cache[f"v{i}"], pos)
            cache[f"k{i}"], cache[f"v{i}"] = kc, vc
        cache["len"] = pos + 1
        return cache, unembed(params, x[:, 0])

    return prefill, decode_step


def empty_cache(cfg: LMConfig, batch: int, start_len: int = 1):
    """A fresh KV cache in the layout make_decode's steps expect — the
    model owns this structure; callers (benches, servers pre-allocating
    serving slots) must not hand-roll it.  ``scan_layers`` configs use
    stacked (depth, ...) caches matching the scanned decode."""
    import jax.numpy as jnp
    hd = cfg.dim // cfg.heads
    cache = {"len": jnp.int32(start_len)}
    if cfg.scan_layers:
        shape = (cfg.depth, batch, cfg.max_seq, cfg.heads, hd)
        # two DISTINCT buffers: donating a cache that aliases k and v
        # to one array is a double-donation error on TPU
        cache["k"] = jnp.zeros(shape, jnp.float32)
        cache["v"] = jnp.zeros(shape, jnp.float32)
        return cache
    for i in range(cfg.depth):
        cache[f"k{i}"] = jnp.zeros((batch, cfg.max_seq, cfg.heads, hd),
                                   jnp.float32)
        cache[f"v{i}"] = jnp.zeros((batch, cfg.max_seq, cfg.heads, hd),
                                   jnp.float32)
    return cache


def kv_page_specs(cfg: LMConfig, batch: int = 1):
    """Ordered ``(shape, dtype, nbytes)`` of a decode cache's
    transferable KV pages — k then v per layer, the page order
    :func:`export_decode_cache` emits and the import side rebuilds
    from.  Layout is owned by the MODEL (like :func:`empty_cache`):
    the wire carries sizes for validation only, never shape."""
    if cfg.scan_layers:
        raise NotImplementedError(
            "paged KV export supports unrolled layers only (the "
            "continuous batcher's serving shape)")
    hd = cfg.dim // cfg.heads
    shape = (batch, cfg.max_seq, cfg.heads, hd)
    nbytes = batch * cfg.max_seq * cfg.heads * hd * 4      # float32
    return [(shape, "float32", nbytes) for _ in range(2 * cfg.depth)]


def export_decode_cache(cfg: LMConfig, cache):
    """A prefilled :func:`make_decode` cache (batch-1, unrolled) as its
    transferable page list ``[(device_array, nbytes), ...]`` in
    :func:`kv_page_specs` order.  No data motion here: the pages ARE
    the live cache arrays — the transfer plane decides whether they
    move as registered memory (descriptor) or bytes."""
    if cfg.scan_layers:
        raise NotImplementedError(
            "paged KV export supports unrolled layers only")
    pages = []
    for i in range(cfg.depth):
        for key in (f"k{i}", f"v{i}"):
            arr = cache[key]
            pages.append((arr, int(arr.size) * arr.dtype.itemsize))
    return pages


def decode_cache_from_pages(cfg: LMConfig, arrays):
    """Imported page arrays (in :func:`kv_page_specs` order) back into
    the per-layer cache dict the batcher's slot insert consumes."""
    if len(arrays) != 2 * cfg.depth:
        raise ValueError(
            f"expected {2 * cfg.depth} pages, got {len(arrays)}")
    cache = {}
    it = iter(arrays)
    for i in range(cfg.depth):
        cache[f"k{i}"] = next(it)
        cache[f"v{i}"] = next(it)
    return cache


def _rope_at_vec(x, pos, head_dim: int):
    """Rotary embedding at PER-ELEMENT positions — the continuous-
    batching variant of :func:`_rope_at`: ``x`` is (b, 1, heads, hd)
    and ``pos`` is a (b,) vector, so every batch slot rotates at its
    own sequence position (sessions in one batch sit at different
    depths).  Same math, same single home for the rotation."""
    import jax.numpy as jnp
    half = head_dim // 2
    freq = jnp.exp(-math.log(10000.0)
                   * jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[:, None, None, None] \
        * freq[None, None, None, :]
    return _rope(x, jnp.sin(ang), jnp.cos(ang))


def _rope_span_vec(x, pos, head_dim: int):
    """Rotary embedding for a SPAN of positions shared across batch —
    the chunked-prefill variant: ``x`` is (b, s, heads, hd) and ``pos``
    is an (s,) position vector (typically ``start + arange(chunk)``),
    the exact math :func:`_rope_tables` produces for ``arange(s)`` —
    so a chunk slice rotates identically with the whole-prompt pass."""
    import jax.numpy as jnp
    half = head_dim // 2
    freq = jnp.exp(-math.log(10000.0)
                   * jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[None, :, None, None] \
        * freq[None, None, None, :]
    return _rope(x, jnp.sin(ang), jnp.cos(ang))


def _rope_at_mat(x, pos, head_dim: int):
    """Rotary embedding at PER-(slot, offset) positions — the
    speculative-verify variant: ``x`` is (b, w, heads, hd) and ``pos``
    is a (b, w) position matrix (each slot's ``len + arange(w)``).
    Same rotation, same single home."""
    import jax.numpy as jnp
    half = head_dim // 2
    freq = jnp.exp(-math.log(10000.0)
                   * jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[:, :, None, None] \
        * freq[None, None, None, :]
    return _rope(x, jnp.sin(ang), jnp.cos(ang))


def make_batch_decode(cfg: LMConfig, chunk: Optional[int] = None):
    """Continuous-batching decode: one compiled step over a FIXED pool
    of session slots, each at its OWN position — the serving shape
    where new sessions join the live batch between steps and finished
    ones evict (the streaming LM service's engine).

    Returns ``(prefill, step)``:
      - ``prefill`` is :func:`make_decode`'s prompt pass, run per
        joining session at batch 1 — the batcher copies the resulting
        per-layer caches into the session's slot;
      - ``step(params, cache, token[b], active[b]) -> (cache, logits)``
        advances every ACTIVE slot one token.  ``cache["len"]`` is a
        per-slot (b,) int32 position vector (vs the scalar in
        :func:`make_decode`); inactive slots are position-clamped and
        never advance, and their logits are garbage by contract.

    With ``chunk`` set, a third program is returned — the
    chunk-scatter path of SLO-tiered scheduling (Sarathi-style chunked
    prefill): ``chunk_step(params, cache, slot, start, n, ids[chunk])
    -> cache`` prefills ``n`` context tokens of one slot at positions
    ``start..start+n-1`` and sets that slot's len to ``start + n``.
    Padding entries (``j >= n``) write their garbage k/v into row
    ``max_seq - 1``, which every admissible session rewrites before
    the live mask admits it (``ctx + max_new <= max_seq`` with
    ``max_new >= 1`` keeps valid context rows strictly below it).
    The slice attends with the same masked softmax as the decode step,
    so a fully chunk-prefilled slot is identical-by-construction to a
    whole-prompt prefill insert — the chunked-prefill identity pin.

    Per-element math is independent (attention never crosses the batch
    axis), so an active slot's tokens are identical with a solo
    :func:`make_decode` run of the same session.  Unrolled dense/MoE
    blocks only — ``scan_layers`` serving should batch per-depth
    shards instead."""
    import jax
    import jax.numpy as jnp

    hd = cfg.dim // cfg.heads
    if cfg.scan_layers:
        raise NotImplementedError(
            "batch decode supports unrolled layers only — scan_layers "
            "serving uses make_decode per shard")
    if cfg.moe_experts > 0:
        from .moe import forward_grouped as moe_forward
        moe_cfg = cfg.moe_cfg()

    from ..ops.quant import qmatmul

    def mlp(bp, h):
        if cfg.moe_experts > 0:
            out, _ = moe_forward(bp["moe"], h, moe_cfg)
            return out
        up = qmatmul(h, bp["w1"])
        return qmatmul(jax.nn.gelu(up), bp["w2"])

    def decode_layer(bp, x, kc, vc, pos):
        """One block, one token per slot, per-slot positions."""
        b = x.shape[0]
        h = _rmsnorm(x, bp["ln1"])
        qkv = qmatmul(h, bp["wqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (b, 1, cfg.heads, hd)
        q = _rope_at_vec(q.reshape(shp), pos, hd)
        k = _rope_at_vec(k.reshape(shp), pos, hd)
        v = v.reshape(shp)

        def upd(cache_b, new_b, pos_b):
            return jax.lax.dynamic_update_slice(cache_b, new_b,
                                                (pos_b, 0, 0))

        kc = jax.vmap(upd)(kc, k, pos)
        vc = jax.vmap(upd)(vc, v, pos)
        s_mat = jnp.einsum("bqhd,bkhd->bhqk", q, kc,
                           preferred_element_type=jnp.float32
                           ) / (hd ** 0.5)
        live = jnp.arange(cfg.max_seq)[None, :] <= pos[:, None]
        s_mat = jnp.where(live[:, None, None, :], s_mat, -1e30)
        p = jax.nn.softmax(s_mat, axis=-1)
        att = jnp.einsum("bhqk,bkhd->bqhd", p, vc,
                         preferred_element_type=jnp.float32)
        x = x + qmatmul(att.reshape(b, 1, cfg.dim), bp["wo"])
        x = x + mlp(bp, _rmsnorm(x, bp["ln2"]))
        return x, kc, vc

    def step(params, cache, token, active):
        cache = dict(cache)
        pos = jnp.minimum(cache["len"], cfg.max_seq - 1)
        x = params["embed"][token][:, None, :]
        for i in range(cfg.depth):
            x, kc, vc = decode_layer(params[f"blk{i}"], x,
                                     cache[f"k{i}"], cache[f"v{i}"],
                                     pos)
            cache[f"k{i}"], cache[f"v{i}"] = kc, vc
        cache["len"] = jnp.where(active, cache["len"] + 1,
                                 cache["len"])
        return cache, qmatmul(x[:, 0], params["unembed"])

    prefill, _ = make_decode(cfg)
    if chunk is None:
        return prefill, step

    cw = int(chunk)

    def chunk_layer(bp, x, kc, vc, slot, rows, pos):
        """One block of a chunked prefill slice for ONE slot: scatter
        the slice's k/v rows, then attend each slice query over the
        slot's full cached stripe under the same causal live mask the
        decode step uses."""
        h = _rmsnorm(x, bp["ln1"])
        qkv = qmatmul(h, bp["wqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (1, cw, cfg.heads, hd)
        q = _rope_span_vec(q.reshape(shp), pos, hd)
        k = _rope_span_vec(k.reshape(shp), pos, hd)
        v = v.reshape(shp)
        kc = kc.at[slot, rows].set(k[0])
        vc = vc.at[slot, rows].set(v[0])
        kcs = kc[slot]                    # (max_seq, heads, hd)
        vcs = vc[slot]
        s_mat = jnp.einsum("qhd,khd->hqk", q[0], kcs,
                           preferred_element_type=jnp.float32
                           ) / (hd ** 0.5)
        live = jnp.arange(cfg.max_seq)[None, :] <= pos[:, None]
        s_mat = jnp.where(live[None, :, :], s_mat, -1e30)
        p = jax.nn.softmax(s_mat, axis=-1)
        att = jnp.einsum("hqk,khd->qhd", p, vcs,
                         preferred_element_type=jnp.float32)
        x = x + qmatmul(att.reshape(1, cw, cfg.dim), bp["wo"])
        x = x + mlp(bp, _rmsnorm(x, bp["ln2"]))
        return x, kc, vc

    def chunk_step(params, cache, slot, start, n, ids):
        cache = dict(cache)
        j = jnp.arange(cw)
        valid = j < n
        pos = start + j
        # invalid (padding) rows land on max_seq-1: a garbage row every
        # admissible session overwrites before its mask admits it
        rows = jnp.where(valid, jnp.minimum(pos, cfg.max_seq - 1),
                         cfg.max_seq - 1)
        x = params["embed"][ids][None]            # (1, chunk, dim)
        for i in range(cfg.depth):
            x, kc, vc = chunk_layer(params[f"blk{i}"], x,
                                    cache[f"k{i}"], cache[f"v{i}"],
                                    slot, rows, pos)
            cache[f"k{i}"], cache[f"v{i}"] = kc, vc
        cache["len"] = cache["len"].at[slot].set(start + n)
        return cache

    return prefill, step, chunk_step


def empty_batch_cache(cfg: LMConfig, slots: int):
    """A fresh slot-pool KV cache for :func:`make_batch_decode` —
    ``len`` is the per-slot position vector (all zero = every slot
    free); layer layouts match :func:`empty_cache`'s unrolled form."""
    import jax.numpy as jnp
    cache = empty_cache(cfg, slots, start_len=1)
    cache["len"] = jnp.zeros((slots,), jnp.int32)
    return cache


def make_paged_batch_decode(cfg: LMConfig, page: int):
    """Block-paged continuous batching: :func:`make_batch_decode` with
    the per-slot contiguous cache arrays replaced by ONE shared page
    pool per layer plus a per-slot **block table** — the serving shape
    where a session holds only ``ctx_len``-rounded pages instead of a
    full ``max_seq`` stripe, and where two sessions may ALIAS the same
    page (the cross-session prefix cache).

    Layout (one logical address space across layers): logical page ``p``
    is row-block ``p`` of EVERY layer's k/v pool, shaped
    ``(num_pages, page, heads, hd)``.  Page 0 is the reserved garbage
    page — unallocated block-table entries and inactive slots write
    there, and the attention mask never admits an unwritten row (the
    ``live`` mask only reaches rows <= pos, all of which the owning
    session has written).

    Returns ``(prefill, step)`` where
    ``step(params, cache, bt, token[b], active[b]) -> (cache, logits)``
    and ``bt`` is the (slots, max_seq // page) int32 block table (host-
    owned, passed per step — NOT part of the donated cache).  The step
    scatters the new k/v row into ``pool[bt[b, pos // page], pos % page]``
    and gathers ``pool[bt]`` back into exactly the (b, max_seq, heads,
    hd) array the contiguous step attends over, then runs the SAME
    masked attention — token identity with :func:`make_batch_decode` by
    construction, which the per-lane pins assert."""
    import jax
    import jax.numpy as jnp

    hd = cfg.dim // cfg.heads
    if cfg.scan_layers:
        raise NotImplementedError(
            "paged batch decode supports unrolled layers only")
    if cfg.max_seq % page:
        raise ValueError(
            f"page size {page} must divide max_seq {cfg.max_seq}")
    pps = cfg.max_seq // page       # pages per slot (block-table width)
    if cfg.moe_experts > 0:
        from .moe import forward_grouped as moe_forward
        moe_cfg = cfg.moe_cfg()

    from ..ops.quant import qmatmul

    def mlp(bp, h):
        if cfg.moe_experts > 0:
            out, _ = moe_forward(bp["moe"], h, moe_cfg)
            return out
        up = qmatmul(h, bp["w1"])
        return qmatmul(jax.nn.gelu(up), bp["w2"])

    def decode_layer(bp, x, pk, pv, bt, pos):
        """One block, one token per slot, block-table addressing."""
        b = x.shape[0]
        h = _rmsnorm(x, bp["ln1"])
        qkv = qmatmul(h, bp["wqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (b, 1, cfg.heads, hd)
        q = _rope_at_vec(q.reshape(shp), pos, hd)
        k = _rope_at_vec(k.reshape(shp), pos, hd)
        v = v.reshape(shp)

        # scatter this step's row into each slot's CURRENT page
        page_idx = bt[jnp.arange(b), pos // page]
        row = pos % page
        pk = pk.at[page_idx, row].set(k[:, 0])
        pv = pv.at[page_idx, row].set(v[:, 0])

        # gather the block table back into the contiguous view the
        # un-paged step attends over (unwritten pages are garbage but
        # sit beyond the live mask by construction)
        kc = pk[bt].reshape(b, cfg.max_seq, cfg.heads, hd)
        vc = pv[bt].reshape(b, cfg.max_seq, cfg.heads, hd)
        s_mat = jnp.einsum("bqhd,bkhd->bhqk", q, kc,
                           preferred_element_type=jnp.float32
                           ) / (hd ** 0.5)
        live = jnp.arange(cfg.max_seq)[None, :] <= pos[:, None]
        s_mat = jnp.where(live[:, None, None, :], s_mat, -1e30)
        p = jax.nn.softmax(s_mat, axis=-1)
        att = jnp.einsum("bhqk,bkhd->bqhd", p, vc,
                         preferred_element_type=jnp.float32)
        x = x + qmatmul(att.reshape(b, 1, cfg.dim), bp["wo"])
        x = x + mlp(bp, _rmsnorm(x, bp["ln2"]))
        return x, pk, pv

    def step(params, cache, bt, token, active):
        cache = dict(cache)
        pos = jnp.minimum(cache["len"], cfg.max_seq - 1)
        x = params["embed"][token][:, None, :]
        for i in range(cfg.depth):
            x, pk, pv = decode_layer(params[f"blk{i}"], x,
                                     cache[f"pk{i}"], cache[f"pv{i}"],
                                     bt, pos)
            cache[f"pk{i}"], cache[f"pv{i}"] = pk, pv
        cache["len"] = jnp.where(active, cache["len"] + 1,
                                 cache["len"])
        return cache, qmatmul(x[:, 0], params["unembed"])

    prefill, _ = make_decode(cfg)
    return prefill, step


def empty_paged_cache(cfg: LMConfig, num_pages: int, slots: int,
                      page: int):
    """A fresh page-pool KV cache for :func:`make_paged_batch_decode`:
    per layer one ``(num_pages, page, heads, hd)`` k and v pool (page 0
    reserved as the garbage page) plus the per-slot ``len`` vector.
    The block table is NOT here — it is host state
    (``kv.pages.PageAllocator`` decides it), passed to the step."""
    import jax.numpy as jnp
    if cfg.max_seq % page:
        raise ValueError(
            f"page size {page} must divide max_seq {cfg.max_seq}")
    hd = cfg.dim // cfg.heads
    cache = {}
    for i in range(cfg.depth):
        cache[f"pk{i}"] = jnp.zeros((num_pages, page, cfg.heads, hd),
                                    jnp.float32)
        cache[f"pv{i}"] = jnp.zeros((num_pages, page, cfg.heads, hd),
                                    jnp.float32)
    cache["len"] = jnp.zeros((slots,), jnp.int32)
    return cache


def paged_page_bytes(cfg: LMConfig, page: int) -> int:
    """Device bytes one LOGICAL page pins across every layer's k+v
    pools (the allocator's per-page accounting unit)."""
    hd = cfg.dim // cfg.heads
    return 2 * cfg.depth * page * cfg.heads * hd * 4       # float32


def make_paged_io(cfg: LMConfig, page: int, chunk: Optional[int] = None):
    """Page-granular device I/O for the paged cache — the spill /
    resume / prefill-insert data motion, all fixed-shape (padded to the
    block-table width with garbage-page entries) so each jits ONCE.

    Returns ``(gather, scatter, insert)``:
      - ``gather(cache, page_ids[pps]) -> (pps, 2*depth, page, heads,
        hd)`` — a session's logical pages as one host-transferable
        block (k then v per layer on axis 1);
      - ``scatter(cache, page_ids[pps], block) -> cache`` — the
        inverse (resume's H2D landing);
      - ``insert(cache, page_ids[pps], src) -> cache`` — a batch-1
        prefilled contiguous cache (``make_decode``'s) blockified into
        the session's pages.
    Padding entries point at page 0 and only ever write garbage there.

    With ``chunk`` set a FOURTH program rides along — the block-paged
    chunk-scatter path of SLO-tiered scheduling:
    ``chunk_prefill(params, cache, bt_row[pps], slot, start, n,
    ids[chunk]) -> cache`` prefills ``n`` context tokens of one slot
    at positions ``start..start+n-1``, scattering each row into
    ``bt_row[pos // page]`` and setting the slot's len to
    ``start + n``.  Padding entries write the garbage page 0 (the
    established paged-padding idiom), and a partial prefix hit's
    catch-up starts at a page-aligned ``covered`` — so aliased prefix
    pages are never written.  The slice gathers the block table back
    into the contiguous view and attends under the decode step's own
    live mask: a fully chunk-prefilled slot is
    identical-by-construction to a whole-prompt prefill insert."""
    import jax.numpy as jnp
    if cfg.max_seq % page:
        raise ValueError(
            f"page size {page} must divide max_seq {cfg.max_seq}")
    pps = cfg.max_seq // page
    hd = cfg.dim // cfg.heads

    def gather(cache, page_ids):
        blocks = []
        for i in range(cfg.depth):
            blocks.append(cache[f"pk{i}"][page_ids])
            blocks.append(cache[f"pv{i}"][page_ids])
        return jnp.stack(blocks, axis=1)

    def scatter(cache, page_ids, block):
        cache = dict(cache)
        for i in range(cfg.depth):
            cache[f"pk{i}"] = cache[f"pk{i}"].at[page_ids].set(
                block[:, 2 * i])
            cache[f"pv{i}"] = cache[f"pv{i}"].at[page_ids].set(
                block[:, 2 * i + 1])
        return cache

    def insert(cache, page_ids, src):
        cache = dict(cache)
        for i in range(cfg.depth):
            kb = src[f"k{i}"][0].reshape(pps, page, cfg.heads, hd)
            vb = src[f"v{i}"][0].reshape(pps, page, cfg.heads, hd)
            cache[f"pk{i}"] = cache[f"pk{i}"].at[page_ids].set(kb)
            cache[f"pv{i}"] = cache[f"pv{i}"].at[page_ids].set(vb)
        return cache

    if chunk is None:
        return gather, scatter, insert

    import jax
    from ..ops.quant import qmatmul
    if cfg.moe_experts > 0:
        from .moe import forward_grouped as moe_forward
        moe_cfg = cfg.moe_cfg()
    cw = int(chunk)

    def mlp(bp, h):
        if cfg.moe_experts > 0:
            out, _ = moe_forward(bp["moe"], h, moe_cfg)
            return out
        up = qmatmul(h, bp["w1"])
        return qmatmul(jax.nn.gelu(up), bp["w2"])

    def chunk_layer(bp, x, pk, pv, bt_row, page_idx, row, pos):
        h = _rmsnorm(x, bp["ln1"])
        qkv = qmatmul(h, bp["wqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (1, cw, cfg.heads, hd)
        q = _rope_span_vec(q.reshape(shp), pos, hd)
        k = _rope_span_vec(k.reshape(shp), pos, hd)
        v = v.reshape(shp)
        pk = pk.at[page_idx, row].set(k[0])
        pv = pv.at[page_idx, row].set(v[0])
        kcs = pk[bt_row].reshape(cfg.max_seq, cfg.heads, hd)
        vcs = pv[bt_row].reshape(cfg.max_seq, cfg.heads, hd)
        s_mat = jnp.einsum("qhd,khd->hqk", q[0], kcs,
                           preferred_element_type=jnp.float32
                           ) / (hd ** 0.5)
        live = jnp.arange(cfg.max_seq)[None, :] <= pos[:, None]
        s_mat = jnp.where(live[None, :, :], s_mat, -1e30)
        p = jax.nn.softmax(s_mat, axis=-1)
        att = jnp.einsum("hqk,khd->qhd", p, vcs,
                         preferred_element_type=jnp.float32)
        x = x + qmatmul(att.reshape(1, cw, cfg.dim), bp["wo"])
        x = x + mlp(bp, _rmsnorm(x, bp["ln2"]))
        return x, pk, pv

    def chunk_prefill(params, cache, bt_row, slot, start, n, ids):
        cache = dict(cache)
        j = jnp.arange(cw)
        valid = j < n
        posc = jnp.minimum(start + j, cfg.max_seq - 1)
        # padding entries write the reserved garbage page 0
        page_idx = jnp.where(valid, bt_row[posc // page], 0)
        row = posc % page
        x = params["embed"][ids][None]            # (1, chunk, dim)
        for i in range(cfg.depth):
            x, pk, pv = chunk_layer(params[f"blk{i}"], x,
                                    cache[f"pk{i}"], cache[f"pv{i}"],
                                    bt_row, page_idx, row, start + j)
            cache[f"pk{i}"], cache[f"pv{i}"] = pk, pv
        cache["len"] = cache["len"].at[slot].set(start + n)
        return cache

    return gather, scatter, insert, chunk_prefill


def make_paged_spec_verify(cfg: LMConfig, page: int, width: int):
    """Speculative-decoding TARGET verification over the paged cache —
    one multi-token step per round: ``width = k + 1`` candidate tokens
    ``[x0, d1..dk]`` (the slot's pending token plus the draft model's
    proposals) are scattered and attended in ONE program, and the
    longest accepted prefix is computed on-device.

    Returns ``verify(params, cache, bt, tokens[b, w], active[b]) ->
    (cache, out[b, w], accepted[b])``:

    - row ``j`` of ``out`` is the greedy argmax at position
      ``len + j`` given context rows ``0..len+j`` — exactly the token
      the plain decode step would emit after feeding ``tokens[:, :j+1]``
      (same scatter-before-gather, same live mask, same einsum
      attention), which is the spec-decode token-identity contract;
    - ``accepted`` is the per-slot length ``m`` of the draft prefix
      matching the target (``d_i == out_{i-1}``), CAPPED at ``k - 1``
      so the draft cache — which holds k/v for inputs ``u_0..u_{k-1}``
      only — never runs ahead of a row it wrote (the standard
      discard-the-bonus-token rule);
    - ``len`` advances by ``m + 1`` for active slots (the emitted
      tokens ``out[:, :m+1]``).  REJECTED rows ``len+m+1..len+k`` keep
      their scattered garbage: they sit beyond the new len, and the
      garbage-beyond-mask invariant (every admissible row is rewritten
      by a later scatter before the live mask admits it) makes the
      rollback a pure len rewind — no page-table mutation.

    The caller must guarantee ``len + width <= max_seq`` for every
    active slot (the batcher falls back to a plain step otherwise)."""
    import jax
    import jax.numpy as jnp

    hd = cfg.dim // cfg.heads
    if cfg.scan_layers:
        raise NotImplementedError(
            "spec verify supports unrolled layers only")
    if cfg.max_seq % page:
        raise ValueError(
            f"page size {page} must divide max_seq {cfg.max_seq}")
    w = int(width)
    if w < 2:
        raise ValueError("spec verify needs width >= 2 (k >= 1)")
    if cfg.moe_experts > 0:
        from .moe import forward_grouped as moe_forward
        moe_cfg = cfg.moe_cfg()

    from ..ops.quant import qmatmul

    def mlp(bp, h):
        if cfg.moe_experts > 0:
            out, _ = moe_forward(bp["moe"], h, moe_cfg)
            return out
        up = qmatmul(h, bp["w1"])
        return qmatmul(jax.nn.gelu(up), bp["w2"])

    def verify_layer(bp, x, pk, pv, bt, pos):
        b = x.shape[0]
        h = _rmsnorm(x, bp["ln1"])
        qkv = qmatmul(h, bp["wqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (b, w, cfg.heads, hd)
        q = _rope_at_mat(q.reshape(shp), pos, hd)
        k = _rope_at_mat(k.reshape(shp), pos, hd)
        v = v.reshape(shp)
        # scatter all w candidate rows (rejected ones become the
        # garbage a later scatter overwrites — see docstring)
        page_idx = bt[jnp.arange(b)[:, None], pos // page]
        row = pos % page
        pk = pk.at[page_idx, row].set(k)
        pv = pv.at[page_idx, row].set(v)
        kc = pk[bt].reshape(b, cfg.max_seq, cfg.heads, hd)
        vc = pv[bt].reshape(b, cfg.max_seq, cfg.heads, hd)
        s_mat = jnp.einsum("bqhd,bkhd->bhqk", q, kc,
                           preferred_element_type=jnp.float32
                           ) / (hd ** 0.5)
        live = jnp.arange(cfg.max_seq)[None, None, :] <= pos[:, :, None]
        s_mat = jnp.where(live[:, None, :, :], s_mat, -1e30)
        p = jax.nn.softmax(s_mat, axis=-1)
        att = jnp.einsum("bhqk,bkhd->bqhd", p, vc,
                         preferred_element_type=jnp.float32)
        x = x + qmatmul(att.reshape(b, w, cfg.dim), bp["wo"])
        x = x + mlp(bp, _rmsnorm(x, bp["ln2"]))
        return x, pk, pv

    def verify(params, cache, bt, tokens, active):
        cache = dict(cache)
        pos = jnp.minimum(
            cache["len"][:, None] + jnp.arange(w)[None, :],
            cfg.max_seq - 1)                       # (b, w)
        x = params["embed"][tokens]                # (b, w, dim)
        for i in range(cfg.depth):
            x, pk, pv = verify_layer(params[f"blk{i}"], x,
                                     cache[f"pk{i}"], cache[f"pv{i}"],
                                     bt, pos)
            cache[f"pk{i}"], cache[f"pv{i}"] = pk, pv
        logits = qmatmul(x, params["unembed"])     # (b, w, vocab)
        out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # accepted prefix: d_i (= tokens[:, i]) vs out[:, i-1], capped
        # at k-1 = w-2 (the bonus-token discard)
        match = (tokens[:, 1:] == out[:, :w - 1]).astype(jnp.int32)
        m = jnp.minimum(jnp.cumprod(match, axis=1).sum(axis=1),
                        w - 2).astype(jnp.int32)   # (b,)
        cache["len"] = jnp.where(active, cache["len"] + m + 1,
                                 cache["len"])
        return cache, out, m

    return verify


def make_decode_loop(cfg: LMConfig, steps: int):
    """Greedy generation as ONE compiled program: ``lax.scan`` feeds the
    argmax token back through ``decode_step`` for ``steps`` tokens, so a
    whole generation burst costs a single device dispatch.  This is the
    serving shape for dispatch-dominated runtimes (a per-token program
    pays the host/tunnel round trip per TOKEN; the scan pays it per
    BURST) and the honest harness for weight-streaming measurements —
    per-token time becomes pure device time.

    Returns (prefill, loop) where loop(params, cache, token) ->
    (cache, tokens (steps, b))."""
    import jax
    import jax.numpy as jnp

    prefill, decode_step = make_decode(cfg)

    def loop(params, cache, token):
        def body(carry, _):
            cache, tok = carry
            cache, logits = decode_step(params, cache, tok)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (cache, nxt), nxt

        (cache, _), toks = jax.lax.scan(body, (cache, token), None,
                                        length=steps)
        return cache, toks

    return prefill, loop


def make_generator(cfg: LMConfig, params):
    """Build a ``gen(prompt_ids, max_new, temperature=0.0, rng=None)``
    closure with the prefill and decode-step programs jitted ONCE —
    the serving form (LMService holds one of these; re-jitting per
    request would pay XLA compilation on every RPC).  temperature 0 is
    greedy; > 0 samples and REQUIRES an rng key (each call should pass
    a fresh one).  The decode step donates the cache for in-place
    updates."""
    import functools as _ft

    import jax
    import jax.numpy as jnp

    prefill, decode_step = make_decode(cfg)
    prefill_j = jax.jit(prefill)
    step_j = jax.jit(_ft.partial(decode_step, params),
                     donate_argnums=(0,))

    def pick(logits, temperature, rng):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            rng, logits / temperature, axis=-1).astype(jnp.int32)

    def gen(prompt_ids, max_new: int, temperature: float = 0.0,
            rng=None):
        """temperature 0 = greedy (deterministic); > 0 samples from the
        softmax at that temperature (pass ``rng`` for reproducibility)."""
        _validate_gen_args(cfg, prompt_ids, max_new, temperature, rng)
        cache, logits = prefill_j(params, prompt_ids)
        out = []
        for i in range(max_new):
            if temperature > 0.0:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            token = pick(logits, temperature, sub)
            out.append(token)
            if i < max_new - 1:          # the last emitted token needs
                cache, logits = step_j(cache, token)   # no further step
        return jnp.stack(out, axis=1)

    return gen


def _validate_gen_args(cfg: LMConfig, prompt_ids, max_new: int,
                       temperature: float, rng) -> None:
    """Shared generation-contract checks (both generator forms)."""
    s = prompt_ids.shape[1]
    if s + max_new > cfg.max_seq:
        raise ValueError(
            f"prompt {s} + max_new {max_new} exceeds max_seq "
            f"{cfg.max_seq} (the cache would silently wrap)")
    if temperature > 0.0 and rng is None:
        raise ValueError(
            "temperature > 0 requires an rng key (a silent default "
            "would make every sampled completion identical)")


def make_scan_generator(cfg: LMConfig, params):
    """Whole-completion generation as ONE device program: prefill, then
    ``lax.scan`` over decode steps with token selection on-device —
    the host dispatches twice per request instead of once per token.

    Single-stream decode at small model sizes is dispatch-bound (each
    per-token program launch costs more than its compute); scanning the
    steps moved the measured rate from ~200 to ~530 tok/s on the test
    chip.  One program compiles per (batch, prompt_len, max_new,
    sampled?) tuple — serving paths should bucket ``max_new``
    (LMService rounds up to the next power of two and slices); the
    greedy specialization carries no sampling ops at all.

    Returns ``gen(prompt_ids, max_new, temperature=0.0, rng=None) ->
    (b, max_new) int32``, same contract as :func:`make_generator`."""
    import functools as _ft

    import jax
    import jax.numpy as jnp

    prefill, decode_step = make_decode(cfg)

    @_ft.partial(jax.jit, static_argnums=(1, 2))
    def run(prompt_ids, max_new, sample, temperature, rng):
        cache, logits = prefill(params, prompt_ids)

        def pick(logits, sub):
            if sample:
                return jax.random.categorical(
                    sub, logits / temperature, axis=-1).astype(jnp.int32)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

        if sample:
            rng, sub = jax.random.split(rng)
        else:
            sub = rng
        first = pick(logits, sub)           # from the prefill logits
        if max_new == 1:
            return first[:, None]

        def body(carry, _):
            cache, token, rng = carry
            cache, logits = decode_step(params, cache, token)
            if sample:
                rng, sub = jax.random.split(rng)
            else:
                sub = rng
            nxt = pick(logits, sub)
            return (cache, nxt, rng), nxt

        # step-then-pick, length max_new-1: no wasted forward after the
        # final token (matches make_generator's step count)
        (_, _, _), toks = jax.lax.scan(
            body, (cache, first, rng), None, length=max_new - 1)
        return jnp.concatenate([first[:, None],
                                jnp.moveaxis(toks, 0, 1)], axis=1)

    def gen(prompt_ids, max_new: int, temperature: float = 0.0,
            rng=None):
        _validate_gen_args(cfg, prompt_ids, max_new, temperature, rng)
        sample = temperature > 0.0
        if rng is None:
            rng = jax.random.PRNGKey(0)   # unused on the greedy path
        return run(jnp.asarray(prompt_ids), int(max_new), sample,
                   jnp.float32(temperature), rng)

    return gen


def generate(params, cfg: LMConfig, prompt_ids, max_new: int):
    """One-off greedy decoding convenience (compiles per call — hold a
    :func:`make_generator` closure to amortize compilation)."""
    return make_generator(cfg, params)(prompt_ids, max_new)


def make_train_step(cfg: LMConfig, mesh=None, sp_axis=None,
                    accum: int = 1):
    """(params, ids, labels) -> (new_params, loss); plain SGD.

    ``accum`` > 1 turns on gradient accumulation: the leading batch dim
    must be ``accum * microbatch`` and one optimizer step scans the
    microbatches inside the jit (``lax.scan`` — compiler-friendly
    control flow, ONE compiled body), so a chip-filling tokens/step is
    reachable with the HBM footprint of a single microbatch."""
    import jax
    import jax.numpy as jnp

    forward = make_forward(cfg, mesh, sp_axis)

    def loss_fn(params, ids, labels):
        logits, aux = forward(params, ids, with_aux=True)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[..., None],
                                   axis=-1).squeeze(-1)
        return nll.mean() + aux

    def train_step(params, ids, labels, lr: float = cfg.lr):
        if accum <= 1:
            loss, grads = jax.value_and_grad(loss_fn)(params, ids, labels)
        else:
            if ids.shape[0] % accum != 0:
                raise ValueError(
                    f"batch {ids.shape[0]} not divisible by "
                    f"accum={accum} — trailing examples would be "
                    "silently dropped")
            b = ids.shape[0] // accum
            mids = ids.reshape(accum, b, *ids.shape[1:])
            mlbl = labels.reshape(accum, b, *labels.shape[1:])

            def body(carry, mb):
                loss_sum, gacc = carry
                l, g = jax.value_and_grad(loss_fn)(params, *mb)
                gacc = jax.tree_util.tree_map(jnp.add, gacc, g)
                return (loss_sum + l, gacc), None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p), params)
            (loss_sum, grads), _ = jax.lax.scan(
                body, (jnp.float32(0.0), zeros), (mids, mlbl))
            loss = loss_sum / accum
            grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, params, grads)
        return new_params, loss

    return train_step


def param_specs(cfg: LMConfig) -> Dict[str, Any]:
    """NamedSharding PartitionSpecs for a ("dp", "tp") mesh: attention/
    MLP projections shard their wide dim over tp (XLA inserts the
    all-reduces), embeddings shard the vocab."""
    from jax.sharding import PartitionSpec as P

    specs: Dict[str, Any] = {
        "embed": P("tp", None),
        "unembed": P(None, "tp"),
    }
    blk = {
        "wqkv": P(None, "tp"),
        "wo": P("tp", None),
        "ln1": P(None),
        "ln2": P(None),
    }
    if cfg.moe_experts > 0:
        # expert parallelism over the tp axis: each device owns
        # num_experts/tp whole experts (moe.param_specs)
        from .moe import param_specs as moe_specs
        blk["moe"] = moe_specs(cfg.moe_cfg(), ep_axis="tp")
    else:
        blk["w1"] = P(None, "tp")
        blk["w2"] = P("tp", None)
    if cfg.scan_layers:
        import jax

        # stacked weights: replicated leading depth axis + per-layer spec
        specs["blocks"] = jax.tree_util.tree_map(
            lambda s: P(None, *s), blk,
            is_leaf=lambda x: isinstance(x, P))
    else:
        for i in range(cfg.depth):
            specs[f"blk{i}"] = blk
    return specs


def batch_specs() -> Tuple[Any, Any]:
    from jax.sharding import PartitionSpec as P
    return P("dp", None), P("dp", None)
