"""Inference-plane observability (ISSUE 18): the serving-side analogue
of the native engine's telemetry table.

Three planes, all fed by the ONE batcher thread and read passively:

- **step profiler** — per-phase monotonic-ns log2 histograms around the
  continuous batcher's step loop (decode round, chunk/catch-up slices,
  spec draft/verify, prefix lookup, page alloc, host spill/resume,
  stream emit).  The write side is the engine-telemetry pattern: plain
  per-thread counters bumped by the batcher thread ONLY — never a lock,
  never an allocation in the step loop (the histograms are preallocated
  lists; ``record_phase`` is entry-listed in the blocking linter).
  Readers see racy-but-monotonic values, exactly like
  ``engine.telemetry()`` readers do;
- **session timelines** — a bounded ring of per-session records
  (tier/tenant, prompt length, TTFT, per-token ITL log2 histogram,
  prefix hit class, peak pages held, spill/resume/preempt counts, close
  reason) that feeds per-tier ``lm_ttft_ms``/``lm_itl_ms`` percentile
  rows and the CLOSED ``LM_SLO_VERDICTS`` attainment counters
  (``lm_slo_attained_total{tier,verdict}``) judged against the
  :class:`~brpc_tpu.models.lm_service.TierRegistry`'s per-tier targets;
- **snapshot cache** — a ``_TelemetryCache``-style short-TTL cache so
  /vars, /metrics and the ``/lm`` portal page all share ONE snapshot
  per interval (``window()`` additionally retains the previous snapshot
  so the windowed ``spec_accept_rate`` / ``prefix_cache_hit_ratio``
  reflect CURRENT behavior instead of lifetime averages — the lifetime
  keys stay where perf_guard reads them).

Everything here must stay importable without the native engine and
without jax — the module is pure-Python bookkeeping.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from time import monotonic as _mono_s
from time import monotonic_ns as _mono_ns
from typing import Optional

from ..butil.flags import define_flag, get_flag, watch_flag
from ..bvar.multi_dimension import PassiveDimension

define_flag("lm_telemetry", True,
            "serving-plane observability master switch: step-phase "
            "histograms, per-session token timelines, SLO attainment "
            "(flippable live; the step loop reads a flag-cache, not "
            "the flags table)",
            validator=lambda v: isinstance(v, bool))
define_flag("lm_timeline_ring", 256,
            "bounded ring of closed per-session token timelines kept "
            "for the /lm portal's recent-sessions table",
            validator=lambda v: isinstance(v, int) and 0 < v <= 65536)

# ---------------------------------------------------------------------------
# Step profiler: per-phase log2 ns histograms (batcher-thread writes)
# ---------------------------------------------------------------------------

# CLOSED enum (tools/check/enums.py pins every member to a test): the
# step loop's named phases.  Indexes are the write-side API — the
# batcher binds the PH_* constants as locals, so the hot path is two
# list increments and an int add per phase sample.
LM_STEP_PHASES = (
    "decode_round",      # one decode round (plain step or spec round)
    "chunk_slice",       # one bounded prefill slice (fresh prompt)
    "catchup_slice",     # slice replaying past a partial prefix hit
    "spec_draft",        # the k draft-model steps of a spec round
    "spec_verify",       # the width-(k+1) target verification
    "prefix_lookup",     # prefix-cache probe at admit
    "page_alloc",        # page allocation incl. the reclaim walk
    "host_spill",        # one session's D2H park
    "host_resume",       # one session's H2D un-park
    "stream_emit",       # one step's token writes across all sessions
)

PH_DECODE_ROUND = 0
PH_CHUNK_SLICE = 1
PH_CATCHUP_SLICE = 2
PH_SPEC_DRAFT = 3
PH_SPEC_VERIFY = 4
PH_PREFIX_LOOKUP = 5
PH_PAGE_ALLOC = 6
PH_HOST_SPILL = 7
PH_HOST_RESUME = 8
PH_STREAM_EMIT = 9

_NPHASES = len(LM_STEP_PHASES)

# engine Hist layout: bucket 0 holds zeros, bucket i covers
# [2^(i-1), 2^i) ns; 40 buckets reach ~9 minutes — beyond any phase
NBUCKETS = 40

_phase_buckets = [[0] * NBUCKETS for _ in LM_STEP_PHASES]
_phase_count = [0] * _NPHASES
_phase_total_ns = [0] * _NPHASES

# flag-cached enable gate (the rpcz _rpcz_live idiom): one list read on
# the hot path instead of a flags-table lookup per phase sample
_live = [bool(get_flag("lm_telemetry", True))]
watch_flag("lm_telemetry", lambda v: _live.__setitem__(0, bool(v)))


def telemetry_enabled() -> bool:
    return _live[0]


def phase_index(name: str) -> int:
    assert name in LM_STEP_PHASES, f"unregistered step phase: {name}"
    return LM_STEP_PHASES.index(name)


def record_phase(idx: int, ns: int) -> None:
    """One phase sample (batcher thread only).  Lock-free and
    allocation-free by construction: preallocated per-phase lists, an
    int bit_length for the log2 bucket — the whole per-sample cost the
    observer-effect bench measures."""
    if not _live[0]:
        return
    b = ns.bit_length() if ns > 0 else 0
    if b >= NBUCKETS:
        b = NBUCKETS - 1
    _phase_buckets[idx][b] += 1
    _phase_count[idx] += 1
    _phase_total_ns[idx] += ns if ns > 0 else 0


def bucket_label(i: int, nbuckets: int = NBUCKETS) -> str:
    """Exclusive upper-bound label for log2 bucket i (the engine Hist
    convention — deliberately ``bin``, not Prometheus's cumulative
    ``le``; see transport.native_bridge.bucket_label)."""
    return "+Inf" if i >= nbuckets - 1 else str(1 << i)


def phase_counters() -> dict:
    return {p: _phase_count[i] for i, p in enumerate(LM_STEP_PHASES)}


def phase_total_ns() -> dict:
    return {p: _phase_total_ns[i]
            for i, p in enumerate(LM_STEP_PHASES)}


def phase_histogram(name: str) -> list:
    return list(_phase_buckets[phase_index(name)])


# ---------------------------------------------------------------------------
# SLO attainment: closed verdicts judged at session close
# ---------------------------------------------------------------------------

# CLOSED enum: one verdict per finished session, judged against the
# session's tier targets (TierRegistry.slo_of).  No "unknown" bucket —
# an unregistered verdict fails the assert at the first count.
LM_SLO_VERDICTS = (
    "slo_ok",            # every configured target met
    "slo_ttft_miss",     # first token later than the tier's TTFT target
    "slo_itl_miss",      # an inter-token gap beyond the tier's ITL target
    "slo_untargeted",    # the session's tier configures no targets
)

_slo: dict = {}          # (tier, verdict) -> count, preseeded lazily


def _slo_table() -> dict:
    if not _slo:
        from .lm_service import SLO_TIERS
        for t in SLO_TIERS:
            for v in LM_SLO_VERDICTS:
                _slo[(t, v)] = 0
    return _slo


def count_slo(tier: str, verdict: str) -> None:
    tab = _slo_table()
    assert (tier, verdict) in tab, \
        f"unregistered SLO verdict: {tier}/{verdict}"
    tab[(tier, verdict)] += 1


def slo_counters() -> dict:
    return dict(_slo_table())


# ---------------------------------------------------------------------------
# Session timelines: bounded ring + per-tier latency histograms
# ---------------------------------------------------------------------------

_tl_seq = itertools.count(1)


class SessionTimeline:
    """One decode session's observable life, written by the batcher
    thread (plus the join-side open stamp), finalized into the ring at
    close.  Slotted: the per-token path touches preallocated fields
    only."""

    __slots__ = ("seq", "tier", "tenant", "prompt_len", "max_new",
                 "join_ns", "first_ns", "last_ns", "tokens",
                 "itl_buckets", "itl_max_ns", "prefix", "pages_peak",
                 "spills", "resumes", "preempts", "close_reason",
                 "verdict")

    def __init__(self, tier: str, tenant: str, prompt_len: int,
                 max_new: int, source: str):
        self.seq = next(_tl_seq)
        self.tier = tier
        self.tenant = tenant
        self.prompt_len = prompt_len
        self.max_new = max_new
        self.join_ns = _mono_ns()
        self.first_ns = 0
        self.last_ns = 0
        self.tokens = 0
        self.itl_buckets = [0] * NBUCKETS
        self.itl_max_ns = 0
        self.prefix = source          # fresh|imported, refined at admit
        self.pages_peak = 0
        self.spills = 0
        self.resumes = 0
        self.preempts = 0
        self.close_reason = None
        self.verdict = None

    def ttft_ms(self) -> Optional[float]:
        if not self.first_ns:
            return None
        return (self.first_ns - self.join_ns) / 1e6

    def describe(self) -> dict:
        return {"seq": self.seq, "tier": self.tier,
                "tenant": self.tenant, "prompt_len": self.prompt_len,
                "max_new": self.max_new, "tokens": self.tokens,
                "ttft_ms": self.ttft_ms(),
                "itl_max_ms": self.itl_max_ns / 1e6,
                "prefix": self.prefix, "pages_peak": self.pages_peak,
                "spills": self.spills, "resumes": self.resumes,
                "preempts": self.preempts,
                "close_reason": self.close_reason,
                "verdict": self.verdict}


# live registry (open → close) + the bounded finished-session ring.
# deque(maxlen) eviction is lock-free; the live dict is mutated by the
# join thread (open) and the batcher thread (close) — both single
# bytecode dict ops, GIL-atomic like the admission counters.
_live_sessions: dict = {}
_ring_max = int(get_flag("lm_timeline_ring", 256))
_ring: deque = deque(maxlen=_ring_max)

# per-tier latency histograms (batcher-thread writes): TTFT observed at
# the first emitted token, ITL per subsequent token
_tier_ttft: dict = {}
_tier_itl: dict = {}


def open_timeline(tier: str, tenant, prompt_len: int, max_new: int,
                  source: str) -> Optional[SessionTimeline]:
    """Called at join (NOT the step loop): allocates the session's
    record and preseeds its tier's histograms."""
    if not _live[0]:
        return None
    from .lm_service import SLO_TIERS
    assert tier in SLO_TIERS, f"unregistered SLO tier: {tier}"
    if tier not in _tier_ttft:
        _tier_ttft[tier] = [0] * NBUCKETS
        _tier_itl[tier] = [0] * NBUCKETS
    if isinstance(tenant, (bytes, bytearray, memoryview)):
        tenant = bytes(tenant).decode("utf-8", "replace")
    tl = SessionTimeline(tier, str(tenant or "-"), int(prompt_len),
                         int(max_new), source)
    _live_sessions[tl.seq] = tl
    return tl


def on_emit(pairs) -> None:
    """Per-step token timing (batcher thread only): ONE monotonic read
    for the whole step, then plain list increments per token — the
    first token closes the session's TTFT, later ones feed its ITL
    histogram and the tier aggregate.  Lock-free, allocation-free."""
    if not _live[0] or not pairs:
        return
    now = _mono_ns()
    for sess, _tok in pairs:
        tl = sess.tl
        if tl is None:
            continue
        if tl.tokens == 0:
            tl.first_ns = now
            d = now - tl.join_ns
            b = d.bit_length() if d > 0 else 0
            if b >= NBUCKETS:
                b = NBUCKETS - 1
            _tier_ttft[tl.tier][b] += 1
            if sess.span is not None:
                sess.span.annotate("lm_first_token")
        else:
            d = now - tl.last_ns
            if d > tl.itl_max_ns:
                tl.itl_max_ns = d
            b = d.bit_length() if d > 0 else 0
            if b >= NBUCKETS:
                b = NBUCKETS - 1
            tl.itl_buckets[b] += 1
            _tier_itl[tl.tier][b] += 1
        tl.last_ns = now
        tl.tokens += 1


def close_timeline(tl: Optional[SessionTimeline], reason: str,
                   ttft_target_ms=None, itl_target_ms=None) -> None:
    """Finalize a session record (batcher thread): judge the SLO
    verdict against the tier's targets, count it, move the record from
    the live table into the bounded ring."""
    if tl is None:
        return
    _live_sessions.pop(tl.seq, None)
    tl.close_reason = reason or "finished"
    if ttft_target_ms is None and itl_target_ms is None:
        v = "slo_untargeted"
    else:
        ttft = tl.ttft_ms()
        if ttft_target_ms is not None \
                and (ttft is None or ttft > ttft_target_ms):
            v = "slo_ttft_miss"
        elif itl_target_ms is not None \
                and tl.itl_max_ns / 1e6 > itl_target_ms:
            v = "slo_itl_miss"
        else:
            v = "slo_ok"
    tl.verdict = v
    count_slo(tl.tier, v)
    _ring.append(tl)


def live_sessions() -> list:
    """Snapshot of in-flight sessions (the /lm live table)."""
    return [tl.describe() for tl in list(_live_sessions.values())]


def timeline_records(limit: int = 0) -> list:
    recs = list(_ring)
    if limit:
        recs = recs[-limit:]
    return [tl.describe() for tl in recs]


def ring_len() -> int:
    return len(_ring)


def ring_maxlen() -> int:
    return _ring.maxlen or 0


# ---------------------------------------------------------------------------
# Percentiles from the log2 histograms
# ---------------------------------------------------------------------------

_QUANTILES = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))


def _hist_quantile_ms(buckets, q: float) -> float:
    """Approximate quantile from a log2 ns histogram: the upper bound
    of the bucket where the cumulative count crosses q (conservative —
    never under-reports a latency)."""
    n = 0
    for c in buckets:
        n += c
    if n == 0:
        return 0.0
    target = q * n
    acc = 0.0
    for i, c in enumerate(buckets):
        acc += c
        if acc >= target:
            return 0.0 if i == 0 else (1 << i) / 1e6
    return (1 << (len(buckets) - 1)) / 1e6


def _ttft_rows() -> dict:
    out = {}
    for tier, h in _tier_ttft.items():
        for name, q in _QUANTILES:
            out[(tier, name)] = round(_hist_quantile_ms(h, q), 3)
    return out


def _itl_rows() -> dict:
    out = {}
    for tier, h in _tier_itl.items():
        for name, q in _QUANTILES:
            out[(tier, name)] = round(_hist_quantile_ms(h, q), 3)
    return out


# ---------------------------------------------------------------------------
# Snapshot cache (the _TelemetryCache pattern): one build per interval
# ---------------------------------------------------------------------------

class LmTelemetryCache:
    """Short-TTL cache over the full serving-plane snapshot.  ``get()``
    refreshes at most once per TTL; ``window()`` returns
    ``(prev, cur, dt)`` under ONE lock hold so windowed ratios never
    pair a snapshot with the wrong interval.  ``builds`` counts actual
    snapshot constructions — the one-snapshot-per-interval test pin."""

    def __init__(self, ttl_s: float = 0.25):
        self._ttl = ttl_s
        self._lock = threading.Lock()
        self._snap = None
        self._t = 0.0
        self._prev = None
        self._prev_t = 0.0
        self.builds = 0

    def _build(self) -> dict:
        self.builds += 1
        from .lm_service import sched_counters, spec_counters
        try:
            from ..kv.pages import prefix_event_counters
            prefix = prefix_event_counters()
        except Exception:
            prefix = {}
        return {
            "phases": phase_counters(),
            "phase_ns": phase_total_ns(),
            "phase_hists": {p: list(_phase_buckets[i])
                            for i, p in enumerate(LM_STEP_PHASES)},
            "sched": sched_counters(),
            "spec": spec_counters(),
            "prefix_events": prefix,
            "slo": slo_counters(),
            "ttft_ms": _ttft_rows(),
            "itl_ms": _itl_rows(),
            "live": live_sessions(),
            "ring": timeline_records(),
        }

    def _refresh_locked(self) -> None:
        now = _mono_s()
        if self._snap is None or now - self._t >= self._ttl:
            snap = self._build()
            self._prev, self._prev_t = self._snap, self._t
            self._snap, self._t = snap, now

    def get(self) -> dict:
        with self._lock:
            self._refresh_locked()
            return self._snap

    def window(self):
        with self._lock:
            self._refresh_locked()
            return (self._prev, self._snap,
                    max(self._t - self._prev_t, 1e-9))


_cache: Optional[LmTelemetryCache] = None
_cache_lock = threading.Lock()


def telemetry_cache() -> LmTelemetryCache:
    global _cache
    with _cache_lock:
        if _cache is None:
            _cache = LmTelemetryCache()
        return _cache


def _delta(cur: dict, prev, key: str) -> int:
    c = cur.get(key, 0)
    return c - prev.get(key, 0) if prev is not None else c


def windowed_spec_accept_rate(cache=None) -> float:
    """Accepted/proposed draft tokens over the LAST snapshot window —
    the /vars answer to 'how is acceptance NOW', vs the lifetime
    cumulative ``spec_accept_rate`` the bench/perf_guard keep."""
    prev, cur, _dt = (cache or telemetry_cache()).window()
    p = prev["spec"] if prev is not None else None
    acc = _delta(cur["spec"], p, "spec_accept")
    rej = _delta(cur["spec"], p, "spec_reject")
    denom = acc + rej
    return acc / denom if denom > 0 else 0.0


def windowed_prefix_hit_ratio(cache=None) -> float:
    """(hit + partial) / lookups over the LAST snapshot window."""
    prev, cur, _dt = (cache or telemetry_cache()).window()
    p = prev["prefix_events"] if prev is not None else None
    hit = _delta(cur["prefix_events"], p, "prefix_hit")
    part = _delta(cur["prefix_events"], p, "prefix_partial_hit")
    miss = _delta(cur["prefix_events"], p, "prefix_miss")
    denom = hit + part + miss
    return (hit + part) / denom if denom > 0 else 0.0


def windowed_slo_deltas(cache=None) -> dict:
    """Per-tier SLO attainment DELTAS over the last snapshot window,
    as ``{tier: {verdict: count}}`` — the fleet load report's answer
    to 'how is this node attaining NOW' (lifetime counters drift
    toward their historical mean and stop moving under incidents)."""
    prev, cur, _dt = (cache or telemetry_cache()).window()
    p = prev["slo"] if prev is not None else None
    out: dict = {}
    for (tier, verdict), n in cur["slo"].items():
        d = n - p.get((tier, verdict), 0) if p is not None else n
        if d:
            out.setdefault(tier, {})[verdict] = d
    return out


def lifetime_spec_accept_rate() -> float:
    """The cumulative ratio (perf_guard continuity — the windowed
    variant above is what /vars shows)."""
    from .lm_service import spec_counters
    c = spec_counters()
    denom = c["spec_accept"] + c["spec_reject"]
    return c["spec_accept"] / denom if denom > 0 else 0.0


def lifetime_prefix_hit_ratio() -> float:
    try:
        from ..kv.pages import prefix_event_counters
        c = prefix_event_counters()
    except Exception:
        return 0.0
    denom = c.get("prefix_hit", 0) + c.get("prefix_partial_hit", 0) \
        + c.get("prefix_miss", 0)
    return (c.get("prefix_hit", 0) + c.get("prefix_partial_hit", 0)) \
        / denom if denom > 0 else 0.0


# ---------------------------------------------------------------------------
# /vars + /metrics exposure (PassiveDimension rows share the module's
# plain counters; the portal page additionally reads the cache)
# ---------------------------------------------------------------------------

_phase_var = PassiveDimension(("phase",), phase_counters,
                              name="lm_step_phase_total")
_phase_ns_var = PassiveDimension(("phase",), phase_total_ns,
                                 name="lm_step_phase_ns_total")


def _phase_bucket_rows() -> dict:
    out = {}
    for i, p in enumerate(LM_STEP_PHASES):
        for b, c in enumerate(_phase_buckets[i]):
            if c:
                out[(p, bucket_label(b))] = c
    return out


_phase_hist_var = PassiveDimension(("phase", "bin"), _phase_bucket_rows,
                                   name="lm_step_phase_ns")
_slo_var = PassiveDimension(("tier", "verdict"), slo_counters,
                            name="lm_slo_attained_total")
_ttft_var = PassiveDimension(("tier", "quantile"), _ttft_rows,
                             name="lm_ttft_ms")
_itl_var = PassiveDimension(("tier", "quantile"), _itl_rows,
                            name="lm_itl_ms")
_windowed_var = PassiveDimension(
    ("ratio",),
    lambda: {"spec_accept_rate": round(windowed_spec_accept_rate(), 4),
             "prefix_cache_hit_ratio":
                 round(windowed_prefix_hit_ratio(), 4)},
    name="lm_windowed")

_LM_VARS = (
    (_phase_var, "lm_step_phase_total"),
    (_phase_ns_var, "lm_step_phase_ns_total"),
    (_phase_hist_var, "lm_step_phase_ns"),
    (_slo_var, "lm_slo_attained_total"),
    (_ttft_var, "lm_ttft_ms"),
    (_itl_var, "lm_itl_ms"),
    (_windowed_var, "lm_windowed"),
)


def expose_lm_variables() -> None:
    """(Re-)expose the serving-plane families — the
    ``expose_default_variables`` discipline: a test registry reset
    must not silently drop the /metrics rows for the rest of the
    process lifetime (``Variable.expose`` is a no-op while the name
    is still registered)."""
    for var, name in _LM_VARS:
        var.expose(name)


def _reset_for_tests(ring: Optional[int] = None) -> None:
    global _ring, _cache
    for i in range(_NPHASES):
        _phase_count[i] = 0
        _phase_total_ns[i] = 0
        for b in range(NBUCKETS):
            _phase_buckets[i][b] = 0
    _slo_table()
    for k in _slo:
        _slo[k] = 0
    _tier_ttft.clear()
    _tier_itl.clear()
    _live_sessions.clear()
    _ring = deque(maxlen=int(ring) if ring else _ring_max)
    _cache = None
    expose_lm_variables()
