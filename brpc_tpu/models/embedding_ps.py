"""EmbeddingPS — the flagship workload: sharded embedding parameter
server with a dense scoring tower.

TPU-first design (SURVEY.md §7 step 6): the embedding table is
vocab-partitioned across the mesh's model axis (the PartitionChannel idea
— key-space sharding — expressed as a NamedSharding instead of N
sockets); batches are data-parallel; the dense tower is tensor-parallel.
XLA inserts the ICI collectives for the sharded gather and the gradient
psum — no hand-written scatter/gather RPCs in the hot path.

Mesh axes:
- ``dp``: data parallel (batch dim)
- ``tp``: model parallel (vocab rows of the table = embedding/expert
  parallelism; hidden dim of the tower = tensor parallelism)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class PSConfig:
    vocab: int = 65536
    dim: int = 128
    slots: int = 16           # lookup ids per example
    hidden: int = 512
    classes: int = 16
    lr: float = 0.05


def init_params(rng, cfg: PSConfig) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    k_emb, k1, k2 = jax.random.split(rng, 3)
    scale = 1.0 / (cfg.dim ** 0.5)
    return {
        "emb": jax.random.normal(k_emb, (cfg.vocab, cfg.dim),
                                 jnp.float32) * scale,
        "w1": jax.random.normal(k1, (cfg.dim, cfg.hidden),
                                jnp.float32) * (1.0 / cfg.dim ** 0.5),
        "b1": jnp.zeros((cfg.hidden,), jnp.float32),
        "w2": jax.random.normal(k2, (cfg.hidden, cfg.classes),
                                jnp.float32) * (1.0 / cfg.hidden ** 0.5),
        "b2": jnp.zeros((cfg.classes,), jnp.float32),
    }


def forward(params: Dict[str, Any], ids):
    """ids (batch, slots) int32 → logits (batch, classes). bf16 matmuls
    feed the MXU; f32 master weights."""
    import jax.numpy as jnp

    emb = jnp.take(params["emb"], ids, axis=0)       # sharded gather
    x = emb.mean(axis=1)
    xb = x.astype(jnp.bfloat16)
    h = jnp.maximum(
        (xb @ params["w1"].astype(jnp.bfloat16)).astype(jnp.float32)
        + params["b1"], 0.0)
    logits = (h.astype(jnp.bfloat16)
              @ params["w2"].astype(jnp.bfloat16)).astype(jnp.float32) \
        + params["b2"]
    return logits


def loss_fn(params, ids, labels):
    import jax
    import jax.numpy as jnp

    logits = forward(params, ids)
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, labels[:, None], axis=1).mean()


def sgd_train_step(params, ids, labels, lr: float):
    """One SGD step. Pure + jittable; under a mesh, gradient psum over dp
    is inserted by XLA from the shardings."""
    import jax

    loss, grads = jax.value_and_grad(loss_fn)(params, ids, labels)
    new_params = jax.tree_util.tree_map(
        lambda p, g: p - lr * g, params, grads)
    return new_params, loss


def param_specs(cfg: PSConfig):
    """PartitionSpecs for the ('dp','tp') mesh."""
    from jax.sharding import PartitionSpec as P

    return {
        "emb": P("tp", None),     # vocab-partitioned (ep-style)
        "w1": P(None, "tp"),      # tower tensor-parallel
        "b1": P("tp"),
        "w2": P("tp", None),
        "b2": P(),
    }


def batch_specs():
    from jax.sharding import PartitionSpec as P

    return P("dp", None), P("dp")


class EmbeddingPS:
    """Convenience wrapper binding config + params (+ optional mesh)."""

    def __init__(self, cfg: Optional[PSConfig] = None, mesh=None,
                 seed: int = 0):
        import jax

        self.cfg = cfg or PSConfig()
        self.mesh = mesh
        self.params = init_params(jax.random.PRNGKey(seed), self.cfg)
        if mesh is not None:
            from jax.sharding import NamedSharding

            shardings = {k: NamedSharding(mesh, s)
                         for k, s in param_specs(self.cfg).items()}
            self.params = {k: jax.device_put(v, shardings[k])
                           for k, v in self.params.items()}
        self._fwd = jax.jit(forward)
        self._step = jax.jit(sgd_train_step, static_argnames=("lr",),
                             donate_argnums=(0,))

    def lookup(self, ids):
        """Serve path: embedding-bag only (the PS read RPC)."""
        from ..ops.device_ops import embedding_bag

        return embedding_bag(self.params["emb"], ids)

    def predict(self, ids):
        return self._fwd(self.params, ids)

    def train_step(self, ids, labels) -> float:
        self.params, loss = self._step(self.params, ids, labels,
                                       lr=self.cfg.lr)
        return float(loss)

    def shard_batch(self, ids, labels):
        if self.mesh is None:
            return ids, labels
        import jax
        from jax.sharding import NamedSharding

        ids_spec, lbl_spec = batch_specs()
        return (jax.device_put(ids, NamedSharding(self.mesh, ids_spec)),
                jax.device_put(labels, NamedSharding(self.mesh, lbl_spec)))
