"""Model families served by the framework.

The reference is an RPC framework, not an ML system — its "models" are
services (echo, redis, media). The TPU build's north star
(BASELINE.md) is parameter-server / embedding-lookup services running
inside a pod, so the flagship model family is a sharded embedding table +
dense tower, exposed both as jittable train/serve steps and as an RPC
service moving tensors in attachments.
"""

from .embedding_ps import PSConfig, EmbeddingPS
from .moe import MoEConfig
from .transformer_lm import (LMConfig, batch_specs, generate,
                             init_params, make_decode, make_forward,
                             make_train_step, param_specs)

__all__ = ["PSConfig", "EmbeddingPS", "LMConfig", "MoEConfig",
           "init_params", "make_forward", "make_train_step",
           "make_decode", "generate", "param_specs", "batch_specs"]
