"""Mixture-of-Experts FFN with expert parallelism (ep).

The third flagship model family next to EmbeddingPS (sparse lookup) and
TransformerLM (dense compute): sparse *compute*, where each token visits
only ``k`` of E expert FFNs and experts shard over an ``ep`` mesh axis.

TPU-first design (the reference has no MoE; its ep analogue is
partitioned services — ``DynamicPartitionChannel`` routing a request to
the shard that owns it, /root/reference/src/brpc/partition_channel.h):

- **static shapes**: capacity-factor routing — each expert processes a
  fixed ``C = ceil(k * T / E * capacity)`` token slots; overflow tokens
  are dropped (their residual passes through), so nothing in the traced
  program is data-dependent and XLA can tile every einsum on the MXU;
- **dispatch/combine as einsums** (the Mesh-TensorFlow formulation):
  a (T, E, C) one-hot dispatch tensor gathers token slots, expert FFNs
  run batched as (E, C, d) einsums, and the combine einsum scatters
  results back weighted by router probabilities;
- **expert parallelism by sharding, not message passing**: expert
  weights carry ``P("ep", ...)`` specs; under ``jit`` over a mesh XLA
  inserts the all_to_all/all_gather collectives that move token slots
  onto the devices owning their experts (ICI, not host);
- router in fp32 (numerics), expert matmuls in bf16 (MXU);
- **grouped routing** (GShard): :func:`forward_grouped` routes within
  fixed-size groups, so dispatch memory is linear in total tokens and
  the routing cumsum never crosses a dp shard boundary (groups align
  with the data-parallel batch dim).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple


class MoEConfig:
    def __init__(self, dim: int = 64, hidden: int = 128,
                 num_experts: int = 4, capacity_factor: float = 1.5,
                 aux_loss_weight: float = 0.01, top_k: int = 1):
        assert 1 <= top_k <= num_experts
        self.dim = dim
        self.hidden = hidden
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight
        # top_k=1 is Switch-style routing; top_k=2 the GShard/Mixtral
        # configuration (each token visits its k best experts, outputs
        # mixed by the renormalized router probabilities)
        self.top_k = top_k

    def capacity(self, tokens: int) -> int:
        c = math.ceil(tokens * self.top_k / self.num_experts
                      * self.capacity_factor)
        return max(1, c)


def init_params(rng, cfg: MoEConfig) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    kg, k1, k2 = jax.random.split(rng, 3)
    scale = 1.0 / math.sqrt(cfg.dim)
    return {
        "wg": jax.random.normal(kg, (cfg.dim, cfg.num_experts),
                                jnp.float32) * scale,
        "w1": jax.random.normal(k1, (cfg.num_experts, cfg.dim, cfg.hidden),
                                jnp.float32) * scale,
        "w2": jax.random.normal(k2, (cfg.num_experts, cfg.hidden, cfg.dim),
                                jnp.float32) * (scale / 2),
    }


def param_specs(cfg: MoEConfig, ep_axis: str = "ep") -> Dict[str, Any]:
    """PartitionSpecs: experts shard over the ep axis, router replicated."""
    from jax.sharding import PartitionSpec as P

    return {
        "wg": P(None, None),
        "w1": P(ep_axis, None, None),
        "w2": P(ep_axis, None, None),
    }


def forward(params: Dict[str, Any], x, cfg: MoEConfig
            ) -> Tuple[Any, Any]:
    """MoE FFN: x (T, d) -> (out (T, d), aux_loss ()).

    Top-k routing with capacity; each of a token's k expert slots is
    dispatched as its own "slot token", outputs mix back weighted by
    the renormalized router probabilities.  Dropped slots contribute
    zero (the caller's residual carries the token through)."""
    import jax
    import jax.numpy as jnp

    T, d = x.shape
    E = cfg.num_experts
    K = cfg.top_k
    C = cfg.capacity(T)

    logits = x @ params["wg"]                      # (T, E) fp32 router
    probs = jax.nn.softmax(logits, axis=-1)
    topv, tope = jax.lax.top_k(probs, K)           # (T, K)
    if K > 1:
        # renormalize over the selected experts (Mixtral-style mixing);
        # K=1 keeps the raw router prob as the scale (Switch style —
        # renormalizing would pin the gate to 1.0 and starve the router
        # of gate gradients)
        topv = topv / jnp.maximum(topv.sum(axis=-1, keepdims=True), 1e-9)
    # capacity positions are assigned over (choice-major) slots so every
    # token's FIRST choice queues ahead of all second choices; since a
    # token's K chosen experts are distinct, its slots never collide and
    # the K per-choice masks fold into ONE (T, E, C) dispatch/combine —
    # the Mesh-TF formulation, keeping every einsum at T rows
    slot_expert = tope.transpose(1, 0).reshape(K * T)          # (K*T,)
    slot_onehot = jax.nn.one_hot(slot_expert, E,
                                 dtype=jnp.int32)              # (K*T, E)
    pos = jnp.cumsum(slot_onehot, axis=0) * slot_onehot - 1    # (K*T, E)
    pos_in_expert = pos.max(axis=1)                            # (K*T,)
    kept = pos_in_expert < C                                   # drop tail

    dispatch = jnp.zeros((T, E, C), x.dtype)      # slot indicator
    combine = jnp.zeros((T, E, C), x.dtype)       # gate-weighted
    for k in range(K):                            # static unroll, K small
        sl = slice(k * T, (k + 1) * T)
        # slot_onehot[sl] IS one_hot(tope[:, k]) in choice-major layout
        mask_k = (slot_onehot[sl].astype(x.dtype)[:, :, None]
                  * jax.nn.one_hot(jnp.clip(pos_in_expert[sl], 0, C - 1),
                                   C, dtype=x.dtype)[:, None, :]
                  * kept[sl][:, None, None].astype(x.dtype))
        dispatch = dispatch + mask_k
        combine = combine + mask_k * topv[:, k].astype(
            x.dtype)[:, None, None]

    # gather token slots, run every expert as one batched bf16 einsum
    expert_in = jnp.einsum("td,tec->ecd", x.astype(jnp.bfloat16),
                           dispatch.astype(jnp.bfloat16))     # (E, C, d)
    h = jnp.einsum("ecd,edh->ech", expert_in,
                   params["w1"].astype(jnp.bfloat16))
    h = jax.nn.gelu(h.astype(jnp.float32)).astype(jnp.bfloat16)
    expert_out = jnp.einsum("ech,ehd->ecd", h,
                            params["w2"].astype(jnp.bfloat16))

    # scatter back, weighted by the (renormalized) router probability
    out = jnp.einsum("ecd,tec->td", expert_out.astype(x.dtype), combine)

    # load-balancing aux loss (Switch Transformer): fraction of FIRST-
    # choice assignments per expert x mean router prob, scaled by E
    frac = jnp.mean(slot_onehot[:T].astype(jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * mean_prob) * cfg.aux_loss_weight
    return out, aux


def forward_grouped(params: Dict[str, Any], x, cfg: MoEConfig
                    ) -> Tuple[Any, Any]:
    """Grouped MoE: x (G, N, d) -> (out (G, N, d), aux ()).

    Routes each group of N tokens independently (capacity per group),
    so the (N, E, C) dispatch tensors stay linear in total tokens and —
    when G is the dp-sharded batch dim — routing is local to each data
    shard (no cross-replica cumsum).  This is the form the transformer
    block uses; plain :func:`forward` is the single-group case."""
    import jax

    out, aux = jax.vmap(lambda xg: forward(params, xg, cfg))(x)
    return out, aux.mean()


def make_train_step(cfg: MoEConfig, lr: float = 0.1):
    """(params, x, target) -> (new_params, loss): regression toy task
    exercising routing + expert grads end to end."""
    import jax
    import jax.numpy as jnp

    def loss_fn(params, x, target):
        out, aux = forward(params, x, cfg)
        return jnp.mean((out - target) ** 2) + aux

    def step(params, x, target):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, target)
        new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                            params, grads)
        return new_params, loss

    return step
