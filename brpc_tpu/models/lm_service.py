"""LM serving over the framework — autoregressive generation as an RPC.

The capstone wiring: the TransformerLM's KV-cache decode path
(``make_decode``/``generate``) behind a Service, so a Channel client
(or grpc/HTTP through the bridges) asks for completions the way it
would ask any brpc-style service.  The reference's analogue is its
model-serving example services; here the "model" is an actual LM.

Wire format (framework control plane is schema-free TLV; payloads are
the service's own): request = ``<u32 batch><u32 prompt_len>
<u32 max_new>`` + int32 prompt ids; response = int32 generated ids,
shape (batch, max_new).
"""

from __future__ import annotations

import struct
import threading
from collections import deque
from time import monotonic_ns as _mono_ns
from typing import Optional

import numpy as np

from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..bvar.multi_dimension import PassiveDimension
from ..server.admission import _MAX_TENANTS, normalize_tenant
from ..server.service import Service
from . import lm_telemetry as _lmt
from .lm_telemetry import (PH_CATCHUP_SLICE, PH_CHUNK_SLICE,
                           PH_DECODE_ROUND, PH_HOST_RESUME,
                           PH_HOST_SPILL, PH_PAGE_ALLOC,
                           PH_PREFIX_LOOKUP, PH_SPEC_DRAFT,
                           PH_SPEC_VERIFY, PH_STREAM_EMIT)
from .lm_telemetry import record_phase as _rec_phase
from .transformer_lm import LMConfig, init_params


def pack_generate_request(prompt: np.ndarray, max_new: int) -> bytes:
    prompt = np.ascontiguousarray(prompt, dtype=np.int32)
    b, s = prompt.shape
    return struct.pack("<III", b, s, max_new) + prompt.tobytes()


def unpack_generated(data: bytes) -> np.ndarray:
    b, n = struct.unpack_from("<II", data)
    return np.frombuffer(data, dtype=np.int32, offset=8).reshape(b, n)


def unpack_token(chunk) -> int:
    """One streamed decode token (the ``Decode`` chunk wire format:
    int32 little-endian per token per step)."""
    (tok,) = struct.unpack("<i", bytes(chunk))
    return tok


# -- SLO tiers ---------------------------------------------------------------

# Per-tenant latency classes the batcher schedules by.  Rank = index:
# lower ranks win the chunk budget, drain first from pending, and are
# spilled LAST under pool pressure.
SLO_TIERS = ("interactive", "standard", "batch")
_TIER_RANK = {t: i for i, t in enumerate(SLO_TIERS)}
_RANK_BATCH = _TIER_RANK["batch"]


class TierRegistry:
    """Tenant → SLO tier, keyed on the SAME normalized TLV-22 identity
    the admission plane uses (``normalize_tenant``) so one tenant name
    means one thing across fair admission and the batch scheduler.
    Unregistered tenants get the default tier.  Bounded at the
    admission plane's tenant cardinality cap — an operator config
    table, not an unbounded per-request map."""

    def __init__(self, default: str = "standard"):
        if default not in SLO_TIERS:
            raise ValueError(f"unknown SLO tier: {default}")
        self._default = default
        self._map: dict = {}
        self._slo: dict = {}       # tier -> (ttft_ms, itl_ms) targets
        self._lock = threading.Lock()

    def set_tier(self, tenant, tier: str) -> None:
        if tier not in SLO_TIERS:
            raise ValueError(f"unknown SLO tier: {tier}")
        key = normalize_tenant(tenant)
        with self._lock:
            if key not in self._map and len(self._map) >= _MAX_TENANTS:
                raise ValueError("tier registry full")
            self._map[key] = tier

    def tier_of(self, tenant) -> str:
        with self._lock:
            return self._map.get(normalize_tenant(tenant),
                                 self._default)

    def rank_of(self, tenant) -> int:
        return _TIER_RANK[self.tier_of(tenant)]

    def set_slo(self, tier: str, ttft_ms: Optional[float] = None,
                itl_ms: Optional[float] = None) -> None:
        """Per-tier latency targets the SLO attainment verdicts
        (``lm_telemetry.LM_SLO_VERDICTS``) are judged against at
        session close.  A tier with no targets judges
        ``slo_untargeted``."""
        if tier not in SLO_TIERS:
            raise ValueError(f"unknown SLO tier: {tier}")
        with self._lock:
            self._slo[tier] = (ttft_ms, itl_ms)

    def slo_of(self, tier: str) -> tuple:
        # deliberately lock-free: the batcher reads targets while
        # finalizing a session inside its loop, and a dict.get of an
        # immutable tuple is GIL-atomic
        return self._slo.get(tier, (None, None))


# CLOSED enums (tools/check/enums.py pins every member to a test): the
# scheduler's named decisions and the spec-decode round outcomes.
# count_* assert membership so an unregistered name fails loudly at the
# first count, not silently in a dashboard.
SLO_SCHED_EVENTS = (
    "sched_chunk_slice",        # one bounded prefill slice ran
    "sched_catchup_slice",      # slice replaying past a partial prefix hit
    "sched_interactive_first",  # interactive outranked lower tiers for budget
    "sched_preempt_batch",      # batch-tier victim spilled under pressure
)

SPEC_DECODE_EVENTS = (
    "spec_round",               # one draft+verify round ran
    "spec_accept",              # draft token confirmed by the target
    "spec_reject",              # draft token refuted by the target
    "spec_fallback_plain",      # round fell back to one plain step
)

_sched_lock = threading.Lock()
_sched = {r: 0 for r in SLO_SCHED_EVENTS}
_spec = {r: 0 for r in SPEC_DECODE_EVENTS}


def count_sched(event: str, n: int = 1) -> None:
    assert event in _sched, f"unregistered scheduler event: {event}"
    with _sched_lock:
        _sched[event] += n


def count_spec(event: str, n: int = 1) -> None:
    assert event in _spec, f"unregistered spec-decode event: {event}"
    with _sched_lock:
        _spec[event] += n


def sched_counters() -> dict:
    with _sched_lock:
        return dict(_sched)


def spec_counters() -> dict:
    with _sched_lock:
        return dict(_spec)


def _reset_sched_for_tests() -> None:
    with _sched_lock:
        for k in _sched:
            _sched[k] = 0
        for k in _spec:
            _spec[k] = 0


_sched_var = PassiveDimension(("event",), lambda: sched_counters(),
                              name="lm_slo_sched_total")
_spec_var = PassiveDimension(("event",), lambda: spec_counters(),
                             name="lm_spec_decode_total")


class _Session:
    __slots__ = ("stream", "prompt", "max_new", "sent", "slot",
                 "cache1", "ctx_len", "last_token",
                 # SLO scheduling: resolved tier + rank, and the
                 # chunked-prefill fill watermark (context positions
                 # written so far; fill < ctx_len means the session
                 # occupies its slot but is NOT yet decoding)
                 "tier", "tier_rank", "fill",
                 # paged mode (kv/pages allocator): the session's
                 # block-table pages, its prefix-cache aliases, and
                 # its host-tier parking state
                 "pages", "n_alias", "n_priv",
                 "host_handles", "saved_len",
                 # observability: the session's timeline record
                 # (lm_telemetry.SessionTimeline, None when telemetry
                 # is off) and its forced rpcz decode-session span
                 # (None when the request was untraced)
                 "tl", "span")

    def __init__(self, stream, prompt: Optional[np.ndarray],
                 max_new: int):
        self.stream = stream
        self.prompt = prompt
        self.max_new = max_new
        self.sent = 0
        self.slot = -1
        self.tier = "standard"
        self.tier_rank = _TIER_RANK["standard"]
        self.fill = 0
        # disaggregated serving (kv/): a session whose prefill ran on
        # ANOTHER tier joins with its imported per-layer caches instead
        # of a prompt — the batcher inserts them into a slot between
        # steps exactly like a local prefill's
        self.cache1 = None
        self.ctx_len = 0
        self.last_token = 0
        # paged mode: block-table pages this session HOLDS (one ref
        # each; the first n_alias are prefix-cache aliases, the next
        # n_priv private), and the host-tier handles while parked
        self.pages: list = []
        self.n_alias = 0
        self.n_priv = 0
        self.host_handles = None
        self.saved_len = 0
        self.tl = None
        self.span = None


def bucketed_prefill(prefill_j, cfg: LMConfig, prompt: np.ndarray):
    """Prompt-CONTEXT prefill (all but the last token), padded to a
    power-of-two bucket — returns ``(cache1, ctx_len)``.  ONE home for
    the bucketing: the continuous batcher's join and the kv prefill
    tier both run exactly this, which is the token-identity contract
    between monolithic and disaggregated serving (the prompt's last
    token then rides the first batch step on whichever tier decodes —
    teacher-forced equivalence, see :meth:`ContinuousBatcher._admit`)."""
    ctx = prompt[:-1]
    bucket = 1
    while bucket < max(len(ctx), 1):
        bucket <<= 1
    bucket = min(bucket, cfg.max_seq)
    padded = np.zeros((bucket,), np.int32)
    padded[:len(ctx)] = ctx
    cache1, _logits = prefill_j(padded[None, :])
    return cache1, len(ctx)


def _contig_insert(cfg: LMConfig):
    """Jittable contiguous-pool slot insert with the pool DONATED: an
    eager .at[].set chain would copy the whole (slots, max_seq, ...)
    pool 2*depth+1 times per join, stalling every live session between
    steps in proportion to pool size.  ONE home for the def — the
    contiguous batcher's cache and the spec-decode DRAFT cache insert
    through exactly this."""

    def _insert(cache, cache1, slot, ctx_len):
        import jax.lax as lax
        cache = dict(cache)
        for i in range(cfg.depth):
            cache[f"k{i}"] = lax.dynamic_update_slice(
                cache[f"k{i}"], cache1[f"k{i}"],
                (slot, 0, 0, 0))
            cache[f"v{i}"] = lax.dynamic_update_slice(
                cache[f"v{i}"], cache1[f"v{i}"],
                (slot, 0, 0, 0))
        cache["len"] = lax.dynamic_update_slice(
            cache["len"], ctx_len[None], (slot,))
        return cache

    return _insert


def _setlen(cache, slot, val):
    """Jittable per-slot ``len`` poke (layout-agnostic: jit re-traces
    per cache pytree, so one def serves paged, contiguous, and the
    spec-decode draft cache)."""
    import jax.lax as lax
    cache = dict(cache)
    cache["len"] = lax.dynamic_update_slice(cache["len"], val[None],
                                            (slot,))
    return cache


class ContinuousBatcher:
    """Continuous-batching decode engine: ONE decode-step loop over a
    fixed pool of session slots.  Per step, every live session advances
    one token and the tokens stream back per session (int32 chunks on
    each session's server stream); NEW sessions are admitted into free
    slots BETWEEN steps (bucketed prefill at batch 1, caches copied
    into the slot, first token emitted by the very next step — that
    write is the time-to-first-token); finished or broken sessions
    evict and free their slot, the stream closing with a NAMED reason.

    This is the fabric-lib serving shape (PAPERS.md): the transport —
    the engine's kind-5 stream lane — batch-writes one step's worth of
    tokens across ALL sessions as one coalesced call, so per-token
    transport cost amortizes exactly like per-token compute does.

    The loop runs on one daemon thread, started lazily at the first
    join and exiting after ``idle_linger_s`` with nothing to serve.

    **Paged mode** (``paged=True``, the kv/pages allocator round): the
    per-slot contiguous cache stripes are replaced by one shared page
    pool per layer plus a per-slot block table, so a session pins only
    ``ctx_len``-rounded pages instead of a ``max_seq`` stripe — the
    slot count decouples from device KV bytes and sessions-per-box
    scales with MEAN context, not max.  Three consequences ride along:

    - a cross-session :class:`~brpc_tpu.kv.pages.PrefixCache` lets a
      re-sent context ALIAS already-prefilled pages (refcounted, zero
      bytes copied) and skip prefill for the covered prefix, any
      remainder caught up through chunked-prefill slices (token
      identity with the uncached path by construction);
    - when the device pool runs dry the batcher first drops LRU
      prefix-cache entries, then SPILLS the fattest live session's
      private pages to the :class:`~brpc_tpu.kv.pages.HostPagePool`
      (one memcpy per page) and parks it; parked sessions resume —
      bit-exact — when pages free up.  Exhaustion beyond that closes
      the admitting stream under a NAMED ``KV_EVICT_REASONS`` member;
    - mid-spill pages are drain-visible: ``Server.drain`` counts them
      (``kv.pages.host_inflight_spills``) and expiry closes parked
      sessions under ``kv_spill_drain_aborted`` instead of leaking.

    **SLO-tiered scheduling** (ROADMAP item 4): the step loop is a
    latency-SLO scheduler over three per-tenant tiers resolved from
    the TLV-22 identity via a :class:`TierRegistry`:

    - **chunked prefill** (``prefill_chunk_tokens``, Sarathi-style):
      each loop round runs ONE decode step plus a bounded budget of
      prefill slices, so a long prompt never head-of-line-blocks live
      sessions' next token.  A joining session occupies its slot
      immediately but stays INACTIVE (``fill < ctx_len``) while chunk
      rounds scatter its context; its first generated token is
      teacher-forced identically to a whole-prompt prefill.  The
      interactive tier spends the budget first;
    - **priority preemption**: pending joins drain interactive-first,
      and under pool pressure the spill victim is chosen
      tier-then-footprint (batch-tier sessions park before standard,
      interactive last) with batch victims taken even BEFORE
      prefix-cache holds when the requester outranks them.  Every
      decision counts under the closed ``SLO_SCHED_EVENTS`` enum;
    - **speculative decoding** (``spec_decode_k``, paged mode): a
      small draft model proposes k tokens per active slot (k cheap
      contiguous steps), the target verifies all of them in ONE
      batched multi-token program, accepted prefixes advance the page
      table and rejections are a pure ``len`` rewind (the refuted
      rows sit beyond the mask and are rewritten before ever being
      admitted) — token identity with plain decode holds on both
      paths.  Acceptance telemetry rides ``SPEC_DECODE_EVENTS``.
    """

    def __init__(self, cfg: LMConfig, params, slots: int = 8,
                 idle_linger_s: float = 5.0, paged: bool = False,
                 page: int = 16, pages: Optional[int] = None,
                 host_slots: int = 0, prefix: bool = True,
                 prefix_budget: Optional[int] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 spec_decode_k: int = 0, draft_params=None,
                 tiers: Optional[TierRegistry] = None):
        self.cfg = cfg
        self.params = params
        self.slots = int(slots)
        self.idle_linger_s = idle_linger_s
        # paged-KV knobs (inert unless paged=True)
        self.paged = bool(paged)
        self.page = int(page)
        self._pps = cfg.max_seq // self.page if self.paged else 0
        # +1: page 0 is the allocator's reserved garbage page
        self.num_pages = int(pages) if pages is not None \
            else self.slots * self._pps + 1
        self.host_slots = int(host_slots)
        self.prefix_enabled = bool(prefix)
        self.prefix_budget = prefix_budget
        # SLO scheduler knobs.  chunk_budget == 0 means chunked
        # prefill is OFF for fresh prompts (legacy whole bucketed
        # prefill) — but a chunk program is still built at _chunk_w:
        # partial prefix-cache hits ALWAYS catch up through chunk
        # slices (round-19 REMAINING thread), budget-unbounded when
        # the scheduler is off.
        self.chunk_budget = int(prefill_chunk_tokens) \
            if prefill_chunk_tokens else 0
        self._chunk_w = min(self.chunk_budget, cfg.max_seq) \
            if self.chunk_budget else min(64, cfg.max_seq)
        self.spec_k = int(spec_decode_k)
        self.draft_params = draft_params
        if self.spec_k > 0 and not self.paged:
            raise ValueError("spec_decode_k requires paged=True "
                             "(rejection rollback is a block-table "
                             "len rewind)")
        if self.spec_k > 0 and draft_params is None:
            raise ValueError("spec_decode_k requires draft_params")
        self.tiers = tiers
        # the HEAVY half (jit wrappers + the device KV-pool allocation)
        # is deferred to the batcher thread's first iteration: the
        # first Decode call runs on an engine loop thread inside the
        # batched GIL entry, and allocating a serving-sized pool there
        # would stall every connection the loop owns
        self._prefill = None
        self._step = None
        self._insert = None
        self._cache = None
        self._tokens = np.zeros((self.slots,), np.int32)
        self._active = np.zeros((self.slots,), bool)
        self._sessions = {}                       # slot -> _Session
        self._pending: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._thread = None
        self._steps = 0                           # decode steps run
        # paged-mode engine state (built in _ensure_engine)
        self._alloc = None                        # kv.pages.PageAllocator
        self._prefix = None                       # kv.pages.PrefixCache
        self._host = None                         # kv.pages.HostPagePool
        self._bt = np.zeros((self.slots, max(self._pps, 1)), np.int32)
        self._gather_j = None
        self._scatter_j = None
        self._setlen_j = None
        self._chunk_j = None                      # chunked prefill slice
        # spec-decode engine state (built when spec_k > 0)
        self._d_prefill = None
        self._d_step = None
        self._d_insert = None
        self._d_cache = None
        self._verify_j = None
        self._d_sync_j = None
        self._parked: list = []                   # spilled sessions
        self.prefills_run = 0
        self.spills = 0
        self.resumes = 0

    # -- public -----------------------------------------------------------

    def join(self, stream, prompt: np.ndarray, max_new: int,
             tenant=None, span=None) -> None:
        """Queue a session; it enters the live batch between steps.
        ``tenant`` (the request's TLV-22 identity, bytes or str)
        resolves the session's SLO tier through the registry.
        ``span`` (optional rpcz Span) is the session's decode-session
        span — the batcher annotates its step events and finishes it
        at evict."""
        sess = _Session(stream, np.ascontiguousarray(prompt, np.int32),
                        int(max_new))
        self._assign_tier(sess, tenant)
        sess.span = span
        sess.tl = _lmt.open_timeline(sess.tier, tenant, len(prompt),
                                     int(max_new), "fresh")
        if span is not None:
            span.annotate("lm_join")
        self._enqueue(sess)

    def _assign_tier(self, sess: _Session, tenant) -> None:
        if self.tiers is not None:
            sess.tier = self.tiers.tier_of(tenant)
            sess.tier_rank = _TIER_RANK[sess.tier]

    def join_imported(self, stream, last_token: int, ctx_len: int,
                      max_new: int, cache1, tenant=None,
                      span=None) -> None:
        """Disaggregated serving (kv/): admit a session whose prefill
        ran on ANOTHER tier.  ``cache1`` is the imported per-layer
        cache dict (``decode_cache_from_pages`` layout, batch 1); it
        drops into a free slot between steps exactly like a local
        prefill's, and the imported last prompt token rides the next
        step — so the token stream is identical with the monolithic
        path by the same teacher-forcing argument as `_admit`'s."""
        sess = _Session(stream, None, int(max_new))
        sess.cache1 = cache1
        sess.ctx_len = int(ctx_len)
        sess.last_token = int(last_token)
        self._assign_tier(sess, tenant)
        sess.span = span
        sess.tl = _lmt.open_timeline(sess.tier, tenant,
                                     int(ctx_len) + 1, int(max_new),
                                     "imported")
        if span is not None:
            span.annotate("lm_join")
        self._enqueue(sess)

    def _enqueue(self, sess: _Session) -> None:
        with self._lock:
            self._pending.append(sess)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="lm-decode-batcher",
                    daemon=True)
                self._thread.start()
        self._wake.set()

    def live_slots(self) -> int:
        with self._lock:
            return len(self._sessions)

    def steps_run(self) -> int:
        return self._steps

    def kv_stats(self) -> dict:
        """Allocator-plane observability (paged mode; minimal shape
        otherwise) — the bench and the capacity tests read this."""
        out = {"paged": self.paged, "steps": self._steps,
               "prefills_run": self.prefills_run,
               "spills": self.spills, "resumes": self.resumes,
               "parked": len(self._parked),
               "sched": sched_counters(), "spec": spec_counters(),
               "phases": _lmt.phase_counters()}
        if self._alloc is not None:
            out["alloc"] = self._alloc.stats()
        if self._prefix is not None:
            out["prefix"] = self._prefix.stats()
        if self._host is not None:
            out["host"] = self._host.stats()
        return out

    # -- internals (batcher thread only past the pending handoff) ---------

    def _ensure_engine(self) -> None:
        """Build the compiled programs + device KV pool, ON the batcher
        thread (see __init__: the constructor must stay cheap enough to
        run inside an engine loop's batched GIL entry)."""
        if self._prefill is not None and self._cache is not None:
            return
        import functools

        import jax

        from .transformer_lm import empty_batch_cache, make_batch_decode

        if self.paged:
            self._ensure_paged_engine()
            return
        if self._prefill is None:
            prefill, step, chunk_step = make_batch_decode(
                self.cfg, chunk=self._chunk_w)
            self._prefill = jax.jit(functools.partial(prefill,
                                                      self.params))
            self._step = jax.jit(functools.partial(step, self.params),
                                 donate_argnums=(0,))
            self._chunk_j = jax.jit(
                functools.partial(chunk_step, self.params),
                donate_argnums=(0,))
            self._insert = jax.jit(_contig_insert(self.cfg),
                                   donate_argnums=(0,))
            self._setlen_j = jax.jit(_setlen, donate_argnums=(0,))
        if self._cache is None:
            self._cache = empty_batch_cache(self.cfg, self.slots)

    def _ensure_paged_engine(self) -> None:
        """Paged-mode engine build: the shared page pools, the block-
        paged step, the page-granular I/O programs, and the allocator /
        prefix-cache / host-tier triple from ``kv.pages``."""
        import functools

        import jax
        import jax.numpy as jnp

        from ..kv.pages import (HostPagePool, PageAllocator,
                                PrefixCache)
        from .transformer_lm import (empty_batch_cache,
                                     empty_paged_cache, make_paged_io,
                                     make_batch_decode,
                                     make_paged_batch_decode,
                                     make_paged_spec_verify,
                                     paged_page_bytes)

        if self._prefill is None:
            prefill, step = make_paged_batch_decode(self.cfg, self.page)
            self._prefill = jax.jit(functools.partial(prefill,
                                                      self.params))
            self._step = jax.jit(functools.partial(step, self.params),
                                 donate_argnums=(0,))
            gather, scatter, insert, chunk_prefill = make_paged_io(
                self.cfg, self.page, chunk=self._chunk_w)
            self._gather_j = jax.jit(gather)
            self._scatter_j = jax.jit(scatter, donate_argnums=(0,))
            self._insert = jax.jit(insert, donate_argnums=(0,))
            self._chunk_j = jax.jit(
                functools.partial(chunk_prefill, self.params),
                donate_argnums=(0,))
            self._setlen_j = jax.jit(_setlen, donate_argnums=(0,))
            if self.spec_k > 0:
                # draft engine: the SMALL model runs k cheap
                # contiguous steps per round; the target verifies all
                # k proposals in one width-(k+1) program.  Draft len
                # sync is a pure arithmetic rewind — after k draft
                # steps len = L + k, the target accepted m, so the
                # draft keeps rows for L..L+m and rewinds k-1-m.
                d_prefill, d_step = make_batch_decode(self.cfg)
                self._d_prefill = jax.jit(functools.partial(
                    d_prefill, self.draft_params))
                self._d_step = jax.jit(functools.partial(
                    d_step, self.draft_params), donate_argnums=(0,))
                self._d_insert = jax.jit(_contig_insert(self.cfg),
                                         donate_argnums=(0,))
                verify = make_paged_spec_verify(self.cfg, self.page,
                                                self.spec_k + 1)
                self._verify_j = jax.jit(
                    functools.partial(verify, self.params),
                    donate_argnums=(0,))
                k = self.spec_k

                def _d_sync(cache, m, active):
                    cache = dict(cache)
                    cache["len"] = jnp.where(
                        active, cache["len"] - (k - 1 - m),
                        cache["len"])
                    return cache

                self._d_sync_j = jax.jit(_d_sync, donate_argnums=(0,))
        if self._cache is None:
            self._cache = empty_paged_cache(self.cfg, self.num_pages,
                                            self.slots, self.page)
            self._bt[:] = 0
        if self.spec_k > 0 and self._d_cache is None:
            self._d_cache = empty_batch_cache(self.cfg, self.slots)
        if self._alloc is None:
            pb = paged_page_bytes(self.cfg, self.page)
            self._alloc = PageAllocator(self.num_pages, self.page, pb)
            self._prefix = PrefixCache(
                self._alloc, budget_pages=self.prefix_budget) \
                if self.prefix_enabled else None
            if self.host_slots > 0 and self._host is None:
                self._host = HostPagePool(self.host_slots, pb)

    def _pages_for(self, ctx_len: int, max_new: int) -> int:
        """Pages a session needs end-to-end: every position it will
        ever write, ctx-ROUNDED — the whole point of paging (vs the
        contiguous pool's unconditional max_seq stripe)."""
        return max(1, -(-(ctx_len + max_new) // self.page))

    # credit wait bound for one step's token writes: a healthy client
    # holds megabytes of window credit per 4-byte token, so a stream
    # that cannot take one token within this is STALLED — and the
    # batcher must never let one stalled client head-of-line-block the
    # whole live batch behind a long write timeout
    EMIT_TIMEOUT_MS = 200

    def _emit(self, pairs) -> list:
        """Write one step's tokens — native-lane streams in ONE
        coalesced engine call per engine (one writev per connection),
        Python-lane ones individually.  Credit waits are bounded by
        EMIT_TIMEOUT_MS so a stalled session costs the batch one short
        stall ONCE and is then evicted — continuous batching must not
        head-of-line-block every live session on one dead client.
        Returns sessions to evict (stream gone or out of credit)."""
        dead = []
        by_engine = {}                 # id(engine) -> (engine, items)
        for sess, tok in pairs:
            s = sess.stream
            if s.closed:
                dead.append((sess, None))
                continue
            data = struct.pack("<i", tok)
            eng = s._native_tx
            if eng is not None:
                # sessions may span servers (multiple engines): group
                # per engine — a sid is only resolvable by its own
                by_engine.setdefault(id(eng), (eng, []))[1].append(
                    (sess, s.id, data))
            else:
                prev = s.options.write_timeout_s
                s.options.write_timeout_s = self.EMIT_TIMEOUT_MS / 1e3
                try:
                    rc = s.write(data)
                finally:
                    s.options.write_timeout_s = prev
                if rc != 0:
                    dead.append((sess, "backpressure" if rc == int(
                        Errno.EOVERCROWDED) else None))
        for eng, items in by_engine.values():
            sts = eng.stream_write_many(
                [(sid, data) for _sess, sid, data in items],
                self.EMIT_TIMEOUT_MS)
            for (sess, _sid, _data), st in zip(items, sts):
                if st == -1:
                    dead.append((sess, "backpressure"))
                elif st == -2:
                    dead.append((sess, None))
        return dead

    def _admit(self, sess: _Session) -> None:
        # Prefill the prompt CONTEXT (all but the last token), padded
        # to a power-of-two bucket so distinct prompt lengths share
        # compiled programs — an unbucketed per-length jit would stall
        # EVERY live session for a fresh XLA compile at each new
        # length.  The prompt's LAST token then rides the next batch
        # step (teacher-forced equivalence: step logits at pos s-1 ==
        # full-prefill last-position logits), which both yields the
        # first generated token and overwrites the padded garbage rows
        # before the mask ever admits them.  A session imported from a
        # prefill tier (kv/ handoff) skips the prefill: its caches
        # arrived as pages and insert the same way.
        if self.paged:
            self._admit_paged(sess)
            return
        import jax.numpy as jnp
        # free = unOCCUPIED, not merely inactive: a chunk-filling
        # session holds its slot while _active is still False
        free = next(i for i in range(self.slots)
                    if i not in self._sessions)
        if sess.cache1 is None and self.chunk_budget \
                and len(sess.prompt) > 1:
            # chunked admit: take the slot now, let _chunk_round
            # scatter the context under the per-step budget; the
            # session activates (and teacher-forces its last prompt
            # token) when fill reaches ctx_len
            self._cache = self._setlen_j(self._cache, jnp.int32(free),
                                         jnp.int32(0))
            sess.ctx_len = len(sess.prompt) - 1
            sess.fill = 0
            sess.slot = free
            sess.sent = 0
            self._sessions[free] = sess
            return
        if sess.cache1 is not None:
            cache1, ctx_len = sess.cache1, sess.ctx_len
            last = int(sess.last_token)
            sess.cache1 = None   # the pool owns the rows after insert
        else:
            cache1, ctx_len = bucketed_prefill(self._prefill, self.cfg,
                                               sess.prompt)
            self.prefills_run += 1
            last = int(sess.prompt[-1])
        self._cache = self._insert(self._cache, cache1,
                                   jnp.int32(free),
                                   jnp.int32(ctx_len))
        sess.ctx_len = ctx_len
        sess.fill = ctx_len      # fully prefilled = active
        self._tokens[free] = last
        self._active[free] = True
        sess.slot = free
        sess.sent = 0            # first token leaves on the next step
        self._sessions[free] = sess

    # -- paged mode: admit / spill / park / resume -------------------------

    def _alloc_with_reclaim(self, need: int, rank: int = 1):
        """Allocate ``need`` pages, reclaiming under pressure in SLO
        order: when the requester outranks the batch tier, spill a
        BATCH-tier victim first (its pages already ride the host
        tier), then drop LRU prefix-cache entries (cheap — redundant
        with a prefill), then spill whatever the tier-then-footprint
        policy picks.  Returns ``(pages, None)`` or ``(None, reason)``
        with the reason a KV_EVICT_REASONS member."""
        pages = self._alloc.alloc(need)
        while pages is None:
            if rank < _RANK_BATCH \
                    and self._spill_one(min_rank=_RANK_BATCH) is None:
                pages = self._alloc.alloc(need)
                continue
            if self._prefix is not None and self._prefix.evict_lru():
                pages = self._alloc.alloc(need)
                continue
            why = self._spill_one()
            if why is not None:
                return None, why
            pages = self._alloc.alloc(need)
        return pages, None

    def _admit_paged(self, sess: _Session) -> None:
        import jax.numpy as jnp

        from ..kv.pages import count_evict
        imported = sess.cache1 is not None
        if imported:
            ctx_len = sess.ctx_len
            aliased, covered = [], 0    # imported manifests carry no
            #                             tokens to fingerprint
        else:
            ctx = sess.prompt[:-1]
            ctx_len = len(ctx)
            if self._prefix is not None:
                t0 = _mono_ns()
                aliased, covered = self._prefix.lookup(ctx)
                _rec_phase(PH_PREFIX_LOOKUP, _mono_ns() - t0)
            else:
                aliased, covered = [], 0
        n_total = self._pages_for(ctx_len, sess.max_new)
        t0 = _mono_ns()
        priv, why = self._alloc_with_reclaim(n_total - len(aliased),
                                             rank=sess.tier_rank)
        _rec_phase(PH_PAGE_ALLOC, _mono_ns() - t0)
        if priv is None:
            for p in aliased:
                self._alloc.release(p)
            count_evict(why)
            if not sess.stream.closed:
                sess.stream.close(reason=why)
            self._finalize_obs(sess, why)
            return
        # free = unOCCUPIED, not merely inactive: a chunk-filling
        # session holds its slot while _active is still False
        free = next(i for i in range(self.slots)
                    if i not in self._sessions)
        n_alias = len(aliased)
        row = np.zeros((self._pps,), np.int32)
        row[:n_alias] = aliased
        row[n_alias:n_total] = priv
        filling = False
        last = 0
        if sess.cache1 is not None:
            # disagg import: blockify the imported contiguous cache
            self._cache = self._insert(self._cache, jnp.asarray(row),
                                       sess.cache1)
            sess.cache1 = None
            last = int(sess.last_token)
            start_len = ctx_len
        elif covered == ctx_len:
            # full prefix hit (or empty context): the aliased pages
            # ARE the covered context's KV (prefill is deterministic —
            # identical values), no prefill and ZERO copies
            last = int(sess.prompt[-1])
            start_len = ctx_len
        elif covered == 0 and not self.chunk_budget:
            cache1, ctx_len = bucketed_prefill(self._prefill, self.cfg,
                                               sess.prompt)
            self.prefills_run += 1
            self._cache = self._insert(self._cache, jnp.asarray(row),
                                       cache1)
            last = int(sess.prompt[-1])
            start_len = ctx_len
            if self._prefix is not None:
                # the context's FULL pages are immutable from here on
                # (decode writes land at pos >= ctx_len) — cache them
                self._prefix.insert(sess.prompt[:-1], priv)
        else:
            # chunked fill: a fresh prompt under the chunk budget, or
            # a PARTIAL prefix hit whose remainder catches up through
            # chunk slices (covered rows are aliased and immutable;
            # slices scatter only private pages from fill onward) —
            # the session holds its slot but stays inactive until
            # _chunk_round completes the context
            filling = True
            sess.fill = covered
            start_len = covered
        self._cache = self._setlen_j(self._cache, jnp.int32(free),
                                     jnp.int32(start_len))
        sess.pages = list(aliased) + list(priv)
        sess.n_alias = n_alias
        sess.n_priv = len(priv)
        sess.ctx_len = ctx_len
        tl = sess.tl
        if tl is not None:
            if not imported:
                tl.prefix = "prefix_hit" if (n_alias and
                                             covered == ctx_len) \
                    else "prefix_partial" if covered > 0 \
                    else "prefix_miss"
            if len(sess.pages) > tl.pages_peak:
                tl.pages_peak = len(sess.pages)
        self._bt[free] = row
        sess.slot = free
        sess.sent = 0
        self._sessions[free] = sess
        if filling:
            return
        sess.fill = ctx_len
        self._tokens[free] = last
        self._active[free] = True
        if self.spec_k > 0:
            self._draft_admit(sess)

    def _spill_one(self, min_rank: int = 0) -> Optional[str]:
        """Park ONE live session's private pages in the host tier.
        Victim choice is TIER-then-footprint: the worst SLO rank
        spills first (batch before standard before interactive — an
        interactive session is never parked while any batch-tier
        victim exists), fattest private footprint within a tier (frees
        the most pages per D2H), deterministic tie-break on slot.
        ``min_rank`` restricts candidates to ranks >= it (used to take
        batch victims before prefix-cache holds).  Returns None on
        success, else the KV_EVICT_REASONS member naming why nothing
        could spill."""
        if self._host is None:
            return "kv_pool_exhausted"
        ab = self._host.abort_reason()
        if ab is not None:
            return ab
        victims = [s for s in self._sessions.values()
                   if s.n_priv > 0 and s.tier_rank >= min_rank]
        if not victims:
            return "kv_pool_exhausted"
        victim = max(victims,
                     key=lambda s: (s.tier_rank, s.n_priv, -s.slot))
        if victim.tier_rank >= _RANK_BATCH:
            count_sched("sched_preempt_batch")
            if victim.tl is not None:
                victim.tl.preempts += 1
        return self._park(victim)

    def _park(self, sess: _Session) -> Optional[str]:
        """Move a live session's private pages device → host and free
        its slot.  Bit-exact resume: everything the step depends on —
        page contents, len, the last fed token, the chunk-fill
        watermark — survives in the session object + host tier."""
        import jax.numpy as jnp
        t0 = _mono_ns()
        if not self._host.begin_spill():
            return self._host.abort_reason() or "kv_host_tier_full"
        handles = []
        try:
            blk = np.asarray(self._gather_j(
                self._cache, jnp.asarray(self._bt[sess.slot])))
            for j in range(sess.n_alias, sess.n_alias + sess.n_priv):
                h = self._host.stage(
                    blk[j].reshape(-1).view(np.uint8))
                if h is None:
                    for hh in handles:
                        self._host.free(hh)
                    return "kv_host_tier_full"
                handles.append(h)
        finally:
            self._host.end_spill()
        sess.host_handles = handles
        sess.saved_len = int(np.asarray(self._cache["len"])[sess.slot])
        sess.last_token = int(self._tokens[sess.slot])
        self._alloc.release_all(sess.pages[sess.n_alias:])
        sess.pages = sess.pages[:sess.n_alias]   # alias holds remain
        self._sessions.pop(sess.slot, None)
        self._active[sess.slot] = False
        self._bt[sess.slot] = 0
        sess.slot = -1
        self._parked.append(sess)
        self.spills += 1
        try:
            from .. import fleet
            fleet.record_event("fleet_host_spill",
                               f"tier={getattr(sess, 'tier', '?')}")
        except Exception:
            pass
        if sess.tl is not None:
            sess.tl.spills += 1
        if sess.span is not None:
            sess.span.annotate("lm_spill")
        _rec_phase(PH_HOST_SPILL, _mono_ns() - t0)
        return None

    def _resume(self, sess: _Session) -> bool:
        """Un-park: re-alloc private pages, land the host bytes back
        (one H2D scatter), rebuild the block-table row, restore len and
        the last fed token.  False = stay parked (no slot or no pages
        yet — never an error)."""
        import jax.numpy as jnp
        free = next((i for i in range(self.slots)
                     if i not in self._sessions), None)
        if free is None:
            return False
        t0 = _mono_ns()
        priv = self._alloc.alloc(sess.n_priv)
        while priv is None:
            # prefix-cache holds are reclaimable — a parked session
            # must never starve behind redundant cached pages
            if self._prefix is not None and self._prefix.evict_lru():
                priv = self._alloc.alloc(sess.n_priv)
                continue
            return False
        hd = self.cfg.dim // self.cfg.heads
        n_alias = sess.n_alias
        n_used = n_alias + sess.n_priv
        # scatter ids: private entries land in their new pages; alias
        # and pad entries point at the garbage page (their contents
        # are already live on device / don't exist)
        ids = np.zeros((self._pps,), np.int32)
        ids[n_alias:n_used] = priv
        blk = np.zeros((self._pps, 2 * self.cfg.depth, self.page,
                        self.cfg.heads, hd), np.float32)
        for j, h in enumerate(sess.host_handles):
            blk[n_alias + j] = self._host.fetch(h).view(
                np.float32).reshape(blk.shape[1:])
            self._host.free(h)
        sess.host_handles = None
        self._cache = self._scatter_j(self._cache, jnp.asarray(ids),
                                      jnp.asarray(blk))
        self._cache = self._setlen_j(self._cache, jnp.int32(free),
                                     jnp.int32(sess.saved_len))
        row = np.zeros((self._pps,), np.int32)
        row[:n_alias] = sess.pages
        row[n_alias:n_used] = priv
        sess.pages = list(sess.pages) + list(priv)
        self._bt[free] = row
        self._tokens[free] = sess.last_token
        # a session parked MID-FILL resumes still inactive and the
        # chunk rounds finish its context; an active one re-enters the
        # decode batch directly
        self._active[free] = sess.fill >= sess.ctx_len
        sess.slot = free
        self._sessions[free] = sess
        if self._active[free] and self.spec_k > 0 \
                and sess.prompt is not None:
            # re-seed the DRAFT context for the resumed slot; rows for
            # already-GENERATED tokens are not replayed, so acceptance
            # dips until the draft re-anchors — correctness is the
            # target's verify either way
            self._draft_admit(sess)
            self._d_cache = self._setlen_j(self._d_cache,
                                           jnp.int32(free),
                                           jnp.int32(sess.saved_len))
        self.resumes += 1
        tl = sess.tl
        if tl is not None:
            tl.resumes += 1
            if len(sess.pages) > tl.pages_peak:
                tl.pages_peak = len(sess.pages)
        if sess.span is not None:
            sess.span.annotate("lm_resume")
        _rec_phase(PH_HOST_RESUME, _mono_ns() - t0)
        return True

    def _drop_parked(self, sess: _Session,
                     reason: Optional[str]) -> None:
        """A parked session that will never resume (stream gone, or
        drain aborted the host tier): free its host slots and alias
        holds, close under the named reason."""
        from ..kv.pages import count_evict
        for h in (sess.host_handles or []):
            try:
                self._host.free(h)
            except Exception:
                pass
        sess.host_handles = None
        self._alloc.release_all(sess.pages)
        sess.pages = []
        if reason is not None:
            count_evict(reason)
        if not sess.stream.closed:
            sess.stream.close(reason=reason or "finished")
        self._finalize_obs(sess, reason or "finished")

    def _service_parked(self) -> None:
        """Between steps: resume whatever fits, discard the dead, and
        — after a drain abort — close everything still parked under
        the named reason."""
        if not self._parked:
            return
        ab = self._host.abort_reason() if self._host is not None \
            else None
        still = []
        # SLO order: interactive parkees resume first (stable within a
        # tier — spill order)
        self._parked.sort(key=lambda s: s.tier_rank)
        for sess in self._parked:
            if sess.stream.closed:
                self._drop_parked(sess, None)
            elif ab is not None:
                self._drop_parked(sess, ab)
            elif not self._resume(sess):
                still.append(sess)
        self._parked = still

    # -- SLO scheduler: chunk rounds, spec rounds, plain rounds ------------

    def _draft_admit(self, sess: _Session) -> None:
        """Seed the DRAFT model's contiguous cache for a newly active
        slot (spec mode).  The draft is small — one bucketed prefill
        here is cheap, and it keeps the draft's rows position-aligned
        with the target's context."""
        if self._d_cache is None or sess.prompt is None:
            return
        import jax.numpy as jnp
        cache1, ctx_len = bucketed_prefill(self._d_prefill, self.cfg,
                                           sess.prompt)
        self._d_cache = self._d_insert(self._d_cache, cache1,
                                       jnp.int32(sess.slot),
                                       jnp.int32(ctx_len))

    def _activate(self, sess: _Session) -> None:
        """A fully chunk-filled session goes live: the prompt's LAST
        token rides the next batch step — the same teacher-forcing as
        a whole-prompt prefill, so the emitted stream is identical by
        construction — and a fresh chunked context enters the prefix
        cache exactly like a prefilled one would."""
        slot = sess.slot
        sess.fill = sess.ctx_len
        self._tokens[slot] = int(sess.prompt[-1])
        self._active[slot] = True
        if sess.n_alias == 0 and sess.ctx_len > 0:
            # a chunk-filled context counts as one prefill (capacity
            # accounting); prefix-hit catch-up does NOT — the hit
            # avoided it
            self.prefills_run += 1
            if self.paged and self._prefix is not None:
                self._prefix.insert(sess.prompt[:-1],
                                    sess.pages[sess.n_alias:])
        if self.spec_k > 0:
            self._draft_admit(sess)

    def _chunk_round(self) -> None:
        """Spend this round's chunk budget: bounded prefill slices
        over the chunk-filling sessions, INTERACTIVE tier first — the
        Sarathi-style half of the step loop (each round = one decode
        step + at most ``prefill_chunk_tokens`` of prefill work), so a
        long prompt costs live sessions one bounded slice per token
        instead of a whole prefill.  Safe interleaving is the pooled
        garbage-beyond-mask argument: a filling slot's rows beyond
        ``fill`` are junk, but the attention mask admits a row only
        once ``len`` passes it, and every admissible row has been
        rewritten by a slice first."""
        filling = [s for s in self._sessions.values()
                   if s.fill < s.ctx_len]
        if not filling:
            return
        import jax.numpy as jnp
        filling.sort(key=lambda s: (s.tier_rank, s.slot))
        if filling[0].tier_rank == _TIER_RANK["interactive"] \
                and any(s.tier_rank > filling[0].tier_rank
                        for s in filling):
            count_sched("sched_interactive_first")
        budget = self.chunk_budget if self.chunk_budget else (1 << 30)
        for sess in filling:
            if budget <= 0:
                break
            if sess.stream.closed:
                self._evict(sess, None)
                continue
            catchup = sess.n_alias > 0
            while budget > 0 and sess.fill < sess.ctx_len:
                t0 = _mono_ns()
                n = int(min(self._chunk_w, sess.ctx_len - sess.fill,
                            budget))
                ids = np.zeros((self._chunk_w,), np.int32)
                ids[:n] = sess.prompt[sess.fill:sess.fill + n]
                if self.paged:
                    self._cache = self._chunk_j(
                        self._cache, jnp.asarray(self._bt[sess.slot]),
                        jnp.int32(sess.slot), jnp.int32(sess.fill),
                        jnp.int32(n), jnp.asarray(ids))
                else:
                    self._cache = self._chunk_j(
                        self._cache, jnp.int32(sess.slot),
                        jnp.int32(sess.fill), jnp.int32(n),
                        jnp.asarray(ids))
                sess.fill += n
                budget -= n
                count_sched("sched_catchup_slice" if catchup
                            else "sched_chunk_slice")
                _rec_phase(PH_CATCHUP_SLICE if catchup
                           else PH_CHUNK_SLICE, _mono_ns() - t0)
                if sess.span is not None:
                    sess.span.annotate("lm_chunk_slice")
            if sess.fill >= sess.ctx_len:
                self._activate(sess)

    def _spec_ok(self) -> bool:
        """Spec rounds need width = k+1 rows of headroom in EVERY
        active slot, and a prompt to draft from (a disagg-imported
        session has none) — otherwise the round falls back to one
        plain step."""
        for slot, sess in self._sessions.items():
            if not self._active[slot]:
                continue
            if sess.prompt is None:
                return False
            if sess.ctx_len + sess.sent + self.spec_k + 1 \
                    > self.cfg.max_seq:
                return False
        return True

    def _plain_round(self):
        """One plain decode step over the active slots; returns
        ``(pairs, finished)`` for the emit/evict epilogue."""
        import jax.numpy as jnp
        t0 = _mono_ns()
        if self.paged:
            cache, logits = self._step(
                self._cache, jnp.asarray(self._bt),
                jnp.asarray(self._tokens), jnp.asarray(self._active))
        else:
            cache, logits = self._step(
                self._cache, jnp.asarray(self._tokens),
                jnp.asarray(self._active))
        self._cache = cache
        self._steps += 1
        _rec_phase(PH_DECODE_ROUND, _mono_ns() - t0)
        toks = np.asarray(jnp.argmax(logits, axis=-1))
        pairs, finished = [], []
        for slot, sess in list(self._sessions.items()):
            if not self._active[slot]:
                continue
            tok = int(toks[slot])
            self._tokens[slot] = tok
            sess.sent += 1
            pairs.append((sess, tok))
            if sess.sent >= sess.max_new:
                finished.append(sess)
        return pairs, finished

    def _spec_round(self):
        """One speculative round: k draft proposals per active slot
        (k cheap contiguous draft steps), ONE width-(k+1) target
        verification, host-side emission of the accepted prefix plus
        the target's own next token.  Token identity with plain decode
        holds on BOTH paths: an accepted row holds exactly the k/v a
        plain step would have written there, and a rejection is a pure
        ``len`` rewind — the refuted rows sit beyond the mask and the
        next round rewrites them before they are ever admitted (see
        ``make_paged_spec_verify``)."""
        import jax.numpy as jnp
        k = self.spec_k
        count_spec("spec_round")
        t_round = _mono_ns()
        active = self._active.copy()
        act_j = jnp.asarray(active)
        cur = self._tokens.copy()
        drafts = []
        for _ in range(k):
            self._d_cache, dl = self._d_step(self._d_cache,
                                             jnp.asarray(cur), act_j)
            cur = np.asarray(jnp.argmax(dl, axis=-1)).astype(np.int32)
            drafts.append(cur)
        t_verify = _mono_ns()
        _rec_phase(PH_SPEC_DRAFT, t_verify - t_round)
        u = np.stack([self._tokens] + drafts, axis=1).astype(np.int32)
        self._cache, out, m = self._verify_j(
            self._cache, jnp.asarray(self._bt), jnp.asarray(u), act_j)
        out = np.asarray(out)
        m = np.asarray(m)
        _rec_phase(PH_SPEC_VERIFY, _mono_ns() - t_verify)
        self._d_cache = self._d_sync_j(self._d_cache, jnp.asarray(m),
                                       act_j)
        self._steps += 1
        _rec_phase(PH_DECODE_ROUND, _mono_ns() - t_round)
        pairs, finished = [], []
        for slot, sess in list(self._sessions.items()):
            if not active[slot]:
                continue
            acc = int(m[slot])
            count_spec("spec_accept", acc)
            count_spec("spec_reject", k - 1 - acc)
            emit = min(acc + 1, sess.max_new - sess.sent)
            for j in range(emit):
                tok = int(out[slot, j])
                self._tokens[slot] = tok
                sess.sent += 1
                pairs.append((sess, tok))
            if sess.sent >= sess.max_new:
                finished.append(sess)
        return pairs, finished

    def _finalize_obs(self, sess: _Session, reason: str) -> None:
        """Session-close observability (batcher thread): judge and
        count the SLO verdict, move the timeline into the ring, close
        out the decode-session span.  Lock-free — runs inside the step
        loop's evict epilogue."""
        tl = sess.tl
        if tl is not None:
            sess.tl = None
            ttft_t, itl_t = self.tiers.slo_of(sess.tier) \
                if self.tiers is not None else (None, None)
            _lmt.close_timeline(tl, reason, ttft_t, itl_t)
        sp = sess.span
        if sp is not None:
            sess.span = None
            sp.annotate("lm_evict:" + reason)
            sp.finish(0)

    def _evict(self, sess: _Session, reason: Optional[str]) -> None:
        self._sessions.pop(sess.slot, None)
        self._active[sess.slot] = False
        if self.paged and sess.pages:
            self._alloc.release_all(sess.pages)
            sess.pages = []
            self._bt[sess.slot] = 0
        if not sess.stream.closed:
            sess.stream.close(reason=reason or "finished")
        self._finalize_obs(sess, reason or "finished")

    def _run(self) -> None:
        try:
            self._ensure_engine()
            while True:
                if self.paged:
                    # parked sessions re-enter BEFORE new admits (they
                    # were serving first), and a drain-aborted host
                    # tier closes them under its named reason here
                    self._service_parked()
                with self._lock:
                    if len(self._pending) > 1:
                        # SLO order: interactive joins drain first
                        # (stable within a tier — FIFO)
                        self._pending = deque(sorted(
                            self._pending,
                            key=lambda s: s.tier_rank))
                    pending = []
                    while self._pending and \
                            len(self._sessions) + len(pending) \
                            < self.slots:
                        pending.append(self._pending.popleft())
                    idle = not self._sessions and not pending \
                        and not self._pending and not self._parked
                if idle:
                    self._wake.clear()
                    # re-check AFTER the clear: a join landing between
                    # the idle check and the clear set the event we
                    # just cleared — its session must not wait out the
                    # whole linger for its first token
                    with self._lock:
                        if self._pending:
                            continue
                    if not self._wake.wait(self.idle_linger_s):
                        with self._lock:
                            if not self._pending \
                                    and not self._sessions:
                                self._thread = None
                                return
                    continue
                for sess in pending:
                    # join-mid-batch: bucketed prefill + slot insert,
                    # BETWEEN steps (bucketing keeps a fresh prompt
                    # length from stalling live sessions on an XLA
                    # compile; the next step emits the first token) —
                    # or, chunked, just the slot grab: _chunk_round
                    # below scatters the context under the budget
                    self._admit(sess)
                # the Sarathi half BEFORE the decode round: a fill
                # completed this round teacher-forces its first token
                # on THIS round's step
                self._chunk_round()
                if not self._sessions:
                    if self.paged and self._parked:
                        # only parked sessions left and none could
                        # resume yet (another holder must release
                        # first): timed poll, never a busy spin
                        import time as _time
                        _time.sleep(0.005)
                    continue
                if not self._active.any():
                    continue    # every occupied slot still filling
                if self.spec_k > 0:
                    if self._spec_ok():
                        pairs, finished = self._spec_round()
                    else:
                        count_spec("spec_fallback_plain")
                        pairs, finished = self._plain_round()
                else:
                    pairs, finished = self._plain_round()
                t0 = _mono_ns()
                dead = self._emit(pairs)
                _rec_phase(PH_STREAM_EMIT, _mono_ns() - t0)
                _lmt.on_emit(pairs)
                evicted = set()
                for sess, reason in dead:
                    # a spec round emits several tokens per session —
                    # one eviction decision each
                    if id(sess) not in evicted:
                        evicted.add(id(sess))
                        self._evict(sess, reason)
                for sess in finished:
                    if self._sessions.get(sess.slot) is sess:
                        self._evict(sess, "finished")
        except Exception:
            LOG.exception("continuous batcher crashed; closing "
                          "sessions")
            with self._lock:
                sessions = list(self._sessions.values()) \
                    + list(self._pending) + list(self._parked)
                self._sessions.clear()
                self._pending.clear()
                self._parked = []
                # free every slot: a leaked _active bit would make the
                # next incarnation's _admit run out of slots forever
                self._active[:] = False
                self._tokens[:] = 0
                # the crashed _step DONATED self._cache — on donating
                # backends those buffers are gone; drop the pool so
                # the next incarnation's _ensure_engine rebuilds it.
                # State reset (incl. _thread) happens BEFORE any
                # fallible allocation: a rebuild failure under the
                # same pressure must not wedge join() forever.  Paged
                # mode drops the allocator triple with the pool: its
                # refcounts describe rows that no longer exist.
                self._cache = None
                self._d_cache = None   # the draft pool donated too
                self._bt[:] = 0
                self._alloc = None
                self._prefix = None
                self._host = None
                self._thread = None
            for sess in sessions:
                try:
                    sess.stream.close(reason="decode_error")
                except Exception:
                    pass
                try:
                    self._finalize_obs(sess, "decode_error")
                except Exception:
                    pass


class LMService(Service):
    """``Generate`` — greedy completion; ``Decode`` — server-streaming
    completion with continuous batching (one token chunk per step per
    session); ``Info`` — model config JSON."""

    def __init__(self, cfg: Optional[LMConfig] = None, params=None,
                 max_new_cap: int = 128, quantize: bool = False,
                 decode_slots: int = 8, paged: bool = False,
                 page: int = 16, kv_pages: Optional[int] = None,
                 kv_host_slots: int = 0, prefix: bool = True,
                 prefill_chunk_tokens: Optional[int] = None,
                 spec_decode_k: int = 0, draft_params=None,
                 tiers: Optional[TierRegistry] = None):
        import jax

        self.cfg = cfg or LMConfig(vocab=256, dim=64, heads=4, depth=2,
                                   max_seq=128, remat=False)
        self.params = params if params is not None else init_params(
            jax.random.PRNGKey(0), self.cfg)
        self.quantized = quantize
        if quantize:
            # weight-only int8 for serving: decode streams every weight
            # per token, so halving the bytes ≈ halves the step time
            # (ops/quant.py); training params stay untouched upstream
            from ..ops.quant import quantize_lm_params
            self.params = quantize_lm_params(self.params)
        self.max_new_cap = max_new_cap
        from ..ops.quant import quantized_nbytes
        self._param_bytes = quantized_nbytes(self.params)  # immutable
        # whole-completion scan generator: one device program per
        # request instead of one per token (per-token dispatch dominates
        # single-stream decode).  Programs compile per
        # (batch, prompt_len, bucketed max_new) and are reused.
        from .transformer_lm import make_scan_generator
        self._gen = make_scan_generator(self.cfg, self.params)
        # continuous-batching decode engine, built lazily at the first
        # Decode call (Generate-only deployments never pay the batch
        # step compile).  scan_layers configs serve Generate only.
        self.decode_slots = int(decode_slots)
        # paged-KV serving knobs (kv/pages allocator; inert when off)
        self.paged = bool(paged)
        self.page = int(page)
        self.kv_pages = kv_pages
        self.kv_host_slots = int(kv_host_slots)
        self.prefix = bool(prefix)
        # SLO-scheduler knobs (ContinuousBatcher docstring)
        self.prefill_chunk_tokens = prefill_chunk_tokens
        self.spec_decode_k = int(spec_decode_k)
        self.draft_params = draft_params
        self.tiers = tiers
        self._batcher: Optional[ContinuousBatcher] = None
        self._batcher_lock = threading.Lock()

    def batcher(self) -> ContinuousBatcher:
        with self._batcher_lock:
            if self._batcher is None:
                self._batcher = ContinuousBatcher(
                    self.cfg, self.params, slots=self.decode_slots,
                    paged=self.paged, page=self.page,
                    pages=self.kv_pages,
                    host_slots=self.kv_host_slots,
                    prefix=self.prefix,
                    prefill_chunk_tokens=self.prefill_chunk_tokens,
                    spec_decode_k=self.spec_decode_k,
                    draft_params=self.draft_params,
                    tiers=self.tiers)
            return self._batcher

    def Generate(self, cntl, request):
        try:
            b, s, max_new = struct.unpack_from("<III", request)
            prompt = np.frombuffer(request, dtype=np.int32,
                                   offset=12).reshape(b, s)
        except (struct.error, ValueError) as e:
            cntl.set_failed(Errno.EREQUEST, f"bad generate request: {e}")
            return None
        if b == 0 or s == 0:
            cntl.set_failed(Errno.EREQUEST, "empty prompt")
            return None
        if max_new <= 0 or max_new > self.max_new_cap:
            cntl.set_failed(Errno.EREQUEST,
                            f"max_new must be in [1, {self.max_new_cap}]")
            return None
        if s + max_new > self.cfg.max_seq:
            cntl.set_failed(
                Errno.EREQUEST,
                f"prompt {s} + max_new {max_new} exceeds max_seq "
                f"{self.cfg.max_seq}")
            return None
        if (prompt < 0).any() or (prompt >= self.cfg.vocab).any():
            cntl.set_failed(Errno.EREQUEST, "prompt ids out of vocab")
            return None
        # bucket max_new to the next power of two so distinct requests
        # share compiled programs; slice the surplus off
        bucket = 1
        while bucket < max_new:
            bucket <<= 1
        bucket = min(bucket, self.max_new_cap,
                     self.cfg.max_seq - s)
        out = np.asarray(self._gen(prompt, int(bucket)),
                         dtype=np.int32)[:, :max_new]
        return struct.pack("<II", *out.shape) + out.tobytes()

    def _check_decode_request(self, cntl, request):
        """Shared ``Decode`` validation + stream accept (the monolithic
        service and the kv/ prefill tier serve the SAME wire contract).
        Returns ``(prompt[1, s], max_new, stream)`` or None with the
        controller already failed."""
        from ..streaming import StreamOptions, stream_accept

        try:
            b, s, max_new = struct.unpack_from("<III", request)
            prompt = np.frombuffer(request, dtype=np.int32,
                                   offset=12).reshape(b, s)
        except (struct.error, ValueError) as e:
            cntl.set_failed(Errno.EREQUEST, f"bad decode request: {e}")
            return None
        if b != 1 or s == 0:
            cntl.set_failed(Errno.EREQUEST,
                            "Decode streams one session per call")
            return None
        if max_new <= 0 or max_new > self.max_new_cap:
            cntl.set_failed(Errno.EREQUEST,
                            f"max_new must be in [1, {self.max_new_cap}]")
            return None
        if s + max_new > self.cfg.max_seq:
            cntl.set_failed(
                Errno.EREQUEST,
                f"prompt {s} + max_new {max_new} exceeds max_seq "
                f"{self.cfg.max_seq}")
            return None
        if (prompt < 0).any() or (prompt >= self.cfg.vocab).any():
            cntl.set_failed(Errno.EREQUEST, "prompt ids out of vocab")
            return None
        if self.cfg.scan_layers:
            cntl.set_failed(Errno.EREQUEST,
                            "Decode serves unrolled configs only")
            return None
        stream = stream_accept(cntl, StreamOptions())
        if stream is None:
            cntl.set_failed(Errno.EREQUEST,
                            "Decode requires a client stream "
                            "(stream_create before the call)")
            return None
        return prompt, int(max_new), stream

    def model_fingerprint(self) -> bytes:
        """Identity the kv/ handoff handshake compares: two tiers may
        exchange KV pages only when they serve the same architecture
        and weight image (a page layout is meaningless under any other
        model).  ``param_bytes`` stands in for a weight hash — cheap,
        and wrong only for same-shape different-weight deployments,
        which a fleet rollout should version explicitly anyway."""
        c = self.cfg
        return (f"{c.vocab}:{c.dim}:{c.heads}:{c.depth}:{c.max_seq}:"
                f"{self._param_bytes}:{int(self.quantized)}").encode()

    def Decode(self, cntl, request):
        """Server-streaming decode: same request wire format as
        ``Generate`` at batch 1, but the caller attaches a stream
        (``stream_create`` before the call) and tokens arrive as int32
        chunks — one per decode step — while the session rides the
        continuous batch (new sessions join between steps, finished
        ones evict; the stream closes with reason ``finished``).  The
        unary response is ``<u32 max_new>`` (the token count the
        stream will carry)."""
        parsed = self._check_decode_request(cntl, request)
        if parsed is None:
            return None
        prompt, max_new, stream = parsed
        # the request's TLV-22 identity picks the session's SLO tier
        meta = getattr(cntl, "request_meta", None)
        tenant = getattr(meta, "tenant", b"") if meta is not None \
            else b""
        self.batcher().join(stream, prompt[0].copy(), max_new,
                            tenant=tenant,
                            span=self._session_span(cntl))
        return struct.pack("<I", max_new)

    def _session_span(self, cntl):
        """Decode-session rpcz span: when the Decode RPC itself is
        traced (its server span exists — forced for a propagated trace
        id, or passively sampled), the session outliving the RPC gets
        its own FORCED child span under the SAME trace id, so the
        batcher's step events (join / chunk slices / first token /
        evict) land in the request's trace — across a disagg handoff
        too, both halves stitch under one id with no new wire format
        (the handoff Controller propagates the trace TLVs any request
        carries)."""
        req_span = getattr(cntl, "span", None)
        if req_span is None:
            return None
        from ..rpcz import Span
        span = Span("LMService.DecodeSession",
                    trace_id=req_span.trace_id,
                    parent_span_id=req_span.span_id)
        span.remote_side = req_span.remote_side
        return span

    def Info(self, cntl, request):
        import json
        c = self.cfg
        return json.dumps({"vocab": c.vocab, "dim": c.dim,
                           "heads": c.heads, "depth": c.depth,
                           "max_seq": c.max_seq,
                           "quantized": self.quantized,
                           "param_bytes": self._param_bytes,
                           }).encode()
