"""LM serving over the framework — autoregressive generation as an RPC.

The capstone wiring: the TransformerLM's KV-cache decode path
(``make_decode``/``generate``) behind a Service, so a Channel client
(or grpc/HTTP through the bridges) asks for completions the way it
would ask any brpc-style service.  The reference's analogue is its
model-serving example services; here the "model" is an actual LM.

Wire format (framework control plane is schema-free TLV; payloads are
the service's own): request = ``<u32 batch><u32 prompt_len>
<u32 max_new>`` + int32 prompt ids; response = int32 generated ids,
shape (batch, max_new).
"""

from __future__ import annotations

import struct
from typing import Optional

import numpy as np

from ..butil.status import Errno
from ..server.service import Service
from .transformer_lm import LMConfig, init_params


def pack_generate_request(prompt: np.ndarray, max_new: int) -> bytes:
    prompt = np.ascontiguousarray(prompt, dtype=np.int32)
    b, s = prompt.shape
    return struct.pack("<III", b, s, max_new) + prompt.tobytes()


def unpack_generated(data: bytes) -> np.ndarray:
    b, n = struct.unpack_from("<II", data)
    return np.frombuffer(data, dtype=np.int32, offset=8).reshape(b, n)


class LMService(Service):
    """``Generate`` — greedy completion; ``Info`` — model config JSON."""

    def __init__(self, cfg: Optional[LMConfig] = None, params=None,
                 max_new_cap: int = 128, quantize: bool = False):
        import jax

        self.cfg = cfg or LMConfig(vocab=256, dim=64, heads=4, depth=2,
                                   max_seq=128, remat=False)
        self.params = params if params is not None else init_params(
            jax.random.PRNGKey(0), self.cfg)
        self.quantized = quantize
        if quantize:
            # weight-only int8 for serving: decode streams every weight
            # per token, so halving the bytes ≈ halves the step time
            # (ops/quant.py); training params stay untouched upstream
            from ..ops.quant import quantize_lm_params
            self.params = quantize_lm_params(self.params)
        self.max_new_cap = max_new_cap
        from ..ops.quant import quantized_nbytes
        self._param_bytes = quantized_nbytes(self.params)  # immutable
        # whole-completion scan generator: one device program per
        # request instead of one per token (per-token dispatch dominates
        # single-stream decode).  Programs compile per
        # (batch, prompt_len, bucketed max_new) and are reused.
        from .transformer_lm import make_scan_generator
        self._gen = make_scan_generator(self.cfg, self.params)

    def Generate(self, cntl, request):
        try:
            b, s, max_new = struct.unpack_from("<III", request)
            prompt = np.frombuffer(request, dtype=np.int32,
                                   offset=12).reshape(b, s)
        except (struct.error, ValueError) as e:
            cntl.set_failed(Errno.EREQUEST, f"bad generate request: {e}")
            return None
        if b == 0 or s == 0:
            cntl.set_failed(Errno.EREQUEST, "empty prompt")
            return None
        if max_new <= 0 or max_new > self.max_new_cap:
            cntl.set_failed(Errno.EREQUEST,
                            f"max_new must be in [1, {self.max_new_cap}]")
            return None
        if s + max_new > self.cfg.max_seq:
            cntl.set_failed(
                Errno.EREQUEST,
                f"prompt {s} + max_new {max_new} exceeds max_seq "
                f"{self.cfg.max_seq}")
            return None
        if (prompt < 0).any() or (prompt >= self.cfg.vocab).any():
            cntl.set_failed(Errno.EREQUEST, "prompt ids out of vocab")
            return None
        # bucket max_new to the next power of two so distinct requests
        # share compiled programs; slice the surplus off
        bucket = 1
        while bucket < max_new:
            bucket <<= 1
        bucket = min(bucket, self.max_new_cap,
                     self.cfg.max_seq - s)
        out = np.asarray(self._gen(prompt, int(bucket)),
                         dtype=np.int32)[:, :max_new]
        return struct.pack("<II", *out.shape) + out.tobytes()

    def Info(self, cntl, request):
        import json
        c = self.cfg
        return json.dumps({"vocab": c.vocab, "dim": c.dim,
                           "heads": c.heads, "depth": c.depth,
                           "max_seq": c.max_seq,
                           "quantized": self.quantized,
                           "param_bytes": self._param_bytes,
                           }).encode()
