"""Parameter-server RPC service — the framework's two halves meeting.

An RPC Service (brpc-capability side) exposing the EmbeddingPS model
(device side): ids ride the request payload, tensors ride the zero-copy
attachment (never through a serializer — the lesson of baidu_std's
attachment, /root/reference/src/brpc/policy/baidu_rpc_protocol.cpp:58).

Methods:
- ``Lookup``  ids → pooled embeddings (attachment: f32 tensor bytes)
- ``Predict`` ids → logits
- ``Train``   (ids, labels) → loss; applies one SGD step server-side
- ``Stat``    → model/table shape info (JSON)
"""

from __future__ import annotations

import json
import struct
from typing import Optional

import numpy as np

from ..butil.status import Errno
from ..server.service import Service
from .embedding_ps import EmbeddingPS, PSConfig


def pack_ids(ids: np.ndarray) -> bytes:
    """(batch, slots) int32 → wire payload."""
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    return struct.pack("<II", *ids.shape) + ids.tobytes()


def unpack_ids(data: bytes) -> np.ndarray:
    b, s = struct.unpack_from("<II", data)
    return np.frombuffer(data, dtype=np.int32,
                         offset=8).reshape(b, s)


class PSService(Service):
    def __init__(self, model: Optional[EmbeddingPS] = None):
        self.model = model or EmbeddingPS(PSConfig(vocab=4096, dim=64,
                                                   hidden=128, classes=8))

    def Lookup(self, cntl, request):
        try:
            ids = unpack_ids(request)
        except (struct.error, ValueError) as e:
            cntl.set_failed(Errno.EREQUEST, f"bad ids payload: {e}")
            return None
        pooled = self.model.lookup(ids)
        # result rides the ICI data plane: device-resident to same-fabric
        # peers (zero host copies), auto host-staged otherwise (ici/)
        cntl.response_device_attachment = pooled
        dtype, shape = str(pooled.dtype), tuple(int(s) for s in pooled.shape)
        return json.dumps({"dtype": dtype, "shape": shape}).encode()

    def Predict(self, cntl, request):
        try:
            ids = unpack_ids(request)
        except (struct.error, ValueError) as e:
            cntl.set_failed(Errno.EREQUEST, f"bad ids payload: {e}")
            return None
        logits = self.model.predict(ids)
        cntl.response_device_attachment = logits
        dtype, shape = str(logits.dtype), tuple(int(s) for s in logits.shape)
        return json.dumps({"dtype": dtype, "shape": shape}).encode()

    def EchoTensor(self, cntl, request):
        """Device-tensor echo — the rdma_performance-equivalent method
        (≈ /root/reference/example/rdma_performance/server.cpp): the
        request's device attachment comes back as the response's,
        never leaving the device fabric."""
        att = cntl.request_device_attachment
        if att is None:
            cntl.set_failed(Errno.EREQUEST, "no device attachment")
            return None
        cntl.response_device_attachment = att.tensor()
        return b"ok"

    def Train(self, cntl, request):
        try:
            ids = unpack_ids(request)
            if cntl.request_device_attachment is not None:
                labels = np.asarray(
                    cntl.request_device_attachment.tensor()).astype(np.int32)
            else:
                labels = np.frombuffer(cntl.request_attachment.to_bytes(),
                                       dtype=np.int32)
        except (struct.error, ValueError) as e:
            cntl.set_failed(Errno.EREQUEST, f"bad train payload: {e}")
            return None
        if labels.shape[0] != ids.shape[0]:
            cntl.set_failed(Errno.EREQUEST, "labels/ids batch mismatch")
            return None
        loss = self.model.train_step(ids, labels)
        return json.dumps({"loss": loss}).encode()

    def Stat(self, cntl, request):
        cfg = self.model.cfg
        return json.dumps({
            "vocab": cfg.vocab, "dim": cfg.dim, "hidden": cfg.hidden,
            "classes": cfg.classes,
            "sharded": self.model.mesh is not None,
        }).encode()
