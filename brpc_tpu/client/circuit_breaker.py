"""Per-node circuit breaker
(≈ /root/reference/src/brpc/circuit_breaker.h:25-85): two EMA error
windows (long + short) trip isolation; isolation duration doubles on
repeated trips within a window and decays after health returns. The LB
skips isolated nodes; feedback is fed from every finished call.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..butil.endpoint import EndPoint
from ..butil.status import Errno

# window/threshold shapes mirror the reference defaults
_SHORT_ALPHA = 0.3        # fast window EMA
_LONG_ALPHA = 0.02        # slow window EMA
_SHORT_TRIP = 0.6         # short-window error rate to trip
_LONG_TRIP = 0.2          # long-window error rate to trip
_MIN_SAMPLES = 8
_BASE_ISOLATION_S = 0.1
_MAX_ISOLATION_S = 30.0
_DOUBLE_WINDOW_S = 30.0   # re-trip within this doubles the duration
# overload plane: an ELIMIT bounce is the server WORKING AS DESIGNED
# under overload — health feedback at reduced weight keeps a merely
# busy (not broken) replica from tripping isolation and shrinking the
# healthy pool exactly when capacity is scarcest; sustained admission
# rejection still trips eventually (0.3 x rate crosses the long
# window's 0.2 threshold)
_ELIMIT_WEIGHT = 0.3
_ELIMIT = int(Errno.ELIMIT)
# operability plane: an ELAMEDUCK bounce is a PLANNED restart — zero
# error weight (the lame-duck registry already removed the node from
# selection; tripping the breaker on top would penalize the node's
# post-restart re-entry, exactly what graceful drain exists to avoid)
_ELAMEDUCK = int(Errno.ELAMEDUCK)


class _NodeBreaker:
    __slots__ = ("short_ema", "long_ema", "samples", "isolated_until",
                 "isolation_s", "last_trip", "lock")

    def __init__(self):
        self.short_ema = 0.0
        self.long_ema = 0.0
        self.samples = 0
        self.isolated_until = 0.0
        self.isolation_s = _BASE_ISOLATION_S
        self.last_trip = 0.0
        self.lock = threading.Lock()

    def on_call(self, error) -> bool:
        """``error``: bool, or a float error weight in [0, 1] (the
        overload plane feeds ELIMIT bounces at reduced weight).
        Returns True when THIS call tripped isolation."""
        e = float(error)
        with self.lock:
            self.samples += 1
            self.short_ema += (e - self.short_ema) * _SHORT_ALPHA
            self.long_ema += (e - self.long_ema) * _LONG_ALPHA
            if self.samples < _MIN_SAMPLES:
                return
            if self.short_ema > _SHORT_TRIP or self.long_ema > _LONG_TRIP:
                now = time.monotonic()
                if now < self.isolated_until:
                    return
                if now - self.last_trip < _DOUBLE_WINDOW_S:
                    self.isolation_s = min(self.isolation_s * 2,
                                           _MAX_ISOLATION_S)
                else:
                    self.isolation_s = _BASE_ISOLATION_S
                self.last_trip = now
                self.isolated_until = now + self.isolation_s
                # both windows restart: a frozen long window would re-trip
                # a healthy server on its first post-isolation call
                self.short_ema = 0.0
                self.long_ema = 0.0
                self.samples = 0
                return True
        return False

    def isolated(self) -> bool:
        return time.monotonic() < self.isolated_until


class CircuitBreakerMap:
    def __init__(self):
        self._lock = threading.Lock()
        self._nodes: Dict[EndPoint, _NodeBreaker] = {}
        self.enabled = True

    def _node(self, ep: EndPoint) -> _NodeBreaker:
        nb = self._nodes.get(ep)
        if nb is None:
            with self._lock:
                nb = self._nodes.setdefault(ep, _NodeBreaker())
        return nb

    def on_call(self, ep: EndPoint, error_code: int,
                latency_us: float) -> None:
        if not self.enabled:
            return
        if error_code == 0 or error_code == _ELAMEDUCK:
            e = 0.0                 # lame duck: planned, not broken
        elif error_code == _ELIMIT:
            e = _ELIMIT_WEIGHT      # busy, not broken: reduced weight
        else:
            e = 1.0
        if self._node(ep).on_call(e):
            # a trip is a fleet-postmortem event: which peer, when
            try:
                from .. import fleet
                fleet.record_event("fleet_breaker_trip", str(ep))
            except Exception:
                pass

    def isolated(self, ep: EndPoint) -> bool:
        if not self.enabled:
            return False
        nb = self._nodes.get(ep)
        return nb.isolated() if nb is not None else False

    def reset(self) -> None:
        with self._lock:
            self._nodes.clear()


_global_map: Optional[CircuitBreakerMap] = None
_global_lock = threading.Lock()


def global_circuit_breaker_map() -> CircuitBreakerMap:
    global _global_map
    with _global_lock:
        if _global_map is None:
            _global_map = CircuitBreakerMap()
        return _global_map
