"""Channel — the client stub.

≈ /root/reference/src/brpc/channel.h:151-190 + channel.cpp:407
(CallMethod): init against a single server ("ip:port") or a cluster
("<naming>://..." + load balancer name), then issue calls through
Controllers. Serialization happens ONCE per call; framing per attempt —
exactly the reference's split between serialize_request and pack_request.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

from ..butil.endpoint import EndPoint, parse_endpoint
from ..butil.logging_util import LOG
from ..protocol.meta import CompressType
from ..protocol.tpu_std import serialize_payload
from . import fast_call
from .controller import Controller


class ChannelOptions:
    """≈ ChannelOptions (channel.h:41). Defaults mirror the reference:
    timeout 500ms, 3 retries, no backup request."""

    __slots__ = ("timeout_ms", "connect_timeout_ms", "max_retry",
                 "backup_request_ms", "connection_type", "protocol",
                 "request_compress_type", "auth_data", "tenant",
                 "enable_circuit_breaker",
                 "retry_budget_max", "retry_budget_ratio",
                 "retry_backoff_ms", "retry_backoff_max_ms",
                 "ssl", "ssl_context", "ssl_ca", "ssl_verify")

    def __init__(self):
        self.timeout_ms = 500
        self.connect_timeout_ms = 1000
        self.max_retry = 3
        self.backup_request_ms = -1
        self.connection_type = "single"
        self.protocol = "tpu_std"
        self.request_compress_type = CompressType.NONE
        self.auth_data = b""
        # overload plane: this channel's tenant identity (API key /
        # team name).  Stamped on every request — tpu_std meta TLV 22,
        # the x-tenant header on HTTP/1.1 and gRPC/h2 — and keyed by
        # the server's per-tenant weighted fair admission, so one hot
        # tenant degrades alone instead of starving the rest.
        self.tenant = ""
        self.enable_circuit_breaker = False
        # retry hardening (deadline plane): every retry AND backup
        # attempt on this channel draws from one gRPC-style token
        # bucket (brpc_tpu.deadline.RetryBudget) — under a degraded
        # backend the sustained retry rate decays to retry_budget_ratio
        # per success instead of multiplying offered load by
        # 1 + max_retry.  max <= 0 disables the budget.  The default is
        # deliberately roomy (50 denied-free retries): ordinary
        # failover must never starve; only storms hit the throttle.
        # Retries back off exponentially from retry_backoff_ms (0 =
        # immediate, the historical behavior) with ±20% jitter, capped
        # at retry_backoff_max_ms.
        self.retry_budget_max = 100.0
        self.retry_budget_ratio = 0.1
        self.retry_backoff_ms = 0
        self.retry_backoff_max_ms = 5000
        # TLS (≈ ChannelSSLOptions, /root/reference/src/brpc/ssl_options.h):
        # ssl=True wraps every connection; ssl_context overrides the
        # default client context; ssl_ca pins a CA file; ssl_verify
        # enables cert verification (off by default — self-signed dev
        # certs work out of the box, like the reference default)
        self.ssl = False
        self.ssl_context = None
        self.ssl_ca = None
        self.ssl_verify = False


class Channel:
    def __init__(self, options: Optional[ChannelOptions] = None):
        self.options = options or ChannelOptions()
        self.single_server: Optional[EndPoint] = None
        self.load_balancer = None
        self._initialized = False
        self._method_tlvs = {}      # method_full -> pre-encoded meta TLVs
        self._ssl_ctx_cache = None
        self._retry_budget = None   # lazy RetryBudget (shared per channel)
        self._retry_budget_lock = threading.Lock()

    # -- retry hardening ---------------------------------------------------

    def retry_budget(self):
        """This channel's retry-throttling token bucket (None when
        disabled via ``retry_budget_max <= 0``)."""
        if self.options.retry_budget_max <= 0:
            return None
        if self._retry_budget is None:
            from ..deadline import RetryBudget
            with self._retry_budget_lock:
                # double-checked: two threads racing the first retry
                # must share ONE bucket, or tokens spent through the
                # losing instance vanish and the cap overshoots
                if self._retry_budget is None:
                    self._retry_budget = RetryBudget(
                        self.options.retry_budget_max,
                        self.options.retry_budget_ratio)
        return self._retry_budget

    def acquire_retry_token(self) -> bool:
        """Spend one retry/backup token; True when the attempt may be
        sent (always True with the budget disabled)."""
        budget = self.retry_budget()
        return True if budget is None else budget.acquire()

    def on_call_success(self) -> None:
        """Refill the retry budget on a successful response."""
        budget = self._retry_budget
        if budget is not None:
            budget.on_success()

    def ssl_ctx(self):
        """The channel's client TLS context (None when TLS is off)."""
        opts = self.options
        if opts.ssl_context is not None:
            return opts.ssl_context
        if not opts.ssl:
            return None
        if self._ssl_ctx_cache is None:
            import ssl as _ssl
            ctx = _ssl.create_default_context(
                cafile=opts.ssl_ca) if opts.ssl_ca \
                else _ssl.create_default_context()
            if not opts.ssl_verify:
                ctx.check_hostname = False
                ctx.verify_mode = _ssl.CERT_NONE
            self._ssl_ctx_cache = ctx
        return self._ssl_ctx_cache

    def init(self, addr: Any, lb_name: str = "") -> int:
        """``addr``: "ip:port" / EndPoint for a single server, or a
        naming URL ("list://a:1,b:2", "file://path", "dns://host:port")
        with a load-balancer name ("rr", "random", "c_murmurhash",
        "la", ...)."""
        if isinstance(addr, EndPoint):
            self.single_server = addr
            self._initialized = True
            return 0
        text = str(addr)
        if "://" in text:
            try:
                from .load_balancer_with_naming import LoadBalancerWithNaming
            except ImportError:
                LOG.error("cluster channels not available in this build")
                return -1
            lb = LoadBalancerWithNaming()
            if lb.init(text, lb_name or "rr",
                       self.options.enable_circuit_breaker) != 0:
                LOG.error("failed to init naming/LB for %s", text)
                return -1
            self.load_balancer = lb
            self._initialized = True
            return 0
        self.single_server = parse_endpoint(text)
        self._initialized = True
        return 0

    def call_method(self, method_full: str, request: Any,
                    response_type: Any = None,
                    done: Optional[Callable] = None,
                    cntl: Optional[Controller] = None,
                    attachment: Any = None) -> Controller:
        """≈ Channel::CallMethod (channel.cpp:407). Synchronous when
        ``done`` is None (blocks the calling fiber/thread via the id
        join); asynchronous otherwise (done(cntl) runs on completion).
        """
        c = cntl or Controller()
        if not self._initialized:
            c._fail_before_launch(2001, "channel not initialized", done)
            return c
        if attachment is not None:
            from ..butil.iobuf import IOBuf
            c.request_attachment = attachment if isinstance(attachment, IOBuf) \
                else IOBuf(attachment)
        if c.trace_id:
            # explicitly traced call: open the client half of the span
            # pair before any lane is chosen, so every wire protocol
            # (tpu_std TLVs, HTTP/h2 traceparent) carries this hop's
            # span id and the server span parents to it
            c._begin_trace_span(method_full)
        if self.options.protocol == "grpc":
            if done is not None:
                # keep call_method's async contract: the blocking h2
                # unary wait runs on a fiber, done fires on completion
                from ..fiber import runtime as fiber_runtime
                fiber_runtime.spawn(self._call_grpc, method_full, request,
                                    response_type, done, c,
                                    name="grpc_call")
                return c
            return self._call_grpc(method_full, request, response_type,
                                   done, c)
        if c.request_compress_type == CompressType.NONE:
            c.request_compress_type = self.options.request_compress_type
        if done is None and fast_call.eligible(self, c):
            # latency fast lane: whole round trip on the calling thread,
            # bytes-like payloads pass through with zero IOBuf churn
            tlv = self._method_tlvs.get(method_full)
            if tlv is None:
                tlv = self._method_tlvs[method_full] = \
                    fast_call.method_tlv(method_full,
                                         self.options.tenant)
            try:
                fast_call.run(self, c, method_full, request, response_type,
                              tlv)
            except TypeError as e:
                c._fail_before_launch(1003, str(e), done)
            return c
        try:
            payload = serialize_payload(request)
        except TypeError as e:
            c._fail_before_launch(1003, str(e), done)
            return c
        c._launch(self, method_full, payload, response_type, done)
        if done is None:
            c._sync_wait()
        return c

    def _call_grpc(self, method_full: str, request: Any,
                   response_type: Any, done: Optional[Callable],
                   c: Controller) -> Controller:
        """gRPC unary over a multiplexed h2 connection
        (protocol="grpc").  Single-server channels only; LB selection
        picks a server per call for cluster channels."""
        from ..butil.time_utils import monotonic_us
        from ..protocol.h2_rpc import errno_of_grpc_status
        from ..protocol.tpu_std import parse_payload
        from .grpc_client import grpc_connection

        remote = self.single_server
        if remote is None and self.load_balancer is not None:
            remote = self.load_balancer.select_server(c)
        if remote is None:
            c._fail_before_launch(2001, "no server available", done)
            return c
        c.remote_side = remote
        try:
            payload = serialize_payload(request).to_bytes()
        except TypeError as e:
            c._fail_before_launch(1003, str(e), done)
            return c
        svc, _, mth = method_full.rpartition(".")
        # deadline inheritance: a grpc call from a deadline'd handler is
        # capped to the remaining upstream budget (grpc-timeout carries
        # it to the server), failing fast when it's already gone
        from ..butil.status import Errno
        from ..deadline import cap_timeout_ms
        tmo_ms, amb_expired = cap_timeout_ms(
            c.timeout_ms or self.options.timeout_ms or 30000)
        if amb_expired:
            c._fail_before_launch(
                int(Errno.ERPCTIMEDOUT),
                "inherited deadline already expired (doomed downstream "
                "call failed fast)", done)
            return c
        timeout_s = tmo_ms / 1e3
        metadata = None
        if c.trace_id and c.span_id:
            # trace context over h2 as a W3C traceparent header (HPACK
            # metadata — same mapping as the HTTP/1.1 client); omitted
            # when span_id==0 (rpcz disabled: no client span) — an
            # all-zero parent-id is W3C-invalid and strict peers drop
            # the whole header
            from ..rpcz import format_traceparent
            metadata = [("traceparent",
                         format_traceparent(c.trace_id, c.span_id))]
        if self.options.tenant:
            # tenant identity: x-tenant over HPACK is TLV 22's gRPC
            # spelling (overload plane fair admission)
            metadata = (metadata or []) + [("x-tenant",
                                            self.options.tenant)]
        begin = monotonic_us()
        status, message, body = grpc_connection(remote).unary_call(
            f"/{svc}/{mth}", payload, timeout_s=timeout_s,
            metadata=metadata)
        c.latency_us = monotonic_us() - begin
        if status != 0:
            c.set_failed(errno_of_grpc_status(status),
                         f"grpc-status {status}: {message}")
        else:
            try:
                c.response = parse_payload(body, response_type)
            except Exception as e:
                c.set_failed(1004, f"response parse failed: {e}")
        if self.load_balancer is not None:
            self.load_balancer.feedback(c)
        c._signal_ended()
        if done is not None:
            try:
                done(c)
            except Exception:
                LOG.exception("rpc done callback raised")
        return c

    def grpc_stream(self, method_full: str,
                    timeout_ms: Optional[int] = None,
                    metadata=None):
        """Open a full-duplex gRPC stream to a single-server channel
        (protocol='grpc'): returns a GrpcStreamCall with write()/read()/
        done_writing()/status()."""
        from .grpc_client import grpc_connection
        if self.single_server is None:
            raise RpcError(2001, "grpc_stream needs a single-server channel")
        svc, _, mth = method_full.rpartition(".")
        timeout_s = (timeout_ms or self.options.timeout_ms or 30000) / 1e3
        return grpc_connection(self.single_server).streaming_call(
            f"/{svc}/{mth}", timeout_s, metadata)

    # sugar: channel.call("Echo.Hi", b"x") -> response bytes or raises
    def call(self, method_full: str, request: Any,
             response_type: Any = None, **kw) -> Any:
        if kw:
            user_cntl = kw.pop("cntl", None)
            cntl = user_cntl or Controller.obtain()
            if "timeout_ms" in kw:
                cntl.timeout_ms = kw.pop("timeout_ms")
            pooled = user_cntl is None and kw.get("done") is None
            c = self.call_method(method_full, request, response_type,
                                 cntl=cntl, **kw)
        else:
            # the controller is internal and synchronous here: obtain
            # it from the free list and recycle after the results are
            # extracted (user code never sees it)
            pooled = True
            c = self.call_method(method_full, request, response_type,
                                 cntl=Controller.obtain())
        failed, code, text = c.failed, c.error_code, c.error_text
        response = c.response
        if pooled:
            c.recycle()
        if failed:
            raise RpcError(code, text)
        return response

    def call_raw(self, method_full: str, payload,
                 attachment=b"",
                 timeout_ms: Optional[int] = None):
        """Raw latency-lane unary call (pairs with @raw_method on the
        server): bytes in → ``(response_view, attachment_view)`` out,
        zero-copy views into the response frame.  No Controller in the
        path; raises RpcError on failure.  One attempt — resilience
        (retries, backup requests, LB) lives on call_method.  Lifetime:
        an attachment view that rode the shm lane aliases a ring slot
        recycled at THIS thread's next call on the channel (the socket
        is thread-pinned) — consume or copy it before then."""
        return fast_call.run_raw(self, method_full, payload, attachment,
                                 timeout_ms)

    def call_batch(self, method_full: str, requests,
                   response_type: Any = None,
                   timeout_ms: Optional[int] = None) -> list:
        """Pipelined unary batch: all requests ride one exclusive
        connection in a single vectored write; responses are matched by
        correlation id.  Amortizes per-call syscall + GIL costs — the
        high-QPS lane for small messages."""
        tlv = self._method_tlvs.get(method_full)
        if tlv is None:
            tlv = self._method_tlvs[method_full] = \
                fast_call.method_tlv(method_full, self.options.tenant)
        if not self._initialized:
            raise RpcError(2001, "channel not initialized")
        if self.options.protocol != "tpu_std" or self.ssl_ctx() is not None:
            return [self.call(method_full, r, response_type,
                              timeout_ms=timeout_ms) for r in requests]
        return fast_call.run_batch(self, method_full, list(requests),
                                   response_type, timeout_ms, tlv)


class RpcError(Exception):
    def __init__(self, code: int, text: str):
        super().__init__(f"[{code}] {text}")
        self.code = code
        self.text = text
