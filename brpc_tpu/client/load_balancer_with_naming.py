"""NamingService + LoadBalancer composition
(≈ /root/reference/src/brpc/details/load_balancer_with_naming.h): the
channel's cluster mode — watch membership, keep the LB's server set
fresh, delegate selection/feedback."""

from __future__ import annotations

from typing import List, Optional

from ..butil.logging_util import LOG
from .load_balancer import LoadBalancer, create_load_balancer
from .naming_service import NamingService, ServerNode, create_naming_service


class LoadBalancerWithNaming:
    def __init__(self):
        self._ns: Optional[NamingService] = None
        self._lb: Optional[LoadBalancer] = None

    def init(self, naming_url: str, lb_name: str,
             enable_circuit_breaker: bool = False) -> int:
        # builtin policies register on import
        from ..policy import load_balancers as _lbs  # noqa: F401
        from ..policy import naming as _naming       # noqa: F401

        self._lb = create_load_balancer(lb_name)
        if self._lb is None:
            LOG.error("unknown load balancer %r", lb_name)
            return -1
        self._lb.use_circuit_breaker = enable_circuit_breaker
        self._ns = create_naming_service(naming_url)
        if self._ns is None:
            return -1
        self._ns.watch(self._on_servers)
        return 0

    def _on_servers(self, nodes: List[ServerNode]) -> None:
        self._lb.reset_servers(nodes)

    def select_server(self, cntl):
        return self._lb.select_server(cntl)

    def feedback(self, cntl) -> None:
        self._lb.feedback(cntl)

    @property
    def servers(self) -> List[ServerNode]:
        return self._lb.servers if self._lb else []

    def stop(self) -> None:
        if self._ns is not None:
            self._ns.stop()
