"""gRPC client — h2 connection with multiplexed unary calls.

The client half of the h2/gRPC interop story (≈ the client paths of
/root/reference/src/brpc/policy/http2_rpc_protocol.cpp): one TCP
connection per peer, streams multiplexed, and ONE process-wide
selector-driven reader thread distributing frames to waiting callers
across ALL connections (h2 responses are unordered across streams, so
the tpu_std direct-read trick does not apply; a thread per connection
would not scale to pod-sized peer sets).

Used by Channel when ``options.protocol == "grpc"``; also usable
standalone against any gRPC server (oracle: grpcio in the tests).
"""

from __future__ import annotations

import selectors
import socket as _socket
import struct
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..butil.endpoint import EndPoint
from ..butil.logging_util import LOG
from ..protocol.h2_rpc import GRPC_CT, pack_grpc_message, unpack_grpc_messages
from ..protocol.h2_session import H2Error, H2Session


class _SharedReader:
    """One selector loop reading for every GrpcConnection.

    Sockets stay BLOCKING: the loop issues exactly one recv per
    readiness event (select guarantees it cannot block), so writer
    threads keep their simple sendall path.  Register/unregister
    requests are queued and applied on the loop thread (selectors are
    not thread-safe), with a socketpair as the wakeup."""

    def __init__(self):
        self._sel = selectors.DefaultSelector()
        self._rd, self._wr = _socket.socketpair()
        self._rd.setblocking(False)
        self._wr.setblocking(False)    # _wake must never block a caller
                                       # holding a connection lock
        self._sel.register(self._rd, selectors.EVENT_READ, None)
        self._ops: deque = deque()     # ("add", sock, conn) | ("del", sock)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._loop,
                                            name="grpc_shared_reader",
                                            daemon=True)
            self._thread.start()

    def _wake(self) -> None:
        try:
            self._wr.send(b"x")
        except OSError:
            pass

    def register(self, sock: _socket.socket, conn: "GrpcConnection") -> None:
        with self._lock:
            self._ops.append(("add", sock, conn))
            self._ensure_thread()
        self._wake()

    def unregister(self, sock: _socket.socket) -> None:
        """Queue removal; the loop thread closes the socket after
        deregistering (closing first would poison the selector)."""
        with self._lock:
            self._ops.append(("del", sock, None))
            self._ensure_thread()      # a dead loop must still close fds
        self._wake()

    def _apply_ops(self) -> None:
        while True:
            with self._lock:
                if not self._ops:
                    return
                op, sock, conn = self._ops.popleft()
            try:
                if op == "add":
                    self._sel.register(sock, selectors.EVENT_READ, conn)
                else:
                    try:
                        self._sel.unregister(sock)
                    except (KeyError, ValueError):
                        pass
                    try:
                        sock.close()
                    except OSError:
                        pass
            except (OSError, ValueError) as e:
                LOG.warning("grpc shared reader op %s failed: %s", op, e)

    def _loop(self) -> None:
        while True:
            self._apply_ops()
            try:
                events = self._sel.select(1.0)
            except OSError:
                # a registered fd died outside the queue (should not
                # happen; defensive): rebuild by dropping dead entries
                for key in list(self._sel.get_map().values()):
                    if key.data is not None and key.fileobj.fileno() < 0:
                        try:
                            self._sel.unregister(key.fileobj)
                        except (KeyError, ValueError):
                            pass
                continue
            for key, _mask in events:
                if key.data is None:
                    try:
                        self._rd.recv(4096)
                    except OSError:
                        pass
                    continue
                try:
                    key.data._on_readable(key.fileobj)
                except Exception as e:   # noqa: BLE001 - blast radius:
                    # ONE connection, never the process-wide loop
                    LOG.exception("grpc reader: connection dispatch "
                                  "raised")
                    try:
                        key.data._fail_all(f"reader: {e}")
                    except Exception:
                        pass


_shared_reader: Optional[_SharedReader] = None
_shared_reader_lock = threading.Lock()


def shared_reader() -> _SharedReader:
    global _shared_reader
    with _shared_reader_lock:
        if _shared_reader is None:
            _shared_reader = _SharedReader()
        return _shared_reader


class _Call:
    __slots__ = ("event", "headers", "trailers", "body", "rst_code",
                 "streaming", "msgs", "cond", "ended")

    def __init__(self, streaming: bool = False):
        self.event = threading.Event()
        self.headers: List[Tuple[str, str]] = []
        self.trailers: List[Tuple[str, str]] = []
        self.body = bytearray()
        self.rst_code: Optional[int] = None
        self.streaming = streaming
        self.msgs: List[bytes] = []        # streaming: decoded messages
        self.cond = threading.Condition()
        self.ended = False

    def header(self, name: str, default: str = "") -> str:
        for n, v in self.trailers:
            if n == name:
                return v
        for n, v in self.headers:
            if n == name:
                return v
        return default


class GrpcConnection:
    """One h2 connection; thread-safe; reconnects lazily after failure."""

    def __init__(self, remote: EndPoint, connect_timeout_s: float = 2.0):
        self._remote = remote
        self._connect_timeout_s = connect_timeout_s
        self._lock = threading.Lock()        # guards session + socket writes
        self._sock: Optional[_socket.socket] = None
        self._session: Optional[H2Session] = None
        self._calls: Dict[int, _Call] = {}
        self._dead = True

    # -- connection management --------------------------------------------

    def _ensure_connected(self) -> None:
        with self._lock:
            if not self._dead and self._sock is not None:
                return
            sock = _socket.create_connection(
                self._remote.to_sockaddr(),
                timeout=self._connect_timeout_s)
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            sock.settimeout(None)
            self._sock = sock
            self._session = H2Session(is_server=False)
            self._session.start()
            self._flush_locked()
            self._dead = False
            shared_reader().register(sock, self)

    def _flush_locked(self) -> None:
        out = self._session.take_output()
        if out and self._sock is not None:
            self._sock.sendall(out)

    def _fail_all(self, why: str) -> None:
        with self._lock:
            self._dead = True
            calls = list(self._calls.values())
            self._calls.clear()
            if self._sock is not None:
                # the reader loop deregisters, then closes
                shared_reader().unregister(self._sock)
            self._sock = None
        for call in calls:
            call.rst_code = -1
            call.trailers = [("grpc-status", "14"),      # UNAVAILABLE
                             ("grpc-message", why)]
            with call.cond:
                call.ended = True
                call.cond.notify_all()
            call.event.set()

    def _on_readable(self, sock: _socket.socket) -> None:
        """Runs on the shared reader loop: one recv (select said it
        cannot block), feed the session, dispatch events."""
        with self._lock:
            if sock is not self._sock:
                # superseded by a reconnect: drop the orphan
                shared_reader().unregister(sock)
                return
            session = self._session
        try:
            # MSG_DONTWAIT: the socket itself stays blocking for the
            # writers' sendall, but a spurious readiness event (select
            # raced a discarded packet) must not hang the shared loop
            data = sock.recv(256 * 1024, _socket.MSG_DONTWAIT)
        except BlockingIOError:
            return                     # spurious readiness
        except OSError as e:
            self._fail_all(f"recv: {e}")
            return
        if not data:
            self._fail_all("connection closed by server")
            return
        try:
            with self._lock:
                if self._session is not session:
                    return                   # superseded mid-recv
                events = session.feed(data)
                self._flush_locked()
        except (H2Error, OSError) as e:
            self._fail_all(f"h2: {e}")
            return
        for ev in events:
            self._on_event(ev)

    def _on_event(self, ev: tuple) -> None:
        kind = ev[0]
        if kind == "headers":
            _, sid, headers, end = ev
            call = self._calls.get(sid)
            if call is None:
                return
            if call.headers:
                call.trailers = headers
            else:
                call.headers = headers
            if end:
                self._finish(sid)
        elif kind == "data":
            _, sid, body, end = ev
            call = self._calls.get(sid)
            if call is None:
                return
            call.body += body
            if call.streaming:
                with call.cond:
                    try:
                        call.msgs.extend(unpack_grpc_messages(call.body))
                    except H2Error:
                        call.rst_code = -2
                        self._finish(sid)
                        return
                    call.cond.notify_all()
            if end:
                self._finish(sid)
        elif kind == "rst":
            _, sid, code = ev
            call = self._calls.get(sid)
            if call is not None:
                call.rst_code = code
                self._finish(sid)
        elif kind == "goaway":
            self._fail_all(f"goaway code={ev[2]}")

    def _finish(self, sid: int) -> None:
        with self._lock:
            call = self._calls.pop(sid, None)
            if self._session is not None:
                self._session.close_stream(sid)
        if call is not None:
            with call.cond:
                call.ended = True
                call.cond.notify_all()
            call.event.set()

    # -- calls -------------------------------------------------------------

    def _request_headers(self, path: str, timeout_s: float,
                         metadata) -> List[Tuple[str, str]]:
        return [
            (":method", "POST"),
            (":scheme", "http"),
            (":path", path),
            (":authority", str(self._remote)),
            ("content-type", GRPC_CT),
            ("te", "trailers"),
            ("grpc-timeout", f"{max(1, int(timeout_s * 1000))}m"),
        ] + list(metadata or [])

    def unary_call(self, path: str, payload: bytes,
                   timeout_s: float = 30.0,
                   metadata: Optional[List[Tuple[str, str]]] = None
                   ) -> Tuple[int, str, bytes]:
        """Returns (grpc_status, message, response_bytes).  14/UNAVAILABLE
        on transport failure, 4/DEADLINE_EXCEEDED on timeout."""
        try:
            self._ensure_connected()
        except OSError as e:
            return 14, f"connect to {self._remote}: {e}", b""
        call = _Call()
        with self._lock:
            if self._dead:
                return 14, "connection lost", b""
            sid = self._session.next_stream_id()
            self._calls[sid] = call
            headers = self._request_headers(path, timeout_s, metadata)
            try:
                self._session.send_headers(sid, headers)
                self._session.send_data(sid, pack_grpc_message(payload),
                                        end_stream=True)
                self._flush_locked()
            except OSError as e:
                self._calls.pop(sid, None)
                self._fail_all(f"send: {e}")
                return 14, f"send: {e}", b""
        if not call.event.wait(timeout_s):
            with self._lock:
                self._calls.pop(sid, None)
                if self._session is not None:
                    try:
                        self._session.send_rst(sid, 0x8)   # CANCEL
                        self._flush_locked()
                    except OSError:
                        pass
            return 4, f"deadline {timeout_s}s exceeded", b""
        if call.rst_code not in (None, -1):
            return 13, f"stream reset (h2 code {call.rst_code})", b""
        status_s = call.header("grpc-status", "2")
        status = int(status_s) if status_s.isdigit() else 2
        message = call.header("grpc-message")
        body = b""
        if call.body:
            buf = bytearray(call.body)
            try:
                msgs = unpack_grpc_messages(buf)
                body = msgs[0] if msgs else b""
            except H2Error as e:
                return 13, f"bad response framing: {e}", b""
        return status, message, body

    def streaming_call(self, path: str, timeout_s: float = 30.0,
                       metadata: Optional[List[Tuple[str, str]]] = None
                       ) -> "GrpcStreamCall":
        """Open a full-duplex gRPC stream (covers server-streaming,
        client-streaming and bidi): write() request messages, read()
        response messages, done_writing() to half-close, status()/
        message() after the response stream ends."""
        self._ensure_connected()
        call = _Call(streaming=True)
        with self._lock:
            if self._dead:
                raise ConnectionError("connection lost")
            sid = self._session.next_stream_id()
            self._calls[sid] = call
            self._session.send_headers(
                sid, self._request_headers(path, timeout_s, metadata))
            self._flush_locked()
        return GrpcStreamCall(self, sid, call, timeout_s)

    def close(self) -> None:
        self._fail_all("closed")


class GrpcStreamCall:
    """Client end of one gRPC stream."""

    def __init__(self, conn: GrpcConnection, sid: int, call: _Call,
                 timeout_s: float):
        self._conn = conn
        self._sid = sid
        self._call = call
        self._timeout_s = timeout_s
        self._half_closed = False

    # -- sending -----------------------------------------------------------

    def write(self, payload: bytes) -> None:
        if self._half_closed:
            raise RuntimeError("write after done_writing")
        if self._call.ended:
            # the server already finished: framing DATA on a closed h2
            # stream is a connection error that would kill every call
            # multiplexed on this connection
            raise ConnectionError(
                f"stream finished (grpc-status {self.status()})")
        with self._conn._lock:
            if self._conn._dead:
                raise ConnectionError("connection lost")
            self._conn._session.send_data(self._sid,
                                          pack_grpc_message(payload))
            self._conn._flush_locked()

    def done_writing(self) -> None:
        """Half-close: no more request messages."""
        if self._half_closed:
            return
        self._half_closed = True
        if self._call.ended:
            return
        with self._conn._lock:
            if self._conn._dead:
                return
            self._conn._session.send_data(self._sid, b"", end_stream=True)
            self._conn._flush_locked()

    # -- receiving ---------------------------------------------------------

    def read(self, timeout_s: Optional[float] = None) -> Optional[bytes]:
        """Next response message; None when the server finished."""
        call = self._call
        deadline = timeout_s if timeout_s is not None else self._timeout_s
        with call.cond:
            ok = call.cond.wait_for(lambda: call.msgs or call.ended,
                                    deadline)
            if call.msgs:
                return call.msgs.pop(0)
            if not ok:
                raise TimeoutError("grpc stream read timed out")
            return None

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        msg = self.read()
        if msg is None:
            raise StopIteration
        return msg

    def cancel(self) -> None:
        with self._conn._lock:
            if not self._conn._dead and self._conn._session is not None:
                try:
                    self._conn._session.send_rst(self._sid, 0x8)  # CANCEL
                    self._conn._flush_locked()
                except OSError:
                    pass
        self._conn._finish(self._sid)

    # -- completion --------------------------------------------------------

    def status(self) -> int:
        s = self._call.header("grpc-status", "2")
        return int(s) if s.isdigit() else 2

    def message(self) -> str:
        return self._call.header("grpc-message")

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        return self._call.event.wait(
            timeout_s if timeout_s is not None else self._timeout_s)


_conns_lock = threading.Lock()
_conns: Dict[EndPoint, GrpcConnection] = {}


def grpc_connection(remote: EndPoint) -> GrpcConnection:
    with _conns_lock:
        conn = _conns.get(remote)
        if conn is None:
            conn = _conns[remote] = GrpcConnection(remote)
        return conn
