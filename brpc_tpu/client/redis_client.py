"""Redis client — RESP over an exclusive pooled connection.

≈ /root/reference/src/brpc/redis.h's client half (RedisRequest/
RedisResponse with pipelining), shaped for this framework: commands are
plain ``*args``, pipeline() ships N commands in one write and reads N
replies — against any RESP server (including this framework's own
shared port with a "redis" service).
"""

from __future__ import annotations

import socket as _socket
import threading
from typing import Any, List, Optional

from ..butil.endpoint import EndPoint, parse_endpoint
from ..protocol.resp import NIL, RedisError, decode_one, encode_command


class RedisClient:
    """One connection, thread-safe via a lock (commands are cheap; use
    several clients for parallelism)."""

    def __init__(self, addr, timeout_s: float = 2.0):
        self._remote: EndPoint = addr if isinstance(addr, EndPoint) \
            else parse_endpoint(str(addr))
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        self._sock: Optional[_socket.socket] = None
        self._buf = b""

    def _ensure(self) -> None:
        if self._sock is None:
            s = _socket.create_connection(self._remote.to_sockaddr(),
                                          timeout=self._timeout_s)
            s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            self._sock = s
            self._buf = b""

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def _read_reply(self) -> Any:
        while True:
            val, pos = decode_one(self._buf, 0)
            if pos > 0 or val is not None:
                self._buf = self._buf[pos:]
                return None if val is NIL else val
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis server closed the connection")
            self._buf += chunk

    def command(self, *args) -> Any:
        """One command; RedisError replies raise."""
        with self._lock:
            self._ensure()
            try:
                self._sock.sendall(encode_command(*args))
                reply = self._read_reply()
            except (OSError, ConnectionError):
                self.close()
                raise
        if isinstance(reply, RedisError):
            raise reply
        return reply

    def pipeline(self, commands: List[tuple]) -> List[Any]:
        """N commands in one write, N replies back (errors returned
        in-place, not raised — pipelining semantics)."""
        with self._lock:
            self._ensure()
            try:
                self._sock.sendall(b"".join(
                    encode_command(*c) for c in commands))
                return [self._read_reply() for _ in commands]
            except (OSError, ConnectionError):
                self.close()
                raise

    # sugar for the common commands
    def set(self, key, value) -> Any:
        return self.command("SET", key, value)

    def get(self, key) -> Any:
        return self.command("GET", key)

    def delete(self, *keys) -> Any:
        return self.command("DEL", *keys)

    def incr(self, key) -> Any:
        return self.command("INCR", key)

    def ping(self) -> Any:
        return self.command("PING")
