"""LoadBalancer plugin interface
(≈ /root/reference/src/brpc/load_balancer.h:35-95): server set mutations
go through DoublyBufferedData so SelectServer is a read-only, lock-free
path; Feedback lets latency-aware policies learn.

Selection context is the Controller: it carries ``request_code`` (for
consistent hashing), the per-call excluded-server set (retries avoid the
server that just failed, ≈ excluded_servers.h), and receives
``remote_side`` back.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

from ..butil.doubly_buffered import DoublyBufferedData
from ..butil.endpoint import EndPoint
from ..butil.extension import extension
from .circuit_breaker import global_circuit_breaker_map
from .naming_service import ServerNode, global_lame_ducks


class LoadBalancer:
    """Subclasses implement select(); the base maintains the server list
    in a DoublyBufferedData and filters excluded/isolated nodes."""

    def __init__(self):
        self._servers: DoublyBufferedData[List[ServerNode]] = \
            DoublyBufferedData([])
        self._breakers = global_circuit_breaker_map()
        # Gated by ChannelOptions.enable_circuit_breaker (off by default,
        # like the reference channel.h:49-77): when False, no node is
        # filtered by breaker state and calls don't feed it.
        self.use_circuit_breaker = False
        # ClusterRecoverPolicy (≈ cluster_recover_policy.h): when fewer
        # than min_working_instances survive breaker isolation, the
        # cluster is deemed "recovering" — selection probes the FULL
        # list (isolated included) so broken-but-healed servers get
        # traffic and can revive, instead of the survivors melting down.
        self.min_working_instances = 0      # 0 = policy off
        self.recovering = False

    # -- membership (≈ AddServer/RemoveServer batched) --------------------

    def reset_servers(self, nodes: Sequence[ServerNode]) -> None:
        self._servers.modify_with_new(list(nodes))

    def add_server(self, node: ServerNode) -> None:
        def add(lst):
            if node not in lst:
                lst.append(node)
            return True
        self._servers.modify(add)

    def remove_server(self, node: ServerNode) -> None:
        def rm(lst):
            if node in lst:
                lst.remove(node)
            return True
        self._servers.modify(rm)

    @property
    def servers(self) -> List[ServerNode]:
        return self._servers.read()

    # -- selection ---------------------------------------------------------

    def candidates(self, cntl) -> List[ServerNode]:
        nodes = self._servers.read()
        excluded = getattr(cntl, "excluded_servers", None) or ()
        breakers = self._breakers if self.use_circuit_breaker else None
        # lame-duck filter (operability plane): a draining node said so
        # itself — drop it from selection immediately, breaker state
        # untouched (unconditional: the mark only exists because the
        # node emitted the signal).  In-flight responses still complete
        # — this filters SELECTION only.
        ducks = global_lame_ducks()
        usable = [n for n in nodes
                  if not ducks.is_lame(n.endpoint)
                  and (breakers is None
                       or not breakers.isolated(n.endpoint))]
        if breakers is not None and self.min_working_instances > 0:
            if len(usable) < self.min_working_instances:
                self.recovering = True
            elif self.recovering and \
                    len(usable) >= self.min_working_instances:
                self.recovering = False
            if self.recovering:
                # probe the full list so isolated-but-healed servers get
                # traffic and can re-qualify
                usable = list(nodes)
        out = [n for n in usable if n.endpoint not in excluded]
        if not out and nodes:
            # every node excluded/isolated: fall back to the full list
            # rather than failing the call outright (cluster recover
            # behavior, ≈ cluster_recover_policy.h)
            out = list(nodes)
        return out

    def select_server(self, cntl) -> Optional[EndPoint]:
        nodes = self.candidates(cntl)
        if not nodes:
            return None
        node = self.select(nodes, cntl)
        return node.endpoint if node is not None else None

    def select(self, nodes: List[ServerNode], cntl) -> Optional[ServerNode]:
        raise NotImplementedError

    # -- learning ----------------------------------------------------------

    def feedback(self, cntl) -> None:
        """Called on RPC completion with the final controller state."""
        if cntl.remote_side is None:
            return
        if self.use_circuit_breaker:
            self._breakers.on_call(cntl.remote_side, cntl.error_code,
                                   cntl.latency_us)
        self.on_feedback(cntl)

    def on_feedback(self, cntl) -> None:
        pass


def lb_registry():
    return extension("load_balancer")


def create_load_balancer(name: str) -> Optional[LoadBalancer]:
    factory = lb_registry().find(name or "rr")
    return factory() if factory is not None else None
