"""Naming services — who are my servers?

≈ /root/reference/src/brpc/naming_service.h:36-61 +
periodic_naming_service.cpp: a NamingService pushes full server lists to
NamingServiceActions; most implementations poll a source periodically and
push on change. A watcher (the LB) applies deltas through
DoublyBufferedData so selection never takes the update lock.

Server entries may carry a tag (``host:port tag``) — PartitionChannel
reads partition tags like ``2/4`` from it
(/root/reference/src/brpc/partition_channel.h:46).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import time as _time

from ..butil.endpoint import EndPoint, parse_endpoint
from ..butil.extension import extension
from ..butil.flags import define_flag, get_flag
from ..butil.logging_util import LOG
from ..fiber.timer_thread import global_timer_thread

DEFAULT_REFRESH_S = 5.0

define_flag("lame_duck_ttl_s", 10.0,
            "how long a lame-duck mark keeps a node out of LB "
            "selection before it may rejoin (a restarted replica "
            "re-qualifies after this TTL even when the naming source "
            "still lists it); refreshed by every further lame-duck "
            "signal from the node",
            validator=lambda v: isinstance(v, (int, float)) and v > 0)


class LameDuckRegistry:
    """Process-global endpoint → lame-duck-until (monotonic seconds).

    The operability plane's client half: a server entering drain says
    so on every response (meta TLV 23 / ``x-lame-duck`` / GOAWAY) and
    with every ``ELAMEDUCK`` rejection; the mark removes the node from
    LB selection IMMEDIATELY — in-flight responses are still accepted,
    and the circuit breaker sees no error (a planned restart is not a
    failure).  Marks expire after ``lame_duck_ttl_s`` so the restarted
    replica rejoins without any naming-source round trip; a fresh
    naming push that no longer lists the node removes it the ordinary
    way."""

    def __init__(self):
        self._lock = threading.Lock()
        self._until: dict = {}          # EndPoint -> monotonic expiry
        self.marks = 0                  # lifetime marks (diagnostics)

    def mark(self, ep, ttl_s: Optional[float] = None) -> None:
        if ep is None:
            return
        ttl = float(ttl_s if ttl_s is not None
                    else get_flag("lame_duck_ttl_s", 10.0))
        with self._lock:
            self._until[ep] = _time.monotonic() + ttl
            self.marks += 1

    def clear(self, ep) -> None:
        """Drop a mark — fed by any CLEAN response from the endpoint
        (no lame-duck TLV): the restarted successor on the same
        address must not inherit its predecessor's mark.  Unmarked
        endpoints exit on the GIL-atomic dict read, so the completion
        paths may call this per response."""
        if ep in self._until:
            with self._lock:
                self._until.pop(ep, None)

    def is_lame(self, ep) -> bool:
        until = self._until.get(ep)
        if until is None:
            return False
        if _time.monotonic() >= until:
            with self._lock:
                # re-check under the lock: a racing mark() must win
                u2 = self._until.get(ep)
                if u2 is not None and _time.monotonic() >= u2:
                    del self._until[ep]
            return False
        return True

    def snapshot(self) -> dict:
        now = _time.monotonic()
        with self._lock:
            return {ep: round(u - now, 3)
                    for ep, u in self._until.items() if u > now}

    def reset(self) -> None:
        with self._lock:
            self._until.clear()


_lame_ducks: Optional[LameDuckRegistry] = None
_lame_lock = threading.Lock()


def global_lame_ducks() -> LameDuckRegistry:
    global _lame_ducks
    if _lame_ducks is None:
        with _lame_lock:
            if _lame_ducks is None:
                _lame_ducks = LameDuckRegistry()
    return _lame_ducks


@dataclass(frozen=True)
class ServerNode:
    endpoint: EndPoint
    tag: str = ""

    def __str__(self) -> str:
        return f"{self.endpoint} {self.tag}".strip()


def parse_server_line(line: str) -> Optional[ServerNode]:
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    parts = line.split(None, 1)
    try:
        ep = parse_endpoint(parts[0])
    except (ValueError, IndexError):
        return None
    return ServerNode(ep, parts[1].strip() if len(parts) > 1 else "")


class NamingService:
    """Implementations override :meth:`fetch_servers` (pull model) or run
    their own push loop calling ``self.push(nodes)``."""

    def __init__(self):
        self._watchers: List[Callable[[List[ServerNode]], None]] = []
        self._watch_lock = threading.Lock()
        # serializes deliveries so a watcher never sees an older list
        # after a newer one (watch()'s initial snapshot vs a racing push)
        self._deliver_lock = threading.Lock()
        self._last: Optional[List[ServerNode]] = None
        self._timer_id = 0
        self._stopped = False
        self.refresh_interval_s = DEFAULT_REFRESH_S

    # -- override points ---------------------------------------------------

    def fetch_servers(self) -> Optional[Sequence[ServerNode]]:
        """Return the full current list, or None on transient failure
        (watchers keep the previous list — the reference's degrade
        behavior)."""
        raise NotImplementedError

    def run_once(self) -> None:
        nodes = None
        try:
            nodes = self.fetch_servers()
        except Exception as e:
            LOG.warning("naming fetch failed: %s", e)
        if nodes is not None:
            self.push(list(nodes))

    # -- machinery ---------------------------------------------------------

    def start(self, url_path: str) -> int:
        """Parse/validate the source; begin periodic refresh."""
        self.run_once()
        self._schedule()
        return 0

    def _schedule(self) -> None:
        if self._stopped or self.refresh_interval_s <= 0:
            return
        self._timer_id = global_timer_thread().schedule(
            self._tick, self.refresh_interval_s)

    def _tick(self) -> None:
        if self._stopped:
            return
        self.run_once()
        self._schedule()

    def push(self, nodes: List[ServerNode]) -> None:
        """≈ NamingServiceActions::ResetServers: full-list semantics."""
        with self._deliver_lock:
            with self._watch_lock:
                if self._last is not None and nodes == self._last:
                    return
                self._last = list(nodes)
                watchers = list(self._watchers)
            for w in watchers:
                try:
                    w(list(nodes))
                except Exception:
                    LOG.exception("naming watcher raised")

    def watch(self, fn: Callable[[List[ServerNode]], None]) -> None:
        with self._deliver_lock:
            with self._watch_lock:
                self._watchers.append(fn)
                last = list(self._last) if self._last is not None else None
            if last is not None:
                fn(last)

    def stop(self) -> None:
        self._stopped = True
        if self._timer_id:
            global_timer_thread().unschedule(self._timer_id)

    @property
    def current(self) -> List[ServerNode]:
        with self._watch_lock:
            return list(self._last or [])


def naming_registry():
    return extension("naming_service")


def create_naming_service(url: str) -> Optional[NamingService]:
    """``scheme://rest`` → a STARTED NamingService instance."""
    from ..policy import naming as _builtin   # registers the schemes
    if "://" not in url:
        return None
    scheme, rest = url.split("://", 1)
    factory = naming_registry().find(scheme)
    if factory is None:
        LOG.error("unknown naming scheme %r (known: %s)", scheme,
                  naming_registry().list())
        return None
    ns = factory()
    if ns.start(rest) != 0:
        return None
    return ns
