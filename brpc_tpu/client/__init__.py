"""Client side — Channel/Controller with deadline, retry, backup request,
cancel; naming/load-balancing/circuit-breaking layers on top.

Capability parity with /root/reference/src/brpc/channel.h:160-190 and
controller.h:110: every call is guarded by a versioned correlation id —
response threads, timers, cancellation, and socket failures all rendezvous
through the id lock, never a global table.
"""

from .channel import Channel, ChannelOptions
from .controller import Controller, start_cancel
from .parallel_channel import SKIP, ParallelChannel, SelectiveChannel
from .partition_channel import PartitionChannel

__all__ = ["Channel", "ChannelOptions", "Controller", "start_cancel",
           "ParallelChannel", "SelectiveChannel", "PartitionChannel",
           "SKIP"]
