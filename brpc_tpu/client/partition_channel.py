"""PartitionChannel — key-space sharding over a tagged cluster.

≈ /root/reference/src/brpc/partition_channel.h:46,75,136: servers publish
partition tags ``i/N`` through the naming service; the channel builds one
sub-channel per partition (each load-balancing over that partition's
replicas) and fans a call out to all partitions, merging responses.
DynamicPartitionChannel's scheme mixing (``:136``) is approximated by
re-reading tags on every naming push, so a cluster can migrate N→M
partitions live.

On a TPU pod, ``mesh://`` naming tags each chip ``i/N`` — a
PartitionChannel over it is the control-plane twin of
MeshTransport.scatter/all_gather (the data plane).
"""

from __future__ import annotations

import re
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..butil.logging_util import LOG
from ..butil.status import Errno
from .channel import Channel, ChannelOptions
from .controller import Controller
from .load_balancer import create_load_balancer
from .naming_service import ServerNode, create_naming_service
from .parallel_channel import SKIP, ParallelChannel, default_response_merger

_TAG_RE = re.compile(r"^(\d+)/(\d+)$")


def parse_partition_tag(tag: str) -> Optional[Tuple[int, int]]:
    """First ``i/N`` token of the tag → (index, count)."""
    for token in tag.split():
        m = _TAG_RE.match(token)
        if m:
            return int(m.group(1)), int(m.group(2))
    return None


class _PartitionLB:
    """A fixed-partition view over the shared server list."""

    def __init__(self, lb_name: str, index: int,
                 enable_circuit_breaker: bool = False):
        self.lb = create_load_balancer(lb_name)
        self.lb.use_circuit_breaker = enable_circuit_breaker
        self.index = index

    def select_server(self, cntl):
        return self.lb.select_server(cntl)

    def feedback(self, cntl):
        self.lb.feedback(cntl)


class _PartitionSubChannel(Channel):
    """Channel whose 'cluster' is one partition's replicas."""

    def __init__(self, lb: _PartitionLB,
                 options: Optional[ChannelOptions] = None):
        super().__init__(options)
        self.load_balancer = lb
        self._initialized = True


class PartitionChannel:
    def __init__(self, partition_count: int = 0,
                 options: Optional[ChannelOptions] = None,
                 fail_limit: int = -1):
        self.partition_count = partition_count    # 0 = learn from tags
        self.options = options or ChannelOptions()
        self.fail_limit = fail_limit
        self._ns = None
        self._lb_name = "rr"
        self._lock = threading.Lock()
        self._partitions: Dict[int, _PartitionLB] = {}

    def init(self, naming_url: str, lb_name: str = "rr") -> int:
        from ..policy import load_balancers  # noqa: F401
        from ..policy import naming          # noqa: F401

        self._lb_name = lb_name
        self._ns = create_naming_service(naming_url)
        if self._ns is None:
            return -1
        self._ns.watch(self._on_servers)
        with self._lock:
            ok = bool(self._partitions)
        if not ok:
            LOG.error("no partition-tagged servers at %s", naming_url)
            self._ns.stop()
            self._ns = None
            return -1
        return 0

    def _on_servers(self, nodes: List[ServerNode]) -> None:
        # group by scheme (the N in "i/N"): mixing schemes would shard
        # one key space two ways at once during an N→M migration
        schemes: Dict[int, Dict[int, List[ServerNode]]] = {}
        for n in nodes:
            parsed = parse_partition_tag(n.tag)
            if parsed is None:
                continue
            idx, total = parsed
            if self.partition_count and total != self.partition_count:
                continue                  # foreign partition scheme
            if 0 <= idx < total:
                schemes.setdefault(total, {}).setdefault(
                    idx, []).append(n)
        # adopt the largest scheme with COMPLETE coverage (every
        # partition has at least one replica); else the most complete one
        # (≈ DynamicPartitionChannel's capacity rule, simplified)
        chosen: Dict[int, List[ServerNode]] = {}
        best_key = (-1.0, 0)
        for total, by_part in schemes.items():
            coverage = len(by_part) / total
            if (coverage, total) > best_key:
                best_key = (coverage, total)
                chosen = by_part
        with self._lock:
            stale = set(self._partitions) - set(chosen)
            for idx in stale:
                del self._partitions[idx]
            for idx, members in chosen.items():
                plb = self._partitions.get(idx)
                if plb is None:
                    plb = self._partitions[idx] = _PartitionLB(
                        self._lb_name, idx,
                        self.options.enable_circuit_breaker)
                plb.lb.reset_servers(members)

    @property
    def partitions(self) -> List[int]:
        with self._lock:
            return sorted(self._partitions)

    def call_method(self, method_full: str, request: Any,
                    response_type: Any = None,
                    done: Optional[Callable] = None,
                    cntl: Optional[Controller] = None,
                    call_mapper: Optional[Callable] = None,
                    merger: Optional[Callable] = None) -> Controller:
        """Fan out to every partition (call_mapper(index, None, request)
        shapes per-partition requests, e.g. splitting a key batch)."""
        with self._lock:
            parts = sorted(self._partitions.items())
        pc = ParallelChannel(fail_limit=self.fail_limit)
        for idx, plb in parts:
            sub = _PartitionSubChannel(plb, self.options)
            if call_mapper is not None:
                def mk(i):
                    return lambda _i, _sub, req: call_mapper(i, _sub, req)
                pc.add_channel(sub, call_mapper=mk(idx))
            else:
                pc.add_channel(sub)
        return pc.call_method(method_full, request, response_type,
                              done=done, cntl=cntl, merger=merger)

    def stop(self) -> None:
        if self._ns is not None:
            self._ns.stop()


class DynamicPartitionChannel(PartitionChannel):
    """≈ DynamicPartitionChannel (partition_channel.h:136): during an
    N→M re-partitioning, servers of BOTH schemes coexist in naming; each
    call picks one scheme, weighted by its capacity (replica count), so
    traffic migrates proportionally as the new scheme fills in — instead
    of the base class's single-scheme adoption cliff."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._schemes: Dict[int, Dict[int, _PartitionLB]] = {}
        self._scheme_sizes: Dict[int, int] = {}

    def _on_servers(self, nodes: List[ServerNode]) -> None:
        groups: Dict[int, Dict[int, List[ServerNode]]] = {}
        for n in nodes:
            parsed = parse_partition_tag(n.tag)
            if parsed is None:
                continue
            idx, total = parsed
            if 0 <= idx < total:
                groups.setdefault(total, {}).setdefault(idx, []).append(n)
        with self._lock:
            # only COMPLETE schemes carry traffic (a scheme missing a
            # partition would black-hole part of the key space)
            complete = {t: g for t, g in groups.items() if len(g) == t}
            stale = set(self._schemes) - set(complete)
            for t in stale:
                del self._schemes[t]
                self._scheme_sizes.pop(t, None)
            for t, by_part in complete.items():
                scheme = self._schemes.setdefault(t, {})
                for idx, members in by_part.items():
                    plb = scheme.get(idx)
                    if plb is None:
                        plb = scheme[idx] = _PartitionLB(
                            self._lb_name, idx,
                            self.options.enable_circuit_breaker)
                    plb.lb.reset_servers(members)
                self._scheme_sizes[t] = sum(
                    len(m) for m in by_part.values())
            # keep the base-class view pointing at the largest scheme so
            # .partitions introspection still answers
            if complete:
                biggest = max(complete)
                self._partitions = dict(self._schemes[biggest])

    @property
    def scheme_weights(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._scheme_sizes)

    def call_method(self, method_full: str, request: Any,
                    response_type: Any = None,
                    done: Optional[Callable] = None,
                    cntl: Optional[Controller] = None,
                    call_mapper: Optional[Callable] = None,
                    merger: Optional[Callable] = None) -> Controller:
        from ..butil.fast_rand import fast_rand
        with self._lock:
            total_cap = sum(self._scheme_sizes.values())
            if total_cap <= 0:
                parts = []
            else:
                r = fast_rand() % total_cap
                chosen = None
                for t in sorted(self._schemes):
                    r -= self._scheme_sizes[t]
                    if r < 0:
                        chosen = t
                        break
                parts = sorted(self._schemes[chosen].items())
        if not parts:
            c = cntl or Controller()
            c._fail_before_launch(int(Errno.EINTERNAL),
                                  "no complete partition scheme", done)
            return c
        pc = ParallelChannel(fail_limit=self.fail_limit)
        for idx, plb in parts:
            sub = _PartitionSubChannel(plb, self.options)
            if call_mapper is not None:
                def mk(i):
                    return lambda _i, _sub, req: call_mapper(i, _sub, req)
                pc.add_channel(sub, call_mapper=mk(idx))
            else:
                pc.add_channel(sub)
        return pc.call_method(method_full, request, response_type,
                              done=done, cntl=cntl, merger=merger)
