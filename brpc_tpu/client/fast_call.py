"""Fast unary call lane — the client latency hot path.

≈ the reference's single-digit-µs per-call discipline
(/root/reference/docs/cn/benchmark.md:57: 200-300ns handler cost, most of
the round trip spent in the kernel).  The general Controller path costs a
correlation-id rendezvous, IOBuf framing, protocol detection and several
cross-thread wakeups per call; an echo-class unary RPC on an exclusive
(pooled/short) connection needs none of that:

- the frame is built as one flat ``bytes`` from cached method TLVs,
- the request/response round trip runs inside the native engine's
  ``sync_call`` (writev + read-one-frame with the GIL released); a pure
  Python fallback keeps the lane working without the toolchain,
- the response is decoded inline on the calling thread.

Anything unusual (streams, device attachments, compression, backup
requests, async ``done``, non-tpu_std wire) is rejected by
:func:`eligible` and flows through the full Controller state machine.
Retriable failures retry *inside* the lane with the same policy and
excluded-servers bookkeeping as the slow path.
"""

from __future__ import annotations

import select as _select
import struct
from typing import Any, Optional, Tuple

from ..butil.iobuf import IOBuf
from ..butil.status import Errno
from time import monotonic_ns as _mono_ns
from time import sleep as _sleep

from ..butil.time_utils import monotonic_us
from ..deadline import backoff_ms as _backoff_ms
from ..deadline import cap_timeout_ms as _cap_timeout_ms
from ..transport.socket import Socket
from ..transport.socket_map import (pooled_socket, return_pooled_socket,
                                    short_socket)

from ..protocol.meta import (RpcMeta, TAG_AUTH, TAG_ICI_CONN, TAG_TENANT,
                             TAG_ICI_DESC,
                             TAG_ICI_DOMAIN, TAG_METHOD,
                             TAG_SERVICE, TLV_ATTACHMENT, TLV_CORRELATION,
                             TLV_SPAN, TLV_TIMEOUT, TLV_TRACE, encode_tlv)
from ..protocol.tpu_std import parse_payload, serialize_payload
from ..ici.endpoint import (_process_ack as _ici_process_ack,
                            conn_nonce_of as _conn_nonce_of,
                            ici_enabled as _ici_enabled,
                            local_domain_id as _local_domain_id,
                            prepare_send as _ici_prepare_send,
                            split_device_attachment as _split_device_att)

_MAGIC = b"TRPC"
_MAX_BODY = 512 * 1024 * 1024   # keep in sync with engine.cpp kMaxBody


def _noop() -> None:
    """put_back stand-in for the pinned-socket lane: the socket stays
    pinned to this thread instead of returning to the pool."""

_CID_TAG = TLV_CORRELATION
_ATT_TAG = TLV_ATTACHMENT
_TMO_TAG = TLV_TIMEOUT

_native_mod: Optional[object] = None
_native_tried = False


_HAS_RAW_CALL = False


def _native():
    global _native_mod, _native_tried, _HAS_RAW_CALL
    if not _native_tried:
        _native_tried = True
        try:
            from ..native import load
            _native_mod = load()
        except Exception:
            _native_mod = None
        _HAS_RAW_CALL = hasattr(_native_mod, "raw_call")
    return _native_mod


# ---------------------------------------------------------------------------
# scatter_call fallback telemetry: every ineligible-shape branch in the
# fan-out screening below increments a NAMED reason counter (the
# client-lane mirror of the engine's reason-coded server fallbacks).
# Exposed as the ``native_scatter_fallback_total{reason=...}`` bvar
# family and surfaced on the /native portal page.
# ---------------------------------------------------------------------------

_scatter_fallbacks: dict = {}
import threading as _threading
_scatter_lock = _threading.Lock()

# exposed eagerly: the family must exist in /vars//metrics from process
# start (a scrape keyed on it must not depend on a fallback having
# happened), and eager creation leaves no check-then-create race
from ..bvar.multi_dimension import PassiveDimension as _PassiveDimension

_scatter_var = _PassiveDimension(
    ("reason",), lambda: scatter_fallback_counters(),
    name="native_scatter_fallback_total")


def _scatter_fallback(reason: str) -> bool:
    """Record one named scatter ineligibility; returns False so the
    screening sites read ``return _scatter_fallback("...")``.  The
    lock keeps concurrent fan-out threads from losing increments
    (read-modify-write on a dict slot is not atomic)."""
    with _scatter_lock:
        _scatter_fallbacks[reason] = _scatter_fallbacks.get(reason, 0) + 1
    return False


def scatter_fallback_counters() -> dict:
    """Snapshot of the named scatter_call fallback counters."""
    with _scatter_lock:
        return dict(_scatter_fallbacks)


_fast_cid = 0x46_0000_0000            # distinct range from the IdPool's ids

# (domain bytes, encoded TLV) — the domain id object is cached by
# fabric.local_domain_id, so identity comparison suffices
_domain_tlv_cache: Tuple[Optional[bytes], bytes] = (None, b"")


def _domain_tlv(domain: bytes) -> bytes:
    global _domain_tlv_cache
    cached_domain, cached = _domain_tlv_cache
    if cached_domain is not domain:
        cached = encode_tlv(TAG_ICI_DOMAIN, domain)
        _domain_tlv_cache = (domain, cached)
    return cached


def _next_cid() -> int:
    global _fast_cid
    _fast_cid += 1
    return _fast_cid


def _reserve_cids(n: int) -> int:
    """Reserve ``n`` consecutive correlation ids; returns the first (the
    native batch lane stamps cid_base..cid_base+n-1 itself)."""
    global _fast_cid
    base = _fast_cid + 1
    _fast_cid += n
    return base


def method_tlv(method_full: str, tenant: str = "") -> bytes:
    """Pre-encoded service+method (+ tenant identity, TLV 22) bytes
    (cached on the Channel) — tenant riding the cached prefix means the
    overload plane's fair-admission key costs nothing per call."""
    svc, _, mth = method_full.rpartition(".")
    out = (encode_tlv(TAG_SERVICE, svc.encode())
           + encode_tlv(TAG_METHOD, mth.encode()))
    if tenant:
        out += encode_tlv(TAG_TENANT, tenant.encode())
    return out


def eligible(channel, cntl) -> bool:
    """Cheap static screen; runtime conditions re-checked in run()."""
    opts = channel.options
    ctype = cntl.connection_type or opts.connection_type
    return (opts.protocol == "tpu_std"
            and not opts.ssl and opts.ssl_context is None
            and ctype in ("pooled", "short")
            and not cntl.request_compress_type
            and cntl._stream_to_create is None
            and (cntl.backup_request_ms is None
                 or cntl.backup_request_ms <= 0)
            and (opts.backup_request_ms is None
                 or opts.backup_request_ms <= 0))


def _py_sync_call(sock, frame: bytes,
                  timeout_s: float) -> Tuple[memoryview, int, tuple]:
    """Python fallback for native sync_call: one response frame, with
    TICI credit-return frames (acks for device descriptors this request
    carried; the server redeems in-handler so its ack precedes the
    response) consumed along the way and returned as the third element."""
    import time as _time
    deadline = _time.monotonic() + timeout_s if timeout_s >= 0 else None
    fd = sock.fd
    view = memoryview(frame)
    while view:
        try:
            n = fd.send(view)
            view = view[n:]
        except BlockingIOError:
            left = None if deadline is None else deadline - _time.monotonic()
            if left is not None and left <= 0:
                raise TimeoutError("rpc deadline exceeded")
            _select.select([], [fd], [], left)
    buf = bytearray()
    acks: list = []
    want = 65536          # frame-sized reads once the header is parsed
    while True:
        # drain everything already buffered before blocking again
        while True:
            if len(buf) >= 8 and buf[:4] == b"TICI":
                got, off = _cut_tici_frames(buf)
                if off == 0:
                    break            # partial ack frame: need more bytes
                acks.extend(got)
                del buf[:off]
                continue
            if len(buf) >= 12:
                if buf[:4] != _MAGIC:
                    raise ValueError("unexpected magic on fast-path read")
                body, meta = struct.unpack_from("<II", buf, 4)
                if meta > body:
                    raise ValueError("bad frame sizes")
                if 12 + body - len(buf) > want:
                    want = 12 + body - len(buf)
                if len(buf) >= 12 + body:
                    # drain any trailing TICI frames the greedy recv
                    # pulled in (acks a lazy redeem sent after the
                    # response) — dropping them would desync the
                    # stream.  The response is complete: grace the
                    # deadline for ack bytes already in flight.
                    tdl = None if deadline is None \
                        else max(deadline, _time.monotonic() + 2.0)
                    off = 12 + body
                    while True:
                        avail = len(buf) - off
                        if avail == 0:
                            break
                        if avail >= 4 and buf[off:off + 4] != b"TICI":
                            raise ValueError(
                                "unexpected trailing bytes after response")
                        got, noff = _cut_tici_frames(buf, off)
                        if noff > off:
                            acks.extend(got)
                            off = noff
                            continue
                        # partial trailing ack frame: finish reading it
                        left = None if tdl is None \
                            else tdl - _time.monotonic()
                        if left is not None and left <= 0:
                            raise TimeoutError("rpc deadline exceeded")
                        r, _, _ = _select.select([fd], [], [], left)
                        if not r:
                            raise TimeoutError("rpc deadline exceeded")
                        chunk = fd.recv(65536)
                        if not chunk:
                            raise ConnectionError(
                                "connection closed mid-ack")
                        buf += chunk
                    return (memoryview(buf)[12:12 + body], meta,
                            tuple(acks))
            break
        left = None if deadline is None else deadline - _time.monotonic()
        if left is not None and left <= 0:
            raise TimeoutError("rpc deadline exceeded")
        r, _, _ = _select.select([fd], [], [], left)
        if not r:
            raise TimeoutError("rpc deadline exceeded")
        try:
            chunk = fd.recv(want)
        except BlockingIOError:
            continue
        if not chunk:
            raise ConnectionError("connection closed by peer")
        buf += chunk


def run(channel, cntl, method_full: str, request: Any,
        response_type: Any, method_tlvs: bytes) -> None:
    """Complete the RPC on the calling thread.  Fills ``cntl`` exactly
    like the Controller slow path (response, attachments, error state,
    latency, LB feedback) and sets ``cntl._ended``.  Raises TypeError
    for unserializable requests (caller maps it to EREQUEST)."""
    opts = channel.options
    cntl._channel = channel      # retry policies (ELIMIT fail-fast)
    #                              consult the channel's LB
    if cntl.timeout_ms is None:
        cntl.timeout_ms = opts.timeout_ms
    # deadline inheritance: inside a deadline'd handler the downstream
    # call is capped to the upstream's remaining budget (fail fast at 0)
    cntl.timeout_ms, _amb_expired = _cap_timeout_ms(cntl.timeout_ms)
    if _amb_expired:
        cntl._begin_us = _mono_ns() // 1000
        _finish(channel, cntl, Errno.ERPCTIMEDOUT,
                "inherited deadline already expired (doomed downstream "
                "call failed fast)")
        return
    if cntl.max_retry is None:
        cntl.max_retry = opts.max_retry
    if cntl.connection_type is None:
        cntl.connection_type = opts.connection_type
    begin = _mono_ns() // 1000
    cntl._begin_us = begin
    timeout_ms = cntl.timeout_ms
    deadline_us = begin + timeout_ms * 1000 \
        if timeout_ms and timeout_ms > 0 else None

    if isinstance(request, (bytes, bytearray, memoryview)):
        payload_b = request
    else:
        payload_b = serialize_payload(request).to_bytes()
    att = cntl._req_att
    att_parts: Tuple = ()
    att_len = 0
    if att is not None and len(att):
        # large attachments ride as scatter-gather views — no flattening
        att_parts = tuple(att.backing_views())
        att_len = len(att)
        if len(att_parts) > 56:
            # sync_call caps the iovec count; a many-block attachment
            # flattens rather than poisoning the socket with a ValueError
            att_parts = (att.to_bytes(),)

    domain = _local_domain_id() if _ici_enabled() else b""
    auth = opts.auth_data or b""

    # pre-flight size check (mirrors run_raw): an oversized request must
    # raise a precise client-side EREQUEST, not burn healthy connections
    # on the engine's fail-fast ValueError
    if len(payload_b) + att_len + 96 > _MAX_BODY:
        _finish(channel, cntl, Errno.EREQUEST,
                "payload + attachment exceeds max body")
        return

    nat = _native()
    pooled = cntl.connection_type == "pooled"
    nretry = 0

    def _retry_or_finish(code: int, text: str) -> bool:
        """Shared retry tail (≈ Controller._retry_locked): True = the
        caller should retry the loop, False = the call is finished.
        Mirrors the slow path's retry hardening: the attempt draws a
        channel retry-budget token, and backs off exponentially with
        jitter (inline sleep — this lane owns the calling thread)."""
        nonlocal nretry
        cntl.excluded_servers.add(remote)
        if cntl.retry_policy(cntl, code) and nretry < cntl.max_retry:
            if deadline_us is not None \
                    and _mono_ns() // 1000 >= deadline_us:
                # deadline first, token second: a retry that can never
                # be sent must not drain the channel budget
                _finish(channel, cntl, Errno.ERPCTIMEDOUT,
                        f"deadline {timeout_ms}ms exceeded")
                return False
            if not channel.acquire_retry_token():
                _finish(channel, cntl, code, text)
                return False
            nretry += 1
            cntl.retried_count = nretry
            # fail-fast: ELIMIT/ELAMEDUCK bounces retry immediately on
            # another replica (excluded_servers steers the LB away,
            # and a lame-duck mark removes the draining node) — no
            # backoff, that's the whole point of the fast rejection
            delay_ms = 0.0 if code in (int(Errno.ELIMIT),
                                       int(Errno.ELAMEDUCK)) else \
                _backoff_ms(opts.retry_backoff_ms, nretry,
                            opts.retry_backoff_max_ms)
            if delay_ms > 0:
                if deadline_us is not None:
                    delay_ms = min(delay_ms, max(
                        0.0, (deadline_us - _mono_ns() // 1000) / 1000.0))
                _sleep(delay_ms / 1e3)
            return True
        _finish(channel, cntl, code, text)
        return False

    while True:
        # -- target selection (mirrors Controller._select_remote) --
        if channel.load_balancer is not None:
            remote = channel.load_balancer.select_server(cntl)
        else:
            remote = channel.single_server
        if remote is None:
            _finish(channel, cntl, Errno.EINTERNAL, "no server available")
            return
        cntl.remote_side = remote
        cntl.attempt_remotes[nretry] = remote

        # -- pinned native round trip (the controller lane's fast sub-
        # path): when nothing per-call needs Python-built meta (no
        # device attachment, no ici domain, auth already on the wire),
        # the whole frame build + write + read + response scan runs in
        # C via nat.raw_call on the thread-pinned pooled socket — the
        # same engine call the raw lane uses, carrying the controller's
        # retry/backup-excluded bookkeeping around it.  Trace context
        # is NOT a screening condition: the trace/span TLVs ride the
        # per-call tail the engine serializes verbatim, so tracing a
        # request no longer changes the very path being observed.
        if (pooled and nat is not None and _HAS_RAW_CALL
                and cntl.request_device_attachment is None):
            psid, psock = _raw_socket(remote)
            if psock is not None and (
                    not psock.direct_read or not psock.read_portal.empty()
                    or not psock.write_path_idle()
                    or (auth and getattr(psock, "app_data", None) is None)):
                # converted/busy, or auth must ride this call: un-pin
                # and take the classic build below
                _unpin(remote, psid)
            elif psock is None:
                if _retry_or_finish(int(Errno.EFAILEDSOCKET),
                                    f"connect to {remote} failed"):
                    continue
                return
            else:
                # the tail carries method TLVs plus (when ici is on)
                # this process's domain and the socket's conn nonce —
                # identical wire content to the classic build below,
                # cached per socket+method so steady-state calls reuse
                # the encoded bytes
                # keyed on (method, tenant): the pinned socket is
                # shared across channels, whose tenant TLVs differ
                tail_key = (method_full, opts.tenant)
                tails = getattr(psock, "_cntl_tails", None)
                tail = tails.get(tail_key) if tails is not None \
                    else None
                if tail is None:
                    tail = method_tlvs
                    if domain:
                        tail = (tail + _domain_tlv(domain)
                                + encode_tlv(TAG_ICI_CONN,
                                             _conn_nonce_of(psock)))
                    if tails is None:
                        tails = psock._cntl_tails = {}
                    tails[tail_key] = tail
                if cntl.trace_id:
                    # per-call trace TLVs after the cached tail (never
                    # cached: ids differ per call) — the engine writes
                    # them into the meta region verbatim
                    tail = tail + TLV_TRACE \
                        + struct.pack("<Q", cntl.trace_id)
                    if cntl.span_id:
                        tail += TLV_SPAN \
                            + struct.pack("<Q", cntl.span_id)
                shm_slot = None
                shm_offered = False
                shm_took = False
                if att_len or psock.shm is not None:
                    # shm data plane: eligible same-host attachments
                    # stage into the tx ring and ride as a descriptor
                    # TLV in the tail (negotiation/credit TLVs too).
                    # Retry attempts decline the lane (multi_attempt):
                    # the failed attempt's descriptor may still be
                    # unread on a server whose socket died under us —
                    # restaging could recycle the slot it names
                    from ..transport import shm_ring as _shm
                    extra, wire_att, shm_slot, shm_offered = \
                        _shm.client_prepare(psock,
                                            att if att_len else None,
                                            multi_attempt=nretry > 0)
                    if extra:
                        tail = tail + extra
                    shm_took = bool(att_len) and wire_att is None
                if not att_len or shm_took:
                    att_buf = None
                elif len(att_parts) > 1:
                    att_buf = att.to_bytes()
                else:
                    att_buf = att_parts[0]
                left_ms = 0
                if deadline_us is not None:
                    left_ms = max(1, (deadline_us - _mono_ns() // 1000)
                                  // 1000)
                cid = _next_cid()
                ack0 = psock._take_ack_frame() if psock._pending_acks \
                    else None
                try:
                    ok, buf, nval, dom, acks = nat.raw_call(
                        psock.fd.fileno(), tail, payload_b,
                        att_buf, int(left_ms), cid, ack0)
                except TimeoutError:
                    if shm_slot is not None:
                        from ..transport import shm_ring as _shm
                        _shm.client_complete(shm_slot)
                    psock.set_failed(Errno.ERPCTIMEDOUT, "rpc timeout")
                    psock.release()
                    _finish(channel, cntl, Errno.ERPCTIMEDOUT,
                            f"deadline {timeout_ms}ms exceeded")
                    return
                except (ConnectionError, ValueError, OSError) as e:
                    if shm_slot is not None:
                        from ..transport import shm_ring as _shm
                        _shm.client_complete(shm_slot)
                    psock.set_failed(Errno.EFAILEDSOCKET, str(e))
                    psock.release()
                    code = int(Errno.EFAILEDSOCKET)
                    text = str(e)
                else:
                    if acks:
                        _ici_process_ack(acks, psock)
                    if ok:
                        if shm_slot is not None or shm_offered:
                            # plain success response: settle the slot;
                            # an unanswered offer marks the peer
                            # capability-less
                            from ..transport import shm_ring as _shm
                            _shm.client_complete(shm_slot)
                            if shm_offered:
                                _shm.client_saw_plain_response(psock)
                        if dom:
                            psock.ici_peer_domain = dom
                        body = memoryview(buf)
                        attachment = IOBuf()
                        if nval:
                            attachment.append_user_data(
                                body[len(body) - nval:])
                            body = body[:len(body) - nval]
                        try:
                            cntl.response = parse_payload(bytes(body),
                                                          response_type)
                        except Exception as e:
                            _finish(channel, cntl, Errno.ERESPONSE,
                                    f"response parse failed: {e}")
                            return
                        cntl.response_attachment = attachment
                        _finish(channel, cntl, 0, "")
                        return
                    # unusual response (error / controller-tier tags /
                    # shm descriptor): full decode; socket stays pinned
                    # (healthy frames leave the connection usable)
                    done, code, text = _handle_response(
                        channel, cntl, psock, psid, pooled, buf, nval,
                        cid, response_type, put_back=_noop,
                        shm_slot=shm_slot, shm_offered=shm_offered)
                    if done:
                        return
                if _retry_or_finish(code, text):
                    continue
                return

        sid, rc = pooled_socket(remote) if pooled else short_socket(remote)
        sock = Socket.address(sid)
        code, text = 0, ""
        if sock is None or (rc != 0 and sock.failed):
            code, text = int(Errno.EFAILEDSOCKET), f"connect to {remote} failed"
        elif sock.fd is None and sock.connect_if_not() != 0:
            code, text = int(Errno.EFAILEDSOCKET), f"connect to {remote} failed"
        elif not sock.direct_read or not sock.read_portal.empty() \
                or not sock.write_path_idle():
            # converted to dispatcher-managed reads (an async call used
            # it), carrying buffered bytes, or a queued write (ack
            # flush) still draining: this lane cannot own the fd —
            # route the call through the full state machine
            if sock is not None:
                if pooled:
                    return_pooled_socket(sid)
                else:
                    sock.release()
            _slow_path(channel, cntl, method_full, request, response_type)
            return

        shm_slot = None
        shm_offered = False
        if code == 0:
            # device attachment: post to the window per attempt; the
            # descriptor TLV rides the frame, an inline tail (host-staged
            # fallback) extends the attachment region
            a_len, a_parts = att_len, att_parts
            shm_extra = b""
            if att_len or sock.shm is not None:
                # shm data plane: the user attachment (never the device
                # tail — device frames decline with a named reason)
                # stages into the tx ring and rides as a descriptor.
                # Retry attempts decline the lane (multi_attempt): the
                # failed attempt's descriptor may still be unread on a
                # server whose socket died under us
                from ..transport import shm_ring as _shm
                shm_extra, _wire_att, shm_slot, shm_offered = \
                    _shm.client_prepare(
                        sock, att if att_len else None,
                        device=cntl.request_device_attachment
                        is not None,
                        multi_attempt=nretry > 0)
                if att_len and _wire_att is None:
                    a_len, a_parts = 0, ()
            dev_desc = b""
            if domain:
                # the conn nonce must exist BEFORE any descriptor post
                # binds to it (prepare_send keys off conn_key_of)
                _conn_nonce_of(sock)
            if cntl.request_device_attachment is not None:
                # credit-return TICI frames may sit unread in THIS
                # socket's kernel buffer (lazy redeems after the last
                # response); the window wait below can only be satisfied
                # by processing them, and nothing else reads this
                # exclusively-owned fd — drain first, then post with a
                # bounded wait and fall back to the slow path (where
                # dispatcher reads return credit) instead of starving
                # into EOVERCROWDED
                _drain_acks_nonblocking(sock, deadline_us)
                if sock.failed:
                    # drain found EOF/garbage: the connection is dead —
                    # do NOT post a descriptor bound to it (the credit
                    # would strand until the TTL sweep); let the full
                    # machinery pick a fresh socket, within the time
                    # this attempt has already partly spent
                    sock.release()
                    _slow_path_remaining(channel, cntl, method_full,
                                         request, response_type,
                                         deadline_us, timeout_ms)
                    return
                post_timeout = 2.0 if deadline_us is None else max(
                    0.001, min(2.0, (deadline_us - _mono_ns() // 1000)
                               / 1e6))
                m = RpcMeta()
                try:
                    tail = _ici_prepare_send(
                        sock, m, cntl.request_device_attachment,
                        timeout_s=post_timeout)
                except RuntimeError:
                    if pooled:
                        return_pooled_socket(sid)
                    else:
                        sock.release()
                    _slow_path_remaining(channel, cntl, method_full,
                                         request, response_type,
                                         deadline_us, timeout_ms)
                    return
                dev_desc = m.ici_desc
                if tail is not None:
                    tb = tail.to_bytes()
                    a_parts = a_parts + (tb,)
                    a_len += len(tb)
            cid = _next_cid()
            mb = bytearray(_CID_TAG)
            mb += struct.pack("<Q", cid)
            if a_len:
                mb += _ATT_TAG + struct.pack("<I", a_len)
            mb += method_tlvs
            if shm_extra:
                mb += shm_extra
            if dev_desc:
                mb += encode_tlv(TAG_ICI_DESC, dev_desc)
            if auth and getattr(sock, "app_data", None) is None:
                mb += encode_tlv(TAG_AUTH, auth)
                sock.app_data = "authed"
            if deadline_us is not None:
                left_ms = max(1, (deadline_us - _mono_ns() // 1000)
                              // 1000)
                mb += _TMO_TAG + struct.pack("<I", left_ms)
            if domain:
                mb += _domain_tlv(domain)
                # conn nonce: the proxy/NAT-safe identity descriptor
                # binding keys off (needed before any post on this
                # attempt; cached on the socket after the first call)
                mb += encode_tlv(TAG_ICI_CONN, _conn_nonce_of(sock))
            if cntl.trace_id:
                mb += TLV_TRACE + struct.pack("<Q", cntl.trace_id)
            if cntl.span_id:
                mb += TLV_SPAN + struct.pack("<Q", cntl.span_id)
            header = _MAGIC + struct.pack(
                "<II", len(mb) + len(payload_b) + a_len, len(mb))
            timeout_s = -1.0 if deadline_us is None \
                else max(0.001, (deadline_us - _mono_ns() // 1000) / 1e6)
            # acks this side owes from earlier redemptions on this
            # connection ride in front of the request (we own the fd —
            # the only safe writer for a direct-read socket)
            ack0 = sock._take_ack_frame() if sock._pending_acks else None
            head_parts = (ack0, header) if ack0 is not None else (header,)
            try:
                if nat is not None:
                    res = nat.sync_call(
                        sock.fd.fileno(),
                        head_parts + (bytes(mb), payload_b) + a_parts,
                        timeout_s)
                else:
                    res = _py_sync_call(
                        sock,
                        b"".join(head_parts + (bytes(mb), payload_b)
                                 + a_parts),
                        timeout_s)
                buf, meta_size = res[0], res[1]
                if len(res) > 2 and res[2]:
                    _ici_process_ack(res[2], sock)   # window credit back
            except TimeoutError:
                # the posted descriptor is NOT released: the request
                # usually reached the server, whose in-flight handler
                # may still redeem it — settle/TTL own reclamation
                # (same semantics as the Controller slow path)
                if shm_slot is not None:
                    from ..transport import shm_ring as _shm
                    _shm.client_complete(shm_slot)
                sock.set_failed(Errno.ERPCTIMEDOUT, "rpc timeout")
                sock.release()
                _finish(channel, cntl, Errno.ERPCTIMEDOUT,
                        f"deadline {timeout_ms}ms exceeded")
                return
            except (ConnectionError, ValueError, OSError) as e:
                if shm_slot is not None:
                    from ..transport import shm_ring as _shm
                    _shm.client_complete(shm_slot)
                sock.set_failed(Errno.EFAILEDSOCKET, str(e))
                sock.release()
                code, text = int(Errno.EFAILEDSOCKET), str(e)

        if code == 0:
            done, code, text = _handle_response(
                channel, cntl, sock, sid, pooled, buf, meta_size, cid,
                response_type, shm_slot=shm_slot,
                shm_offered=shm_offered)
            if done:
                return

        # -- retriable failure: shared tail --
        if _retry_or_finish(code, text):
            continue
        return


def _handle_response(channel, cntl, sock, sid: int, pooled: bool, buf,
                     meta_size: int, cid: int, response_type: Any,
                     put_back=None, shm_slot=None,
                     shm_offered: bool = False) -> Tuple[bool, int, str]:
    """Decode one response frame.  Returns (done, code, text); done=False
    means a retriable failure the caller's loop should handle.
    ``put_back`` overrides how a healthy socket is handed back (the
    pinned-socket lane passes a no-op: the pin IS the checkout).
    ``shm_slot``/``shm_offered``: the request's shm data-plane state —
    settled here (every exit path) against the response meta."""
    if put_back is not None:
        _put_back = put_back
    else:
        def _put_back():
            if pooled:
                return_pooled_socket(sid)
            else:
                sock.release()

    def _complete(raw: bytes, attachment: IOBuf) -> Tuple[bool, int, str]:
        """Shared completion tail: parse the payload, hand the socket
        back, finish the call (success or parse failure)."""
        try:
            cntl.response = parse_payload(raw, response_type)
        except Exception as e:
            _put_back()
            _finish(channel, cntl, Errno.ERESPONSE,
                    f"response parse failed: {e}")
            return True, 0, ""
        cntl.response_attachment = attachment
        _put_back()
        _finish(channel, cntl, 0, "")
        return True, 0, ""

    mv = memoryview(buf)
    scan = _scan_raw_resp(mv[:meta_size])
    if scan is not None:
        # success response with nothing controller-tier in the meta:
        # skip the RpcMeta object entirely (the common echo shape)
        if shm_slot is not None or shm_offered:
            # plain success response: settle the staged slot; an offer
            # answered without an accept marks the peer capability-less
            from ..transport import shm_ring as _shm
            _shm.client_complete(shm_slot)
            if shm_offered:
                _shm.client_saw_plain_response(sock)
        rcid, natt, dom = scan
        if rcid != cid:
            sock.set_failed(Errno.ERESPONSE, "response cid mismatch")
            sock.release()
            return False, int(Errno.EFAILEDSOCKET), "cid mismatch"
        if dom:
            sock.ici_peer_domain = dom
        body = mv[meta_size:]
        attachment = IOBuf()
        if natt:
            if natt > len(body):
                sock.set_failed(Errno.ERESPONSE,
                                "attachment size exceeds body")
                sock.release()
                return False, int(Errno.ERESPONSE), "malformed response"
            attachment.append_user_data(body[len(body) - natt:])
            body = body[:len(body) - natt]
        return _complete(bytes(body), attachment)
    meta = RpcMeta.decode(bytes(mv[:meta_size]))
    if meta is None or meta.correlation_id != cid:
        if shm_slot is not None:
            from ..transport import shm_ring as _shm
            _shm.client_complete(shm_slot)
        sock.set_failed(Errno.ERESPONSE, "undecodable response meta")
        sock.release()
        return False, int(Errno.EFAILEDSOCKET), "undecodable response"
    shm_view = shm_settle = None
    if (meta.shm_offer or meta.shm_accept or meta.shm_desc
            or shm_offered or shm_slot is not None):
        from ..transport import shm_ring as _shm
        try:
            shm_view, shm_settle = _shm.client_on_response_meta(
                sock, meta,
                offered_now=shm_offered and not meta.error_code,
                staged_slot=shm_slot)
        except _shm.ShmDescriptorError as e:
            sock.set_failed(Errno.ERESPONSE, str(e))
            sock.release()
            return False, int(Errno.ERESPONSE), str(e)
    if meta.ici_domain:
        sock.ici_peer_domain = meta.ici_domain
    _mark_lame(meta, cntl.remote_side)
    if meta.error_code:
        # full frame consumed — the connection itself is healthy
        _put_back()
        return False, meta.error_code, meta.error_text
    body = mv[meta_size:]
    if shm_view is not None:
        # response attachment resolved from shared memory (zero-copy);
        # the ring slot recycles when this buffer is dropped
        attachment = _shm.wrap_view_iobuf(shm_view, shm_settle)
    else:
        attachment = IOBuf()
    if meta.attachment_size:
        n = meta.attachment_size
        if n > len(body):
            if meta.ici_desc:
                # malformed frame still carried a posted descriptor:
                # return the peer's window credit before bailing
                from ..ici.endpoint import ack_unused
                ack_unused(meta, sid)
            sock.set_failed(Errno.ERESPONSE, "attachment size exceeds body")
            sock.release()
            return False, int(Errno.ERESPONSE), "malformed response"
        # zero-copy: the attachment view keeps the frame buffer alive
        attachment.append_user_data(body[len(body) - n:])
        body = body[:len(body) - n]
    if meta.ici_desc:
        attachment, cntl.response_device_attachment = \
            _split_device_att(meta, attachment, sid)
    raw = bytes(body)
    if meta.compress_type:
        from ..protocol import compress as compress_mod
        raw = compress_mod.decompress(raw, meta.compress_type)
        if raw is None:
            _put_back()
            _finish(channel, cntl, Errno.ERESPONSE,
                    "undecompressable response")
            return True, 0, ""
    return _complete(raw, attachment)


_ELAMEDUCK_CODE = int(Errno.ELAMEDUCK)
_lame_registry = None        # resolved once: the batch lanes decode a
#                              meta per item, so per-call import/
#                              accessor machinery would tax them


def _mark_lame(meta, remote) -> None:
    """Operability plane, pinned-lane half: a decoded response meta
    carrying the lame-duck TLV (or an ELAMEDUCK rejection) removes the
    draining node from LB selection immediately — the plain-scan fast
    shape can never carry the TLV, so this only runs on the full-decode
    sub-paths.  A clean decoded response CLEARS a stale mark (restarted
    successor on the same address; no-op when unmarked — clear()'s
    unmarked exit is one dict read)."""
    global _lame_registry
    ducks = _lame_registry
    if ducks is None:
        from .naming_service import global_lame_ducks
        ducks = _lame_registry = global_lame_ducks()
    if meta.lame_duck or meta.error_code == _ELAMEDUCK_CODE:
        ducks.mark(remote)
    elif not meta.error_code and remote is not None:
        ducks.clear(remote)


def _breaker_feed(channel, remote, code: int, latency_us: int = 0) -> None:
    """The pinned raw/scatter lanes have no LB in the path to route
    health feedback — feed the GLOBAL circuit-breaker map directly
    (keyed by endpoint, so cluster channels sharing this backend see
    the flap), gated on the channel's enable_circuit_breaker exactly
    like LB-routed feedback."""
    if remote is None or not channel.options.enable_circuit_breaker:
        return
    from .circuit_breaker import global_circuit_breaker_map
    global_circuit_breaker_map().on_call(remote, int(code), latency_us)


def _finish(channel, cntl, code, text: str) -> None:
    if code:
        cntl.set_failed(code, text)
    cntl.latency_us = _mono_ns() // 1000 - cntl._begin_us
    if channel.load_balancer is not None:
        channel.load_balancer.feedback(cntl)
    else:
        _breaker_feed(channel, cntl.remote_side, int(code),
                      cntl.latency_us)
    if not code:
        channel.on_call_success()      # refill the retry budget
    cntl._signal_ended()


def _slow_path(channel, cntl, method_full, request, response_type) -> None:
    """Escape hatch: run the full Controller machinery."""
    payload = serialize_payload(request)
    cntl._launch(channel, method_full, payload, response_type, None)
    cntl._sync_wait()


def _slow_path_remaining(channel, cntl, method_full, request,
                         response_type, deadline_us, timeout_ms) -> None:
    """Escape hatch taken AFTER this lane already burned wall time
    (window waits, drains): cap the controller attempt to the original
    deadline — _launch resets the clock, so without this a 1s-deadline
    call could run ~2s."""
    if deadline_us is not None:
        left_ms = (deadline_us - _mono_ns() // 1000) // 1000
        if left_ms <= 0:
            _finish(channel, cntl, Errno.ERPCTIMEDOUT,
                    f"deadline {timeout_ms}ms exceeded")
            return
        cntl.timeout_ms = max(1, int(left_ms))
    _slow_path(channel, cntl, method_full, request, response_type)


def run_scatter(branches, timeout_ms: Optional[int]) -> bool:
    """Fan-out fast lane for ParallelChannel: write every branch's
    request first, then collect the responses — wire-level parallelism
    from ONE thread, no dispatcher/fiber machinery per branch.

    ``branches``: list of (channel, cntl, method_full, request,
    response_type).  Returns False (nothing sent) when any branch is
    ineligible — the caller falls back to the async path.  On True,
    every branch cntl is completed (success or failure; no retries —
    ParallelChannel's fail_limit is the recovery story here).

    Two sub-lanes: the PINNED NATIVE scatter (engine scatter_call —
    frames built/written/read in C on thread-pinned sockets, the whole
    fan-out costing Python one call; VERDICT r5 Next #7) when every
    branch fits its shape, else the classic per-branch build below."""
    for channel, cntl, _m, request, _r in branches:
        if not eligible(channel, cntl):
            return _scatter_fallback("ineligible_cntl")
        if channel.load_balancer is not None:
            return _scatter_fallback("load_balancer")
        if cntl.request_device_attachment is not None:
            # scatter frames carry no descriptor logic
            return _scatter_fallback("device_attachment")
        if not isinstance(request, (bytes, bytearray, memoryview)):
            return _scatter_fallback("nonbytes_request")
    for channel, cntl, _m, _req, _r in branches:
        if cntl.trace_id:
            # traced fan-out: each branch opens its own client span
            # (parented to whatever span id the branch carried in —
            # the fan-out root), and the branch's OWN span id rides
            # the wire so every sub-server span links to its branch.
            # Both sub-lanes below serialize the trace TLVs natively.
            cntl._begin_trace_span(_m)
    nat = _native()
    if nat is not None and hasattr(nat, "scatter_call") \
            and _scatter_native(branches, timeout_ms, nat):
        return True
    inflight = []      # (channel, cntl, sock, sid, cid, response_type)
    for channel, cntl, method_full, request, response_type in branches:
        opts = channel.options
        if cntl.timeout_ms is None:
            cntl.timeout_ms = timeout_ms or opts.timeout_ms
        cntl.connection_type = cntl.connection_type or opts.connection_type
        cntl._begin_us = monotonic_us()
        remote = channel.single_server
        cntl.remote_side = remote
        pooled = cntl.connection_type == "pooled"
        sock = None
        for _redraw in range(2):
            sid, rc = pooled_socket(remote) if pooled \
                else short_socket(remote)
            s = Socket.address(sid)
            if s is None or (rc != 0 and s.failed) \
                    or (s.fd is None and s.connect_if_not() != 0):
                if s is not None:
                    s.release()
                break                      # real connect failure
            if not s.direct_read:
                # a dispatcher/lane-converted connection drifted back
                # into the pool (an async call used it): it can never
                # serve the sync scatter lanes again — retire it and
                # draw a fresh one instead of failing the branch
                s.release()
                continue
            if not s.read_portal.empty() or not s.write_path_idle():
                # carries buffered state another path owns: hand it
                # back untouched, fail the branch like before
                s.release()
                break
            sock = s
            break
        if sock is None:
            _finish(channel, cntl, Errno.EFAILEDSOCKET,
                    f"connect to {remote} failed")
            continue
        tlv = channel._method_tlvs.get(method_full)
        if tlv is None:
            tlv = channel._method_tlvs[method_full] = \
                method_tlv(method_full, channel.options.tenant)
        cid = _next_cid()
        mb = _CID_TAG + struct.pack("<Q", cid) + tlv
        if cntl.timeout_ms and cntl.timeout_ms > 0:
            mb += _TMO_TAG + struct.pack("<I", int(cntl.timeout_ms))
        if cntl.trace_id:
            mb += TLV_TRACE + struct.pack("<Q", cntl.trace_id)
            if cntl.span_id:
                mb += TLV_SPAN + struct.pack("<Q", cntl.span_id)
        frame = (_MAGIC
                 + struct.pack("<II", len(mb) + len(request), len(mb))
                 + mb + request)
        ack0 = sock._take_ack_frame() if sock._pending_acks else None
        if ack0 is not None:
            frame = ack0 + frame
        try:
            _send_all(sock, frame, (cntl.timeout_ms or 1000) / 1e3)
        except (OSError, TimeoutError) as e:
            sock.set_failed(Errno.EFAILEDSOCKET, str(e))
            sock.release()
            _finish(channel, cntl, Errno.EFAILEDSOCKET, f"send: {e}")
            continue
        inflight.append((channel, cntl, sock, sid, cid, response_type,
                         pooled))
    # phase 2: collect responses (arrival order ≈ completion order)
    for channel, cntl, sock, sid, cid, response_type, pooled in inflight:
        timeout_s = max(0.001, (cntl.timeout_ms or 1000) / 1e3
                        - (monotonic_us() - cntl._begin_us) / 1e6)
        try:
            if nat is not None:
                res = nat.sync_call(sock.fd.fileno(), (), timeout_s)
            else:
                res = _py_sync_call(sock, b"", timeout_s)
            buf, meta_size = res[0], res[1]
            if len(res) > 2 and res[2]:
                _ici_process_ack(res[2], sock)
        except TimeoutError:
            sock.set_failed(Errno.ERPCTIMEDOUT, "rpc timeout")
            sock.release()
            _finish(channel, cntl, Errno.ERPCTIMEDOUT,
                    f"deadline {cntl.timeout_ms}ms exceeded")
            continue
        except (ConnectionError, ValueError, OSError) as e:
            sock.set_failed(Errno.EFAILEDSOCKET, str(e))
            sock.release()
            _finish(channel, cntl, Errno.EFAILEDSOCKET, str(e))
            continue
        done, code, text = _handle_response(channel, cntl, sock, sid,
                                            pooled, buf, meta_size, cid,
                                            response_type)
        if not done:
            _finish(channel, cntl, code, text)
    return True


_SC_ERRNO = {1: Errno.ERPCTIMEDOUT, 2: Errno.EFAILEDSOCKET,
             3: Errno.ERESPONSE}


def _scatter_native(branches, timeout_ms: Optional[int], nat) -> bool:
    """Pinned-socket native scatter-gather: sub-call frames are built,
    written and read by the engine's scatter_call on the raw lane's
    thread-pinned connections — no pool checkout/return per call, no
    Python frame build per branch, and all branch servers work
    concurrently (every request is on the wire before the first
    response is read).  Returns False when this call's shape needs the
    classic per-branch path (busy/converted sockets, first-call auth,
    a repeated remote — pinning is per (thread, remote) so two
    branches to one server need two pooled checkouts); nothing has
    been written or completed by then.  On True every branch cntl is
    completed."""
    screened = []      # (channel, cntl, sock, sid, method_full,
    #                     request, response_type)
    seen_fds = set()
    timeouts = set()
    for channel, cntl, method_full, request, response_type in branches:
        opts = channel.options
        if opts.auth_data:
            # verify-on-first rides the classic build
            return _scatter_fallback("auth_on_first")
        if len(request) + 96 > _MAX_BODY:
            # oversized: classic path owns the error
            return _scatter_fallback("oversized_request")
        if cntl.timeout_ms is None:
            cntl.timeout_ms = timeout_ms or opts.timeout_ms
        # one shared deadline covers the scatter read loop: branches
        # with DIFFERING per-branch deadlines keep the classic path,
        # which enforces each branch's own remaining time
        timeouts.add(cntl.timeout_ms)
        if len(timeouts) > 1:
            return _scatter_fallback("mixed_deadlines")
        cntl.connection_type = cntl.connection_type or opts.connection_type
        cntl._begin_us = monotonic_us()
        remote = channel.single_server
        if remote is None:
            # classic path reports the missing server
            return _scatter_fallback("no_single_server")
        cntl.remote_side = remote
        sid, sock = _raw_socket(remote)
        if sock is None:
            # classic path reports the connect failure
            return _scatter_fallback("connect_failed")
        if not sock.direct_read or not sock.read_portal.empty() \
                or not sock.write_path_idle():
            _unpin(remote, sid)
            return _scatter_fallback("socket_busy")
        fd = sock.fd.fileno()
        if fd in seen_fds:
            return _scatter_fallback("repeated_remote")
        seen_fds.add(fd)
        screened.append((channel, cntl, sock, sid, method_full, request,
                         response_type))
    # commit point: build items (cids, cached tails, pending-ack leads)
    domain = _local_domain_id() if _ici_enabled() else b""
    prep = []
    items = []
    timeout_s = 0.001
    for channel, cntl, sock, sid, method_full, request, rtype in screened:
        # the socket tail cache keys on (method, tenant): sockets are
        # shared across channels, and two channels naming different
        # tenants must never reuse each other's cached TLV prefix
        tail_key = (method_full, channel.options.tenant)
        tails = getattr(sock, "_cntl_tails", None)
        tail = tails.get(tail_key) if tails is not None else None
        if tail is None:
            tail = channel._method_tlvs.get(method_full)
            if tail is None:
                tail = channel._method_tlvs[method_full] = \
                    method_tlv(method_full, channel.options.tenant)
            if domain:
                tail = (tail + _domain_tlv(domain)
                        + encode_tlv(TAG_ICI_CONN, _conn_nonce_of(sock)))
            if tails is None:
                tails = sock._cntl_tails = {}
            tails[tail_key] = tail
        if cntl.trace_id:
            # per-branch trace TLVs after the cached tail (never
            # cached: each branch's span id is unique) — scatter_call
            # serializes them into the meta region verbatim, so a
            # traced fan-out emits N properly-parented child spans
            # without leaving the native lane
            tail = tail + TLV_TRACE + struct.pack("<Q", cntl.trace_id)
            if cntl.span_id:
                tail += TLV_SPAN + struct.pack("<Q", cntl.span_id)
        cid = _next_cid()
        ack0 = sock._take_ack_frame() if sock._pending_acks else None
        items.append((sock.fd.fileno(), tail, request, None, cid, ack0))
        prep.append((channel, cntl, sock, sid, cid, rtype))
        timeout_s = max(timeout_s, (cntl.timeout_ms or 1000) / 1e3)
    try:
        results = nat.scatter_call(items, timeout_s)
    except Exception as e:
        # argument-level failure after frames may be partially written:
        # the pinned connections cannot be trusted — fail every branch
        for channel, cntl, sock, sid, cid, rtype in prep:
            sock.set_failed(Errno.EFAILEDSOCKET, str(e))
            sock.release()
            _finish(channel, cntl, Errno.EFAILEDSOCKET, str(e))
        return True
    for (channel, cntl, sock, sid, cid, rtype), res in zip(prep, results):
        ok = res[0]
        if ok is None:
            errkind, text = res[1], res[2]
            code = _SC_ERRNO.get(errkind, Errno.EFAILEDSOCKET)
            sock.set_failed(code, text)
            sock.release()
            if errkind == 1:
                _finish(channel, cntl, Errno.ERPCTIMEDOUT,
                        f"deadline {cntl.timeout_ms}ms exceeded")
            else:
                _finish(channel, cntl, code, text)
            continue
        acks = res[4]
        if acks:
            _ici_process_ack(acks, sock)
        if ok:
            buf, natt, dom = res[1], res[2], res[3]
            if dom:
                sock.ici_peer_domain = dom
            body = memoryview(buf)
            attachment = IOBuf()
            if natt:
                attachment.append_user_data(body[len(body) - natt:])
                body = body[:len(body) - natt]
            try:
                cntl.response = parse_payload(bytes(body), rtype)
            except Exception as e:
                _finish(channel, cntl, Errno.ERESPONSE,
                        f"response parse failed: {e}")
                continue
            cntl.response_attachment = attachment
            _finish(channel, cntl, 0, "")
            continue
        # unusual response (errors / controller-tier tags): full decode;
        # a healthy frame leaves the connection pinned (put_back no-op)
        done, code, text = _handle_response(channel, cntl, sock, sid,
                                            True, res[1], res[2], cid,
                                            rtype, put_back=_noop)
        if not done:
            _finish(channel, cntl, code, text)
    return True


def _cut_tici_frames(buf, off: int = 0) -> Tuple[list, int]:
    """Cut complete TICI credit-return frames from ``buf[off:]``.
    Returns (ack ids, new offset past the consumed frames); stops at the
    first incomplete frame or non-TICI byte.  Raises ValueError on an
    oversized count (protocol desync)."""
    acks: list = []
    while len(buf) - off >= 8 and bytes(buf[off:off + 4]) == b"TICI":
        (cnt,) = struct.unpack_from("<I", buf, off + 4)
        if cnt > 1 << 20:
            raise ValueError("oversized ack frame")
        total = 8 + 8 * cnt
        if len(buf) - off < total:
            break
        acks.extend(struct.unpack_from(f"<{cnt}Q", buf, off + 8))
        off += total
    return acks, off


def _drain_acks_nonblocking(sock, deadline_us: Optional[int] = None) -> None:
    """Consume TICI credit-return frames already buffered in the kernel
    for this exclusively-owned fd.  Between calls the only legal inbound
    bytes are acks, so a partial frame is finished with a short blocking
    wait (the sender wrote it atomically; completion is imminent) capped
    by the caller's remaining RPC deadline.  On protocol desync or EOF
    the socket is failed — callers must check ``sock.failed`` before
    using the connection further."""
    import time as _time
    fd = sock.fd
    if fd is None:
        return
    buf = bytearray()
    deadline = None
    while True:
        try:
            chunk = fd.recv(65536)
        except (BlockingIOError, InterruptedError):
            chunk = None
        except OSError as e:
            # ECONNRESET etc.: the connection is dead — fail the socket
            # so the caller's guard sees it (a silent return would let a
            # descriptor be posted onto the corpse)
            sock.set_failed(Errno.EFAILEDSOCKET, f"drain: {e}")
            return
        if chunk:
            buf += chunk
        elif chunk == b"":
            sock.set_failed(Errno.EFAILEDSOCKET, "closed while draining")
            return
        try:
            acks, off = _cut_tici_frames(buf)
        except ValueError:
            sock.set_failed(Errno.ERESPONSE, "oversized ack frame")
            return
        if acks:
            _ici_process_ack(acks, sock)
        del buf[:off]
        if not buf:
            if chunk is None:
                return               # kernel buffer dry, nothing partial
            continue                 # maybe more already buffered
        if bytes(buf[:4]) != b"TICI"[:len(buf[:4])]:
            sock.set_failed(Errno.ERESPONSE,
                            "unexpected bytes while idle")
            return
        # partial ack frame: give the in-flight bytes a moment, but
        # never overshoot the RPC deadline the caller is living under
        if deadline is None:
            deadline = _time.monotonic() + 2.0
            if deadline_us is not None:
                deadline = min(
                    deadline,
                    _time.monotonic()
                    + max(0.001, (deadline_us - _mono_ns() // 1000) / 1e6))
        left = deadline - _time.monotonic()
        if left <= 0:
            sock.set_failed(Errno.ERESPONSE, "truncated ack frame")
            return
        _select.select([fd], [], [], left)


def _send_all(sock, frame: bytes, timeout_s: float) -> None:
    """Blocking-with-deadline send of one frame on a non-blocking fd."""
    import time as _time
    fd = sock.fd
    view = memoryview(frame)
    deadline = _time.monotonic() + timeout_s
    while view:
        try:
            n = fd.send(view)
            view = view[n:]
        except (BlockingIOError, InterruptedError):
            left = deadline - _time.monotonic()
            if left <= 0:
                raise TimeoutError("send timed out")
            _select.select([], [fd], [], left)


def _scan_raw_resp(data):
    """Minimal TLV walk of a success-response meta: returns
    ``(cid, att_size, ici_domain_or_None)``, or None when any tag
    beyond correlation/attachment/ici-domain is present (errors,
    descriptors, compression → full RpcMeta decode)."""
    cid = 0
    att = 0
    dom = None
    off, end = 0, len(data)
    try:
        while off < end:
            tag = data[off]
            (ln,) = struct.unpack_from("<I", data, off + 1)
            off += 5
            if off + ln > end:
                return None
            if tag == 1:
                (cid,) = struct.unpack_from("<Q", data, off)
            elif tag == 3:
                (att,) = struct.unpack_from("<I", data, off)
            elif tag == 15:
                dom = bytes(data[off:off + ln])
            else:
                return None
            off += ln
    except (struct.error, IndexError):
        return None
    return cid, att, dom


_tls_raw = __import__("threading").local()


_unpin_pending: "deque" = __import__("collections").deque()


def _unpin_all(sids_map: dict) -> None:
    """Finalizer body: park a dead thread's pinned sockets for later
    return (the map outlives the wrapper; see _PinnedSocks).

    Runs from a weakref finalizer, i.e. potentially mid-GC at an
    arbitrary allocation point — possibly while THIS thread already
    holds the socket pool's non-reentrant lock.  So it must not call
    back into the pool; it only enqueues the sids, and the next
    _raw_socket call drains them outside GC context."""
    _unpin_pending.extend(sids_map.values())
    sids_map.clear()


def _unpin(remote, sid: int) -> None:
    """Dissolve this thread's pin on ``sid`` and hand the socket back to
    the pool (the single place the pin/un-pin discipline lives)."""
    cache = getattr(_tls_raw, "socks", None)
    if cache is not None and cache.get(remote) == sid:
        del cache[remote]
    return_pooled_socket(sid)


def _drain_unpinned() -> None:
    while True:
        try:
            sid = _unpin_pending.popleft()
        except IndexError:
            return
        s = Socket.address(sid)
        if s is not None and not s.failed:
            return_pooled_socket(sid)


# The raw lane may go quiet after worker threads die (process switches
# to the full path, or idles) — without a periodic drain their parked
# sockets would stay checked out of the pool forever.
_drain_task = None
_drain_task_lock = __import__("threading").Lock()


def _ensure_drain_task() -> None:
    global _drain_task
    if _drain_task is None:
        with _drain_task_lock:
            if _drain_task is None:
                from ..butil.periodic_task import PeriodicTask
                _drain_task = PeriodicTask(5.0, _drain_unpinned)


class _PinnedSocks(dict):
    """Thread-pinned {remote: sid} map.  When the owning thread dies its
    thread-locals are dropped — a plain dict would strand the checked-out
    pooled sockets (one leaked fd per thread per remote, forever).  A
    weakref finalizer returns them to the pool instead; it closes over a
    plain inner mirror of the sids (the wrapper itself is unreachable by
    the time the finalizer runs)."""

    def __init__(self):
        super().__init__()
        import weakref
        self._mirror: dict = {}
        self._finalizer = weakref.finalize(self, _unpin_all, self._mirror)
        _ensure_drain_task()

    def __setitem__(self, k, v):
        super().__setitem__(k, v)
        self._mirror[k] = v

    def __delitem__(self, k):
        super().__delitem__(k)
        self._mirror.pop(k, None)

    def pop(self, k, *default):
        self._mirror.pop(k, None)
        return super().pop(k, *default)


def _raw_socket(remote, ssl_none=True):
    """The raw lane's connection: checked out of the shared pool once
    and PINNED to this thread (≈ the reference's client-in-bthread
    keeping a connection hot) — steady-state calls skip the pool's
    get/put locking entirely.  Other threads check out their own; the
    pinned socket returns to circulation only by failing or when the
    owning thread exits (finalizer on the per-thread map)."""
    if _unpin_pending:
        _drain_unpinned()
    cache = getattr(_tls_raw, "socks", None)
    if cache is None:
        cache = _tls_raw.socks = _PinnedSocks()
    sid = cache.get(remote)
    if sid is not None:
        s = Socket.address(sid)
        if s is not None and not s.failed and s.fd is not None \
                and s.direct_read:
            return sid, s
        cache.pop(remote, None)
        if s is not None and not s.failed and not s.direct_read:
            return_pooled_socket(sid)     # converted: back to the pool
    sid, rc = pooled_socket(remote)
    s = Socket.address(sid)
    if s is None or (rc != 0 and s.failed) \
            or (s.fd is None and s.connect_if_not() != 0):
        if s is not None:
            s.release()
        return sid, None
    cache[remote] = sid
    return sid, s


def run_raw(channel, method_full: str, payload, attachment=b"",
            timeout_ms: Optional[int] = None):
    """Raw latency-lane unary call — the client half of @raw_method.

    ``payload``/``attachment`` are bytes-like; returns
    ``(response_view, attachment_view)`` — zero-copy views into the
    response frame.  Raises RpcError on failure.  One attempt, no
    retries/backup: this is the perf lane; resilience needs call_method.
    Single-server channels only (no LB selection in the path)."""
    from .channel import RpcError

    # pre-flight size check IN PYTHON: an oversized argument must raise
    # a precise client-side error without touching the pinned socket
    # (the engine's own kMaxBody check raises ValueError, which the
    # transport-error handler below would misread as a socket failure)
    na0 = len(attachment) if attachment is not None else 0
    if len(payload) + na0 + 96 > _MAX_BODY:
        raise RpcError(int(Errno.EREQUEST),
                       "payload + attachment exceeds max body")

    opts = channel.options
    if timeout_ms is None:
        timeout_ms = opts.timeout_ms
    # deadline inheritance: the raw lane fails fast too when the
    # enclosing handler's budget is gone, and never outlives it
    timeout_ms, _amb_expired = _cap_timeout_ms(timeout_ms)
    if _amb_expired:
        raise RpcError(int(Errno.ERPCTIMEDOUT),
                       "inherited deadline already expired (doomed "
                       "downstream call failed fast)")
    remote = channel.single_server

    def _full_path():
        # controller machinery serves the same call (TLS, other wire
        # protocols, cluster channels, converted/busy connections)
        from .controller import Controller
        cntl = Controller()
        cntl.timeout_ms = timeout_ms
        if attachment is not None and len(attachment):
            cntl.request_attachment = IOBuf(attachment)
        c = channel.call_method(method_full, bytes(payload), cntl=cntl)
        if c.failed:
            raise RpcError(c.error_code, c.error_text)
        return memoryview(c.response), \
            memoryview(c.response_attachment.to_bytes())

    if remote is None or opts.protocol != "tpu_std" or opts.ssl \
            or opts.ssl_context is not None:
        return _full_path()
    tlv = channel._method_tlvs.get(method_full)
    if tlv is None:
        # include the tenant TLV like every other populator of this
        # shared method-keyed cache: whichever lane caches first pins
        # the prefix for all of them, and a tenant-less run_raw entry
        # would silently strip TLV 22 from later call_method traffic
        # (the raw server kinds tolerate-and-ignore the tag)
        tlv = channel._method_tlvs[method_full] = \
            method_tlv(method_full, opts.tenant)
    sid, sock = _raw_socket(remote)
    if sock is None:
        # connect failures are health signal too: without this feed a
        # fully-dead backend reached only through the raw lane would
        # never trip the breaker
        _breaker_feed(channel, remote, int(Errno.EFAILEDSOCKET))
        raise RpcError(int(Errno.EFAILEDSOCKET),
                       f"connect to {remote} failed")
    if not sock.direct_read or not sock.read_portal.empty() \
            or not sock.write_path_idle():
        # connection converted/busy: un-pin it (back to the pool) so
        # the next call can pin a fresh direct-read connection, and run
        # through the full machinery this time
        _unpin(remote, sid)
        return _full_path()

    try:
        out = _raw_pinned(opts, payload, attachment, timeout_ms, sid,
                          sock, tlv)
    except RpcError as e:
        _breaker_feed(channel, remote, e.code)
        raise
    _breaker_feed(channel, remote, 0)
    return out


def _raw_pinned(opts, payload, attachment, timeout_ms, sid, sock, tlv):
    """The pinned-socket lane body of run_raw (fully-native raw_call
    round trip when available, classic frame build otherwise), split
    out so run_raw can route its one outcome into circuit-breaker
    feedback — the pinned lane has no Controller/LB in the path."""
    from .channel import RpcError
    nat = _native()
    cid = _next_cid()
    if nat is not None and hasattr(nat, "raw_call") \
            and not (opts.auth_data
                     and getattr(sock, "app_data", None) is None):
        # fully-native round trip: the C++ side builds the frame,
        # writes, reads, and scans the response meta — Python's
        # per-call work is one counter bump and one tuple unpack.
        # (The rare first-call-with-auth case keeps the classic build.)
        shm_slot = None
        shm_offered = False
        wire_att = attachment if attachment is not None \
            and len(attachment) else None
        if wire_att is not None or sock.shm is not None:
            # shm data plane: eligible same-host attachments ride a
            # descriptor TLV appended to the tail the engine
            # serializes verbatim; the byte region stays empty
            from ..transport import shm_ring as _shm
            extra, wire_att, shm_slot, shm_offered = \
                _shm.client_prepare(sock, wire_att)
            if extra:
                tlv = tlv + extra
        ack0 = sock._take_ack_frame() if sock._pending_acks else None
        try:
            ok, buf, nval, dom, acks = nat.raw_call(
                sock.fd.fileno(), tlv, payload, wire_att,
                int(timeout_ms) if timeout_ms and timeout_ms > 0 else 0,
                cid, ack0)
        except TimeoutError:
            if shm_slot is not None:
                from ..transport import shm_ring as _shm
                _shm.client_complete(shm_slot)
            sock.set_failed(Errno.ERPCTIMEDOUT, "rpc timeout")
            sock.release()
            raise RpcError(int(Errno.ERPCTIMEDOUT),
                           f"deadline {timeout_ms}ms exceeded") from None
        except (ConnectionError, ValueError, OSError) as e:
            if shm_slot is not None:
                from ..transport import shm_ring as _shm
                _shm.client_complete(shm_slot)
            sock.set_failed(Errno.EFAILEDSOCKET, str(e))
            sock.release()
            raise RpcError(int(Errno.EFAILEDSOCKET), str(e)) from None
        if acks:
            _ici_process_ack(acks, sock)
        if ok:
            if shm_slot is not None or shm_offered:
                # plain success response: settle the staged slot; an
                # unanswered offer marks the peer capability-less
                from ..transport import shm_ring as _shm
                _shm.client_complete(shm_slot)
                if shm_offered:
                    _shm.client_saw_plain_response(sock)
            if dom is not None:
                sock.ici_peer_domain = dom
            body = memoryview(buf)
            if nval:
                return body[:len(body) - nval], body[len(body) - nval:]
            return body, memoryview(b"")
        # unusual response: full decode (errors, controller-tier tags,
        # shm negotiation/descriptor TLVs)
        mv = memoryview(buf)
        meta = RpcMeta.decode(bytes(mv[:nval]))
        if meta is None or meta.correlation_id != cid:
            if shm_slot is not None:
                from ..transport import shm_ring as _shm
                _shm.client_complete(shm_slot)
            sock.set_failed(Errno.ERESPONSE, "undecodable response meta")
            sock.release()
            raise RpcError(int(Errno.ERESPONSE), "undecodable response")
        shm_view = shm_settle = None
        if (meta.shm_offer or meta.shm_accept or meta.shm_desc
                or shm_offered or shm_slot is not None):
            from ..transport import shm_ring as _shm
            try:
                shm_view, shm_settle = _shm.client_on_response_meta(
                    sock, meta,
                    offered_now=shm_offered and not meta.error_code,
                    staged_slot=shm_slot)
            except _shm.ShmDescriptorError as e:
                sock.set_failed(Errno.ERESPONSE, str(e))
                sock.release()
                raise RpcError(int(Errno.ERESPONSE), str(e)) from None
        _mark_lame(meta, sock.remote_side)
        if meta.error_code:
            raise RpcError(meta.error_code, meta.error_text)
        natt = meta.attachment_size
        if meta.ici_domain:
            sock.ici_peer_domain = meta.ici_domain
        body = mv[nval:]
        ratt = memoryview(b"")
        if shm_view is not None:
            # the response attachment rode shared memory.  NOTE (raw
            # lane contract): this view aliases a ring slot recycled at
            # the NEXT call on this channel from this thread (the
            # socket is thread-pinned, so no other caller can trigger
            # it) — consume or copy the view before then.
            _shm.defer_settle(sock, shm_settle)
            ratt = shm_view
        if natt:
            if natt > len(body):
                sock.set_failed(Errno.ERESPONSE,
                                "attachment size exceeds body")
                sock.release()
                raise RpcError(int(Errno.ERESPONSE),
                               "attachment size exceeds body")
            ratt = body[len(body) - natt:]
            body = body[:len(body) - natt]
        return body, ratt

    shm_slot = None
    shm_offered = False
    shm_extra = b""
    wire_att = attachment if attachment is not None \
        and len(attachment) else None
    if wire_att is not None or sock.shm is not None:
        from ..transport import shm_ring as _shm
        shm_extra, wire_att, shm_slot, shm_offered = \
            _shm.client_prepare(sock, wire_att)
    attachment = wire_att
    na = len(attachment) if attachment is not None else 0
    mb = _CID_TAG + struct.pack("<Q", cid)
    if na:
        mb += _ATT_TAG + struct.pack("<I", na)
    mb += tlv + shm_extra
    if opts.auth_data and getattr(sock, "app_data", None) is None:
        mb += encode_tlv(TAG_AUTH, opts.auth_data)
        sock.app_data = "authed"
    if timeout_ms and timeout_ms > 0:
        mb += _TMO_TAG + struct.pack("<I", int(timeout_ms))
    head = _MAGIC + struct.pack("<II", len(mb) + len(payload) + na,
                                len(mb))
    timeout_s = timeout_ms / 1e3 if timeout_ms and timeout_ms > 0 else -1.0
    ack0 = sock._take_ack_frame() if sock._pending_acks else None
    parts = (head, mb, payload) if na == 0 \
        else (head, mb, payload, attachment)
    if ack0 is not None:
        parts = (ack0,) + parts
    try:
        if nat is not None:
            res = nat.sync_call(sock.fd.fileno(), parts, timeout_s)
        else:
            res = _py_sync_call(sock, b"".join(parts), timeout_s)
    except TimeoutError:
        if shm_slot is not None:
            from ..transport import shm_ring as _shm
            _shm.client_complete(shm_slot)
        sock.set_failed(Errno.ERPCTIMEDOUT, "rpc timeout")
        sock.release()
        raise RpcError(int(Errno.ERPCTIMEDOUT),
                       f"deadline {timeout_ms}ms exceeded") from None
    except (ConnectionError, ValueError, OSError) as e:
        if shm_slot is not None:
            from ..transport import shm_ring as _shm
            _shm.client_complete(shm_slot)
        sock.set_failed(Errno.EFAILEDSOCKET, str(e))
        sock.release()
        raise RpcError(int(Errno.EFAILEDSOCKET), str(e)) from None
    buf, meta_size = res[0], res[1]
    if len(res) > 2 and res[2]:
        _ici_process_ack(res[2], sock)
    mv = memoryview(buf)
    scan = _scan_raw_resp(mv[:meta_size])
    shm_view = shm_settle = None
    if scan is None:
        # error tags / unexpected tags (incl. shm negotiation and
        # descriptor TLVs): full decode
        meta = RpcMeta.decode(bytes(mv[:meta_size]))
        if meta is None or meta.correlation_id != cid:
            if shm_slot is not None:
                from ..transport import shm_ring as _shm
                _shm.client_complete(shm_slot)
            sock.set_failed(Errno.ERESPONSE, "undecodable response meta")
            sock.release()
            raise RpcError(int(Errno.ERESPONSE), "undecodable response")
        if (meta.shm_offer or meta.shm_accept or meta.shm_desc
                or shm_offered or shm_slot is not None):
            from ..transport import shm_ring as _shm
            try:
                shm_view, shm_settle = _shm.client_on_response_meta(
                    sock, meta,
                    offered_now=shm_offered and not meta.error_code,
                    staged_slot=shm_slot)
            except _shm.ShmDescriptorError as e:
                sock.set_failed(Errno.ERESPONSE, str(e))
                sock.release()
                raise RpcError(int(Errno.ERESPONSE), str(e)) from None
        _mark_lame(meta, sock.remote_side)
        if meta.error_code:
            raise RpcError(meta.error_code, meta.error_text)
        rcid, natt = meta.correlation_id, meta.attachment_size
    else:
        rcid, natt, _dom = scan
        if rcid != cid:
            if shm_slot is not None:
                from ..transport import shm_ring as _shm
                _shm.client_complete(shm_slot)
            sock.set_failed(Errno.ERESPONSE, "response cid mismatch")
            sock.release()
            raise RpcError(int(Errno.ERESPONSE), "response cid mismatch")
        if shm_slot is not None or shm_offered:
            # plain success response: settle; an unanswered offer marks
            # the peer capability-less
            from ..transport import shm_ring as _shm
            _shm.client_complete(shm_slot)
            if shm_offered:
                _shm.client_saw_plain_response(sock)
        if _dom:
            # learn the peer's device-fabric domain on the classic lane
            # too — otherwise a pure-Python install never enables the
            # descriptor path from raw responses
            sock.ici_peer_domain = _dom
    body = mv[meta_size:]
    ratt = memoryview(b"")
    if shm_view is not None:
        # response attachment resolved from shared memory (see the raw
        # lane view-lifetime note above: slot recycles at this thread's
        # next call on the pinned socket)
        from ..transport import shm_ring as _shm
        _shm.defer_settle(sock, shm_settle)
        ratt = shm_view
    if natt:
        if natt > len(body):
            sock.set_failed(Errno.ERESPONSE, "attachment size exceeds body")
            sock.release()
            raise RpcError(int(Errno.ERESPONSE),
                           "attachment size exceeds body")
        ratt = body[len(body) - natt:]
        body = body[:len(body) - natt]
    return body, ratt


def run_batch(channel, method_full: str, requests, response_type: Any,
              timeout_ms: Optional[int], method_tlvs: bytes):
    """Pipelined batch of unary calls on ONE exclusive connection: all
    frames written in one vectored send, responses matched by
    correlation id (the server may answer out of order when user code
    runs on fibers).  Raises RpcError on the first failed sub-call or on
    transport failure — batch is the perf lane, not the resilience lane.
    """
    from ..protocol.meta import RpcMeta
    from ..protocol.tpu_std import parse_payload, serialize_payload
    from .channel import RpcError

    if not requests:
        return []                 # nothing to send; touch no socket
    if timeout_ms is None:
        timeout_ms = channel.options.timeout_ms
    # deadline inheritance: a batch from a deadline'd handler shares the
    # upstream's remaining budget (fail fast when it's already gone)
    timeout_ms, _amb_expired = _cap_timeout_ms(timeout_ms)
    if _amb_expired:
        raise RpcError(int(Errno.ERPCTIMEDOUT),
                       "inherited deadline already expired (doomed "
                       "downstream batch failed fast)")
    remote = channel.single_server
    if remote is None:
        # cluster channel: batching across servers loses the single-
        # connection pipelining anyway — fall back to per-call
        return [channel.call(method_full, r, response_type,
                             timeout_ms=timeout_ms) for r in requests]
    sid, rc = pooled_socket(remote)
    sock = Socket.address(sid)
    if sock is None or (rc != 0 and sock.failed) \
            or (sock.fd is None and sock.connect_if_not() != 0):
        if sock is not None:
            sock.release()
        raise RpcError(int(Errno.EFAILEDSOCKET),
                       f"connect to {remote} failed")
    if not sock.direct_read or not sock.read_portal.empty() \
            or not sock.write_path_idle():
        return_pooled_socket(sid)
        return [channel.call(method_full, r, response_type,
                             timeout_ms=timeout_ms) for r in requests]

    tmo_tlv = _TMO_TAG + struct.pack("<I", max(1, timeout_ms)) \
        if timeout_ms and timeout_ms > 0 else b""
    auth = channel.options.auth_data or b""
    auth_tlv = b""
    if auth and getattr(sock, "app_data", None) is None:
        # credentials ride the connection's first message (server verifies
        # once per connection)
        auth_tlv = encode_tlv(TAG_AUTH, auth)
        sock.app_data = "authed"
    timeout_s = timeout_ms / 1e3 if timeout_ms and timeout_ms > 0 else -1.0
    nat = _native()
    if nat is not None and hasattr(nat, "call_batch"):
        # fully-native lane: the C++ side builds every frame (stamping
        # consecutive cids), writes vectored, reads and cid-matches the
        # responses — the whole batch costs Python ONE call
        try:
            pls = [r if isinstance(r, (bytes, bytearray, memoryview))
                   else serialize_payload(r).to_bytes() for r in requests]
        except Exception:
            # unserializable request: hand the healthy socket back
            # before surfacing the caller's error — un-marking the auth
            # state this call claimed but never transmitted
            if auth_tlv:
                sock.app_data = None
            return_pooled_socket(sid)
            raise
        base = _reserve_cids(len(pls))
        ack0 = sock._take_ack_frame() if sock._pending_acks else None
        try:
            results, acks = nat.call_batch(
                sock.fd.fileno(), method_tlvs + tmo_tlv, pls, timeout_s,
                base, auth_tlv, ack0 or b"")
        except (TimeoutError, ConnectionError, ValueError, OSError) as e:
            sock.set_failed(Errno.EFAILEDSOCKET, str(e))
            sock.release()
            code = Errno.ERPCTIMEDOUT if isinstance(e, TimeoutError) \
                else Errno.EFAILEDSOCKET
            raise RpcError(int(code), str(e)) from None
        if acks:
            _ici_process_ack(acks, sock)
        # phase 1 — socket-sensitive work only (meta decode, error
        # classification): the connection must go back to the pool
        # BEFORE user-level payload parsing, whose exceptions must not
        # strand an exclusively-checked-out fd
        raws = []
        first_error = None
        for item in results:
            if type(item) is not tuple:
                # plain success payload (the common shape); bytes() so
                # the caller-facing type matches the classic lane
                raws.append(bytes(item))
                continue
            buf, msize = item
            mv = memoryview(buf)
            meta = RpcMeta.decode(bytes(mv[:msize]))
            if meta is None:
                sock.set_failed(Errno.ERESPONSE,
                                "undecodable batch response")
                sock.release()
                raise RpcError(int(Errno.ERESPONSE),
                               "undecodable batch response")
            if meta.ici_desc:
                # the batch lane carries no descriptor logic: return the
                # peer's window credit instead of silently pinning it
                from ..ici.endpoint import ack_unused
                ack_unused(meta, sid)
            _mark_lame(meta, sock.remote_side)
            if meta.error_code:
                if first_error is None:
                    first_error = (meta.error_code, meta.error_text)
                raws.append(None)
                continue
            body = mv[msize:]
            if meta.attachment_size:
                if meta.attachment_size > len(body):
                    sock.set_failed(Errno.ERESPONSE,
                                    "attachment size exceeds body")
                    sock.release()
                    raise RpcError(int(Errno.ERESPONSE),
                                   "attachment size exceeds body")
                body = body[:len(body) - meta.attachment_size]
            raws.append(bytes(body))
        return_pooled_socket(sid)
        if first_error is not None:
            raise RpcError(first_error[0], first_error[1])
        # phase 2 — user-level parsing, socket already safe in the pool
        return [parse_payload(r, response_type) for r in raws]

    parts = []
    cids = []
    marked_auth = bool(auth_tlv)
    try:
        for req in requests:
            if isinstance(req, (bytes, bytearray, memoryview)):
                pb = req
            else:
                pb = serialize_payload(req).to_bytes()
            cid = _next_cid()
            cids.append(cid)
            mb = _CID_TAG + struct.pack("<Q", cid) + method_tlvs \
                + auth_tlv + tmo_tlv
            auth_tlv = b""                   # first message only
            parts.append(_MAGIC
                         + struct.pack("<II", len(mb) + len(pb), len(mb))
                         + mb)
            parts.append(pb)
    except Exception:
        if marked_auth:
            sock.app_data = None             # auth never hit the wire
        return_pooled_socket(sid)            # socket untouched: re-pool
        raise
    timeout_s = timeout_ms / 1e3 if timeout_ms and timeout_ms > 0 else -1.0
    nat = _native()
    ack0 = sock._take_ack_frame() if sock._pending_acks else None
    try:
        if nat is not None:
            wire = [ack0] + parts if ack0 is not None else parts
            frames = nat.sync_call_many(sock.fd.fileno(), wire,
                                        len(cids), timeout_s)
            if isinstance(frames, tuple):     # (frames, interleaved acks)
                frames, batch_acks = frames
                _ici_process_ack(batch_acks, sock)
        else:
            frames = []
            it = iter(range(len(cids)))
            for i in it:
                head = parts[2 * i] if i or ack0 is None \
                    else ack0 + parts[0]
                view, msize, acks = _py_sync_call(
                    sock, head + parts[2 * i + 1], timeout_s)
                if acks:
                    _ici_process_ack(acks, sock)
                frames.append((view, msize))
    except (TimeoutError, ConnectionError, ValueError, OSError) as e:
        sock.set_failed(Errno.EFAILEDSOCKET, str(e))
        sock.release()
        code = Errno.ERPCTIMEDOUT if isinstance(e, TimeoutError) \
            else Errno.EFAILEDSOCKET
        raise RpcError(int(code), str(e)) from None

    by_cid = {}
    first_error = None
    for buf, meta_size in frames:
        mv = memoryview(buf)
        meta = RpcMeta.decode(bytes(mv[:meta_size]))
        if meta is None:
            sock.set_failed(Errno.ERESPONSE, "undecodable batch response")
            sock.release()
            raise RpcError(int(Errno.ERESPONSE), "undecodable batch response")
        _mark_lame(meta, sock.remote_side)
        if meta.error_code and first_error is None:
            first_error = (meta.error_code, meta.error_text)
        body = mv[meta_size:]
        if meta.attachment_size:
            if meta.attachment_size > len(body):
                sock.set_failed(Errno.ERESPONSE,
                                "attachment size exceeds body")
                sock.release()
                raise RpcError(int(Errno.ERESPONSE),
                               "attachment size exceeds body")
            body = body[:len(body) - meta.attachment_size]
        by_cid[meta.correlation_id] = bytes(body)
    return_pooled_socket(sid)
    if first_error is not None:
        raise RpcError(first_error[0], first_error[1])
    out = []
    for cid in cids:
        if cid not in by_cid:
            raise RpcError(int(Errno.ERESPONSE),
                           "batch response missing a correlation id")
        out.append(parse_payload(by_cid[cid], response_type))
    return out
