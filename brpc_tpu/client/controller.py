"""Controller — the per-RPC state machine.

≈ /root/reference/src/brpc/controller.cpp: IssueRPC (:985),
OnVersionedRPCReturned (:568), Call::OnComplete (:726), HandleSocketFailed,
HandleTimeout, HandleBackupRequest (channel.cpp:402), StartCancel (:358).

Rendezvous design (the reference's, re-expressed):

- a ranged correlation id spans ``max_retry + 2`` versions; attempt k
  writes ``cid_base + k`` into the frame meta, so a response names the
  attempt that produced it;
- the response path, the deadline timer, the backup-request timer, socket
  failure, and user cancel ALL deliver through the IdPool — whoever locks
  the id owns the controller for that moment; stale attempts fail the
  version check and are dropped.
"""

from __future__ import annotations

import struct
import threading
from collections import deque
from typing import Any, Callable, Optional, Set, Tuple

from ..butil.iobuf import IOBuf, LazyAttachmentsMixin
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..butil.time_utils import monotonic_us
from ..deadline import backoff_ms as _backoff_ms
from ..deadline import cap_timeout_ms as _cap_timeout_ms
from ..fiber.timer_thread import global_timer_thread
from ..fiber.versioned_id import global_id_pool
from ..protocol import compress as compress_mod
from ..protocol.meta import (CompressType, RpcMeta, TLV_CORRELATION,
                             TLV_SPAN, TLV_TIMEOUT, TLV_TRACE)
from ..protocol.tpu_std import RpcMessage, pack_frame, parse_payload
from ..transport.client_lane import lane_cancel, lane_expect
from ..transport.socket import Socket
from ..transport.socket_map import (global_socket_map, pooled_socket,
                                    return_pooled_socket, short_socket)

_idp = global_id_pool()

# Pooled Controllers: a free-list of reset-on-reuse instances for the
# INTERNAL call sites that create controllers per call (ParallelChannel
# legs, SelectiveChannel attempts, Channel.call sugar).  Reset is a full
# __init__ re-run — every slot re-assigned, so NO state (tenant, trace,
# deadline, attachment views, shm leases) can leak across calls; the
# pooling saves the allocation + GC churn, which at fan-out rates is a
# measurable slice of the per-leg cost.  deque ops are GIL-atomic.
_cntl_pool: "deque[Controller]" = deque()
_CNTL_POOL_MAX = 256

# guards lazy creation of per-controller completion Events (rare: only
# async joins ever create one; sync fast-path calls complete inline)
_EV_CREATE_LOCK = threading.Lock()

# errors worth retrying on another attempt (≈ DefaultRetryPolicy,
# /root/reference/src/brpc/retry_policy.cpp)
_RETRIABLE = {int(Errno.EFAILEDSOCKET), int(Errno.EEOF),
              int(Errno.ELOGOFF), int(Errno.EUNUSED)}
_ELIMIT = int(Errno.ELIMIT)
_ELAMEDUCK = int(Errno.ELAMEDUCK)
# errors the server answered in microseconds PRECISELY so the caller
# can go elsewhere right now: retried immediately (no backoff) and
# only when an LB can actually pick a different replica
_FAIL_FAST = (_ELIMIT, _ELAMEDUCK)


def default_retry_policy(cntl: "Controller", error_code: int) -> bool:
    if error_code in _FAIL_FAST:
        # brpc-style fail-fast (≈ -server_fail_fast consumer side): an
        # overloaded server's ELIMIT — or a draining server's
        # ELAMEDUCK — answers in microseconds precisely so the caller
        # can try a DIFFERENT replica immediately — so retry only when
        # a load balancer can actually pick another one (the failed
        # server lands in excluded_servers; the retry is still
        # token-bucket bounded and skips backoff)
        ch = getattr(cntl, "_channel", None)
        return ch is not None and ch.load_balancer is not None
    return error_code in _RETRIABLE


class Controller(LazyAttachmentsMixin):
    # user-facing knobs (None = inherit from ChannelOptions)
    __slots__ = (
        "timeout_ms", "max_retry", "backup_request_ms",
        "_req_att", "_resp_att",
        "request_device_attachment", "response_device_attachment",
        "request_compress_type", "connection_type", "retry_policy",
        "request_code", "excluded_servers",
        # results
        "response", "latency_us", "remote_side", "retried_count",
        "has_backup_request",
        # internals
        "_error_code", "_error_text", "_cid_base", "_nretry",
        "_live_versions", "_done", "_response_type", "_request_payload",
        "_method_full", "_remote", "_begin_us", "_ended", "_ended_flag",
        "_timeout_timer", "_backup_timer", "_last_attempt_error",
        "_sending_sid",
        "_attempt_sids", "_inflight_marks", "attempt_remotes",
        "_stream_to_create",
        "_channel", "_lb_ctx", "trace_id", "span_id", "_direct_ok",
        "_client_span", "_shm_slot", "_shm_offered", "_shm_retired",
    )

    def __init__(self):
        self.timeout_ms: Optional[int] = None
        self.max_retry: Optional[int] = None
        self.backup_request_ms: Optional[int] = None
        self._req_att: Optional[IOBuf] = None      # lazy (hot path)
        self._resp_att: Optional[IOBuf] = None     # lazy (hot path)
        # device tensors (ici/): out = a jax array to ship
        # device-resident; in = DeviceAttachment handle (.tensor())
        self.request_device_attachment = None
        self.response_device_attachment = None
        self.request_compress_type = CompressType.NONE
        self.connection_type: Optional[str] = None
        self.retry_policy: Callable = default_retry_policy
        self.request_code = 0            # consistent-hashing key
        self.excluded_servers: Set = set()   # retries avoid these
        self.response: Any = None
        self.latency_us = 0
        self.remote_side = None
        self.retried_count = 0
        self.has_backup_request = False
        self._error_code = 0
        self._error_text = ""
        self._cid_base = 0
        self._nretry = 0
        self._live_versions: Set[int] = set()
        self._done: Optional[Callable] = None
        self._response_type: Any = None
        self._request_payload: Optional[IOBuf] = None   # set by _launch
        self._method_full = ""
        self._remote = None
        self._begin_us = 0
        self._ended: Optional[threading.Event] = None   # lazy (hot path)
        self._ended_flag = False
        self._timeout_timer = 0
        self._backup_timer = 0
        self._last_attempt_error: Optional[Tuple[int, str]] = None
        self._sending_sid = 0
        self._attempt_sids = []          # pooled/short sids per attempt
        self._inflight_marks = []        # (sid, cid) to unhook at end
        self.attempt_remotes = {}        # attempt version -> EndPoint
        self._stream_to_create = None    # set by streaming.stream_create
        self._direct_ok = False
        self._channel = None
        self._lb_ctx = None
        self.trace_id = 0
        self.span_id = 0
        self._client_span = None         # rpcz Span for a forced trace
        self._shm_slot = None            # staged shm ring slot (request)
        self._shm_offered = False        # this attempt carried the offer
        self._shm_retired = None         # earlier attempts' slots; freed
        #                                  only at call end (descriptors
        #                                  may still be live on the wire)

    # -- pooled controllers ------------------------------------------------

    @classmethod
    def obtain(cls) -> "Controller":
        """A controller from the free list (or a fresh one).  ONLY for
        internal call sites that also :meth:`recycle` — user-facing
        controllers are never pooled (callers may hold them forever)."""
        try:
            return _cntl_pool.popleft()
        except IndexError:
            return cls()

    def recycle(self) -> None:
        """Return an internally-owned, FINISHED controller to the free
        list.  Reset is a full ``__init__`` re-run: every slot is
        re-assigned, so nothing — tenant, trace ids, deadline, response
        views, shm leases, excluded servers — survives into the next
        call (pinned by tests/test_client_lane.py)."""
        if len(_cntl_pool) >= _CNTL_POOL_MAX:
            return
        self.__init__()
        _cntl_pool.append(self)

    # -- lazy hot-path members ---------------------------------------------
    # attachments: LazyAttachmentsMixin.  The Event is also lazy: a sync
    # unary call never touches it (completed inline on the caller).

    def _begin_trace_span(self, method_full: str) -> None:
        """Open the client half of an EXPLICITLY traced call (trace_id
        set): the client span parents to whatever span id the caller
        carried in (a fan-out root, an upstream server span) and the
        call's own span id replaces it on the wire, so the server span
        links back to THIS hop.  Idempotent — retries and lane
        escalations reuse the one span."""
        if not self.trace_id or self._client_span is not None:
            return
        from ..rpcz import start_client_span
        span = start_client_span(method_full, self.trace_id, self.span_id)
        if span is not None:
            self._client_span = span
            self.span_id = span.span_id

    def _signal_ended(self) -> None:
        """Completion signal: flag first, then wake any created Event.
        Also unhooks every attempt's correlation id from its socket's
        in-flight set (and the native client lane's demux table) — a
        call that ends without a response (timeout, cancel, abandoned
        retry) must not leave its id pinned on a long-lived connection.
        The common fast-lane completion (no span, no Event, no marks)
        is three attribute reads."""
        span = self._client_span
        if span is not None:
            self._client_span = None
            span.remote_side = str(self.remote_side or "")
            span.finish(self._error_code)
        self._ended_flag = True
        ev = self._ended
        if ev is not None:
            ev.set()
        marks = self._inflight_marks
        if marks:
            for sid, cid in marks:
                s = Socket.address(sid) if sid else None
                if s is not None:
                    s.remove_inflight(cid)
                    if s.lane_token:
                        lane_cancel(s, cid)
            marks.clear()

    def _ended_event(self) -> threading.Event:
        """The completion Event, created on first wait (double-checked
        against the flag so a signal between create and wait is never
        lost)."""
        ev = self._ended
        if ev is None:
            with _EV_CREATE_LOCK:
                ev = self._ended
                if ev is None:
                    ev = threading.Event()
                    self._ended = ev
            if self._ended_flag:
                ev.set()
        return ev

    # -- results -----------------------------------------------------------

    @property
    def failed(self) -> bool:
        return self._error_code != 0

    @property
    def error_code(self) -> int:
        return self._error_code

    @property
    def error_text(self) -> str:
        return self._error_text

    def set_failed(self, code: int, text: str = "") -> None:
        self._error_code = int(code)
        self._error_text = text

    @property
    def call_id(self) -> int:
        """Cancel handle (≈ Controller::call_id, controller.cpp:358)."""
        return self._cid_base

    def join(self, timeout: Optional[float] = None) -> bool:
        return _idp.join(self._cid_base, timeout) if self._cid_base \
            else (self._ended_flag
                  or self._ended_event().wait(timeout))

    def _sync_wait(self) -> None:
        """Block until completion.  Fast path: on an exclusive
        (pooled/short) connection the caller reads+processes its own
        response inline — the whole round trip costs zero cross-thread
        wakeups.  Falls back to the id join whenever the attempt's
        socket is unavailable/converted (retries re-enter the loop)."""
        if not self._direct_ok:
            self.join()
            return
        import select as _select

        from ..transport.input_messenger import client_messenger
        messenger = client_messenger()
        deadline = None
        if self.timeout_ms and self.timeout_ms > 0:
            deadline = self._begin_us / 1e6 + self.timeout_ms / 1e3
        while not self._ended_flag:
            if deadline is not None:
                left = deadline - monotonic_us() / 1e6
                if left <= 0:
                    _idp.error(self._cid_base, int(Errno.ERPCTIMEDOUT),
                               f"deadline {self.timeout_ms}ms exceeded")
                    self._ended_event().wait(1.0)
                    return
            else:
                left = 0.1
            sock = Socket.address(self._sending_sid)
            if sock is None or sock.failed or not sock.direct_read \
                    or sock.fd is None:
                # the id machinery owns this phase (connect error, retry
                # in flight, converted socket): poll-join briefly
                self._ended_event().wait(0.01)
                continue
            try:
                r, _, _ = _select.select([sock.fd], [], [],
                                         min(left or 0.1, 0.1))
            except (OSError, ValueError):
                self._ended_event().wait(0.005)  # fd closed under us
                continue
            if not r or self._ended_flag:
                continue
            nread = sock.read_into_portal()
            if nread == 0:
                if not sock.failed:
                    sock.set_failed(Errno.EEOF, "remote closed connection")
            elif nread > 0:
                messenger._cut_and_process(sock)

    def _fail_before_launch(self, code: int, text: str,
                            done: Optional[Callable]) -> None:
        """Failure before a correlation id exists: set results and end so
        join() returns instead of hanging."""
        self.set_failed(code, text)
        self._signal_ended()
        if done is not None:
            try:
                done(self)
            except Exception:
                LOG.exception("rpc done callback raised")

    # -- launch (called by Channel) ---------------------------------------

    def _launch(self, channel, method_full: str, payload: IOBuf,
                response_type: Any, done: Optional[Callable]) -> None:
        opts = channel.options
        self._channel = channel
        self._method_full = method_full
        self._request_payload = payload
        self._response_type = response_type
        self._done = done
        if self.timeout_ms is None:
            self.timeout_ms = opts.timeout_ms
        # deadline inheritance: issued from a deadline'd server handler,
        # this call can never outlive the upstream request's remaining
        # budget — and fails fast when that budget is already gone
        self.timeout_ms, _amb_expired = _cap_timeout_ms(self.timeout_ms)
        if _amb_expired:
            self._fail_before_launch(
                int(Errno.ERPCTIMEDOUT),
                "inherited deadline already expired (doomed downstream "
                "call failed fast)", done)
            return
        if self.max_retry is None:
            self.max_retry = opts.max_retry
        if self.backup_request_ms is None:
            self.backup_request_ms = opts.backup_request_ms
        if self.connection_type is None:
            self.connection_type = opts.connection_type
        if opts.protocol == "http" and self.connection_type == "single":
            # http/1 cannot multiplex a shared connection
            self.connection_type = "pooled"
        if self._stream_to_create is not None:
            # a stream must bind to exactly one long-lived server
            # connection: retry/backup could get a second server to
            # accept, and short/pooled connections are released or
            # recycled at RPC completion under the live stream
            self.max_retry = 0
            self.backup_request_ms = -1
            self.connection_type = "single"
        self._begin_us = monotonic_us()
        # sync fast path eligibility: the caller thread reads responses
        # directly off an exclusive (pooled/short) connection — no
        # dispatcher wake, no fiber spawn, no butex wake per call
        self._direct_ok = (done is None
                           and self.connection_type in ("pooled", "short")
                           and (not self.backup_request_ms
                                or self.backup_request_ms <= 0)
                           and self._stream_to_create is None
                           # TLS buffers decrypted bytes inside the SSL
                           # layer: a select()-driven direct reader could
                           # stall on data that will never hit the fd —
                           # dispatcher-managed reads drain correctly
                           and (channel is None
                                or channel.ssl_ctx() is None))
        self._cid_base = _idp.create_ranged(
            self, Controller._on_id_error, self.max_retry + 2)
        self._live_versions = {0}
        if self.timeout_ms and self.timeout_ms > 0 and not self._direct_ok:
            # direct sync calls enforce the deadline inline in
            # _sync_wait — no timer-thread round trip per call
            self._timeout_timer = global_timer_thread().schedule(
                _idp.error, self.timeout_ms / 1e3, None,
                self._cid_base, int(Errno.ERPCTIMEDOUT),
                f"deadline {self.timeout_ms}ms exceeded")
        if self.backup_request_ms and self.backup_request_ms > 0 \
                and self.backup_request_ms < (self.timeout_ms or 1 << 30):
            self._backup_timer = global_timer_thread().schedule(
                _idp.error, self.backup_request_ms / 1e3, None,
                self._cid_base, int(Errno.EBACKUPREQUEST), "")
        self._issue_rpc()

    # -- attempt issuing ---------------------------------------------------

    def _select_remote(self):
        """Single server or LB selection (≈ IssueRPC :1020-1036)."""
        ch = self._channel
        if ch.load_balancer is not None:
            return ch.load_balancer.select_server(self)
        return ch.single_server

    def _issue_rpc(self) -> None:
        """Send attempt ``self._nretry``. Runs with the id logically held
        (either at launch or inside an error handler)."""
        remote = self._select_remote()
        if remote is None:
            self._finish_locked_or_now(Errno.EINTERNAL,
                                       "no server available", locked=False)
            return
        self.remote_side = remote
        self.attempt_remotes[self._nretry] = remote
        attempt_id = self._cid_base + self._nretry
        ctype = self.connection_type or "single"
        ssl_ctx = self._channel.ssl_ctx() if self._channel else None
        wire = self._channel.options.protocol if self._channel else "tpu_std"
        # client-lane eligibility: tpu_std plaintext responses can ride
        # the native demux; streams keep the dispatcher (their chunk
        # frames would each pay a lane fallback hop)
        lane_ok = (wire == "tpu_std" and ssl_ctx is None
                   and self._stream_to_create is None)
        if ctype == "pooled":
            sid, rc = pooled_socket(remote, ssl_context=ssl_ctx)
            self._attempt_sids.append(sid)
        elif ctype == "short":
            sid, rc = short_socket(remote, ssl_context=ssl_ctx)
            self._attempt_sids.append(sid)
        else:
            sid, rc = global_socket_map().get_socket(
                remote, ssl_context=ssl_ctx, prefer_lane=lane_ok)
        self._sending_sid = sid
        sock = Socket.address(sid)
        if sock is not None and sock.direct_read and not self._direct_ok:
            # async/backup call on a fast-path connection: hand its
            # reads to the NATIVE CLIENT LANE (engine-side response
            # demux; the classic dispatcher conversion is the fallback
            # and the only path for streams/TLS/non-tpu_std wires)
            if lane_ok:
                sock.ensure_client_lane()
            else:
                sock.ensure_dispatched()
        if sock is None or (rc != 0 and sock.failed):
            # connection failed synchronously: deliver through the id so
            # the retry path is uniform
            _idp.error(attempt_id, int(Errno.EFAILEDSOCKET),
                       f"connect to {remote} failed")
            return
        svc, mth = self._method_full.rsplit(".", 1)
        if wire == "http":
            # HTTP/1 has no multiplexing: the in-flight call rides the
            # connection itself (correlation_id on the socket), so the
            # connection must be exclusive — pooled or short
            from ..protocol.http import build_request
            att = self.request_attachment.to_bytes()
            body = self._request_payload.to_bytes() + att
            headers = [("x-rpc-attachment-size", str(len(att)))] \
                if att else []
            if self.timeout_ms and self.timeout_ms > 0:
                # x-deadline-ms: the HTTP/1.1 spelling of tpu_std's
                # remaining-deadline TLV 13 — every (retry) attempt
                # stamps what's LEFT of the budget, not the original
                # timeout, so the server's shed decision sees the truth
                elapsed_ms = (monotonic_us() - self._begin_us) // 1000
                headers.append(("x-deadline-ms",
                                str(max(1, int(self.timeout_ms
                                               - elapsed_ms)))))
            if self.trace_id and self.span_id:
                # trace context rides HTTP as a W3C traceparent header
                # (the tpu_std meta TLVs' cross-protocol spelling).
                # span_id==0 (rpcz disabled: no client span recorded)
                # would spell an all-zero parent-id, which the W3C
                # grammar forbids and strict peers drop — omit instead
                from ..rpcz import format_traceparent
                headers.append(("traceparent", format_traceparent(
                    self.trace_id, self.span_id)))
            if self._channel.options.tenant:
                # tenant identity: the x-tenant header is TLV 22's
                # HTTP/1.1 spelling (overload plane fair admission)
                headers.append(("x-tenant",
                                self._channel.options.tenant))
            frame = build_request("POST", f"/{svc}/{mth}", body=body,
                                  host=str(remote),
                                  headers=headers or None)
            sock.correlation_id = attempt_id   # response routing (no
            # failure-notification role: the inflight set owns that, so
            # a set_failed racing this write cannot double-error the id)
            sock.add_inflight(attempt_id)
            self._inflight_marks.append((sid, attempt_id))
            if self._ended_flag:
                sock.remove_inflight(attempt_id)
            rc = sock.write(frame)
            if rc and sock.remove_inflight(attempt_id):
                _idp.error(attempt_id, rc,
                           sock.error_text or f"write to {remote} failed")
            return
        # -- precompiled call template (flat frame build) ------------------
        # The run_raw TLV-prefix cache extended to the full-Controller
        # path: for the plain request shape (no compression, stream,
        # device/shm attachment, wire attachment or per-frame auth) the
        # frame is cid TLV + the per-(socket, method, tenant) cached
        # tail (service/method/tenant TLVs + ici domain/nonce) +
        # per-attempt deadline/trace TLVs + payload views — no RpcMeta
        # object, no pack_frame walk, byte-compatible with the classic
        # build (same TLVs, fast-lane order).
        na0 = len(self._req_att) if self._req_att is not None else 0
        if (not self.request_compress_type
                and self._stream_to_create is None
                and self.request_device_attachment is None
                and self._shm_slot is None and not self._shm_retired
                and na0 == 0 and sock.shm is None
                and not (self._channel is not None
                         and self._channel.options.auth_data)):
            mb = bytearray(TLV_CORRELATION)
            mb += struct.pack("<Q", attempt_id)
            mb += self._flat_tail(sock)
            if self.timeout_ms and self.timeout_ms > 0:
                elapsed_ms = (monotonic_us() - self._begin_us) // 1000
                mb += TLV_TIMEOUT + struct.pack(
                    "<I", max(1, int(self.timeout_ms - elapsed_ms)))
            if self.trace_id:
                mb += TLV_TRACE + struct.pack("<Q", self.trace_id)
                if self.span_id:
                    mb += TLV_SPAN + struct.pack("<Q", self.span_id)
            payload = self._request_payload
            plen = len(payload) if payload is not None else 0
            header = b"TRPC" + struct.pack("<II", len(mb) + plen,
                                           len(mb))
            parts = (header, bytes(mb))
            if plen:
                parts = parts + tuple(payload.backing_views())
            sock.add_inflight(attempt_id)
            self._inflight_marks.append((sid, attempt_id))
            if sock.lane_token:
                # native demux rendezvous: registered BEFORE the write
                # (mirrors add_inflight's ordering contract)
                lane_expect(sock, attempt_id)
            if self._ended_flag:
                sock.remove_inflight(attempt_id)
                lane_cancel(sock, attempt_id)
            rc = sock.write_parts(parts)
            if rc and sock.remove_inflight(attempt_id):
                lane_cancel(sock, attempt_id)
                _idp.error(attempt_id, rc,
                           sock.error_text or f"write to {remote} failed")
            return
        meta = RpcMeta()
        meta.correlation_id = attempt_id
        meta.service_name = svc
        meta.method_name = mth
        meta.trace_id = self.trace_id
        meta.span_id = self.span_id
        if self._channel is not None and self._channel.options.auth_data:
            # credentials ride every frame; the server verifies on the
            # connection's first message (≈ Protocol::verify)
            meta.auth_data = self._channel.options.auth_data
        if self._channel is not None and self._channel.options.tenant:
            # tenant identity (TLV 22): the overload plane's per-tenant
            # fair-admission key, stamped on every attempt
            meta.tenant = self._channel.options.tenant.encode()
        if self._stream_to_create is not None:
            meta.stream_id = self._stream_to_create.id
            meta.stream_window = \
                self._stream_to_create.options.max_buf_size
        if self.timeout_ms and self.timeout_ms > 0:
            elapsed_ms = (monotonic_us() - self._begin_us) // 1000
            meta.timeout_ms = max(1, int(self.timeout_ms - elapsed_ms))
        payload = self._request_payload
        if self.request_compress_type:
            data = compress_mod.compress(payload.to_bytes(),
                                         self.request_compress_type)
            if data is not None:
                meta.compress_type = self.request_compress_type
                payload = IOBuf(data)
        attachment = self.request_attachment
        from ..ici.endpoint import (conn_nonce_of, ici_enabled,
                                    local_domain_id, prepare_send)
        if ici_enabled():
            # advertise our fabric domain on every frame (one-roundtrip
            # handshake, ≈ RdmaEndpoint's TCP-then-QP bring-up), plus
            # the connection nonce descriptor binding keys off (proxy/
            # NAT-safe identity; must precede prepare_send's post)
            meta.ici_domain = local_domain_id()
            meta.ici_conn = conn_nonce_of(sock)
        if self.request_device_attachment is not None:
            # with ici disabled prepare_send degrades to host-staged
            # bytes itself — the attachment must never be dropped
            post_timeout = 30.0
            if self.timeout_ms and self.timeout_ms > 0:
                elapsed_ms = (monotonic_us() - self._begin_us) // 1000
                post_timeout = min(
                    30.0, max(0.001, (self.timeout_ms - elapsed_ms) / 1e3))
            try:
                tail = prepare_send(sock, meta,
                                    self.request_device_attachment,
                                    timeout_s=post_timeout)
            except RuntimeError as e:
                _idp.error(attempt_id, int(Errno.EOVERCROWDED), str(e))
                return
            if tail is not None:
                combined = IOBuf()
                combined.append_iobuf(attachment)
                combined.append_iobuf(tail)
                attachment = combined
        # shm data plane: a same-host attachment ≥ threshold rides a
        # descriptor into this process's tx ring instead of the frame
        # (negotiation/credit TLVs splice into the meta region verbatim)
        shm_extra = b""
        multi_attempt = False
        if self._shm_slot is not None:
            # a backup/retry attempt starts while the previous attempt's
            # on-wire descriptor may still be unread by the server (a
            # backup's primary is STILL LIVE): the slot must not be
            # freed — retire it, settled once the call ends
            # (_signal_ended), and keep later attempts off the shm lane
            # (their early settle would have the same hazard)
            if self._shm_retired is None:
                self._shm_retired = []
            self._shm_retired.append(self._shm_slot)
            self._shm_slot = None
            multi_attempt = True
        self._shm_offered = False
        na = len(attachment) if attachment is not None else 0
        if na or getattr(sock, "shm", None) is not None:
            from ..transport import shm_ring as _shm
            shm_extra, wire_att, slot, offered = _shm.client_prepare(
                sock, attachment if na else None,
                device=self.request_device_attachment is not None,
                multi_attempt=multi_attempt)
            self._shm_slot = slot
            self._shm_offered = offered
            if na and wire_att is None:
                attachment = None       # the attachment rides shm
        frame = pack_frame(meta, payload, attachment=attachment,
                           extra_meta=shm_extra)
        # exactly-once failure notification by inflight-set ownership:
        # the id is NOT passed to write (its refused-enqueue path could
        # double-notify an id set_failed's drain already errored); whoever
        # claims the id from the set delivers its one outcome
        sock.add_inflight(attempt_id)
        self._inflight_marks.append((sid, attempt_id))
        if sock.lane_token:
            lane_expect(sock, attempt_id)
        if self._ended_flag:
            # the call ended while this send was mid-launch (timeout or
            # cancel racing the issuing thread): _signal_ended's drain
            # may have run before our append and will not run again —
            # unhook the id ourselves or it pins the long-lived socket
            sock.remove_inflight(attempt_id)
            lane_cancel(sock, attempt_id)
        rc = sock.write(frame)
        if rc and sock.remove_inflight(attempt_id):
            lane_cancel(sock, attempt_id)
            _idp.error(attempt_id, rc,
                       sock.error_text or f"write to {remote} failed")

    def _flat_tail(self, sock) -> bytes:
        """The per-(socket, method, tenant) cached meta-TLV tail of the
        precompiled call template: service/method (+ tenant) TLVs plus,
        with ici on, this process's domain TLV and the socket's conn
        nonce — the same cache (``sock._cntl_tails``) and wire content
        the pinned fast lane uses, so the two paths can never drift."""
        from . import fast_call as _fc
        opts = self._channel.options
        tail_key = (self._method_full, opts.tenant)
        tails = getattr(sock, "_cntl_tails", None)
        tail = tails.get(tail_key) if tails is not None else None
        if tail is None:
            ch = self._channel
            tlv = ch._method_tlvs.get(self._method_full)
            if tlv is None:
                tlv = ch._method_tlvs[self._method_full] = \
                    _fc.method_tlv(self._method_full, opts.tenant)
            tail = tlv
            from ..ici.endpoint import (conn_nonce_of, ici_enabled,
                                        local_domain_id)
            if ici_enabled():
                from ..protocol.meta import TAG_ICI_CONN, encode_tlv
                tail = (tail + _fc._domain_tlv(local_domain_id())
                        + encode_tlv(TAG_ICI_CONN, conn_nonce_of(sock)))
            if tails is None:
                tails = sock._cntl_tails = {}
            tails[tail_key] = tail
        return tail

    # -- asynchronous events (timers / socket failures / cancel) ----------

    def _retry_locked(self, failed_version: int, code: int) -> bool:
        """Common retry decision+launch, run with the id locked: discard
        the failed attempt, consult the policy, issue attempt n+1.
        Returns True if a retry was issued."""
        self._live_versions.discard(failed_version)
        # exclude the server of the attempt that actually failed — with a
        # backup in flight, remote_side already points at the newer
        # attempt's server (≈ excluded_servers.h)
        failed_remote = self.attempt_remotes.get(failed_version)
        if failed_remote is not None:
            self.excluded_servers.add(failed_remote)
        if self.retry_policy(self, code) and self._nretry < self.max_retry:
            ch = self._channel
            if ch is not None and not ch.acquire_retry_token():
                # retry budget exhausted: a degraded backend must not
                # see offered load multiplied by 1 + max_retry
                return False
            self._nretry += 1
            self.retried_count = self._nretry
            self._live_versions.add(self._nretry)
            delay_ms = 0.0
            if ch is not None and code not in _FAIL_FAST:
                # fail-fast: an ELIMIT/ELAMEDUCK bounce retries
                # IMMEDIATELY on a different replica — backing off
                # would waste exactly the time the server's
                # microsecond rejection saved
                delay_ms = _backoff_ms(ch.options.retry_backoff_ms,
                                       self._nretry,
                                       ch.options.retry_backoff_max_ms)
            if delay_ms > 0:
                # exponential backoff with jitter: the timer thread only
                # trampolines — the attempt is issued by a short-lived
                # thread after the delay (the deadline timer races it
                # fairly: a backed-off retry that would land past the
                # deadline simply never fires).  The scheduled attempt's
                # VERSION rides along so a backup request firing during
                # the backoff window can't make the late issue duplicate
                # the backup's cid on the wire.
                global_timer_thread().schedule(
                    Controller._backoff_fire, delay_ms / 1e3, None,
                    self._cid_base, self._nretry)
            else:
                self._issue_rpc()
            return True
        return False

    @staticmethod
    def _backoff_fire(call_id: int, version: int) -> None:
        """Timer-thread trampoline of a backed-off retry: hop straight
        onto a short-lived issuer thread.  Both halves of the issue can
        block (``_idp.lock`` cond-waits on a held id; connect/write can
        take seconds) and the shared timer thread must keep every other
        call's deadline/backup timers firing meanwhile."""
        threading.Thread(target=Controller._backoff_issue,
                         args=(call_id, version), daemon=True).start()

    @staticmethod
    def _backoff_issue(call_id: int, version: int) -> None:
        """Issuer body of a backed-off retry: re-take the id lock (the
        call may have completed or timed out during the backoff — stale
        ids refuse to lock) and issue the pending attempt — unless a
        backup request fired during the backoff and already advanced
        ``_nretry``: issuing then would put a DUPLICATE of the backup's
        cid on the wire, so the never-issued scheduled version is
        retired instead."""
        ok, cntl = _idp.lock(call_id)
        if not ok:
            return
        if cntl is None:
            _idp.unlock(call_id)
            return
        if cntl._nretry == version:
            cntl._issue_rpc()
            _idp.unlock(call_id)
            return
        cntl._live_versions.discard(version)
        if not cntl._live_versions:
            # every issued attempt already failed and retry was declined
            # while this version kept the call looking alive: finish it
            # now with the last REAL failure (a fabricated timeout would
            # misdirect retry policies and breaker analysis) instead of
            # hanging to the full deadline
            code, text = (cntl._last_attempt_error
                          or (int(Errno.ERPCTIMEDOUT),
                              "all attempts failed during retry backoff"))
            cntl._finish_locked(code, text)
            return
        _idp.unlock(call_id)

    @staticmethod
    def _on_id_error(call_id: int, cntl: "Controller", code: int,
                     text: str) -> None:
        """Runs with the correlation id LOCKED (IdPool contract)."""
        if cntl is None:
            _idp.unlock_and_destroy(call_id)
            return
        if code == int(Errno.EBACKUPREQUEST):
            # backup/hedged requests draw from the SAME retry budget as
            # retries: hedging against a degraded backend is exactly a
            # retry storm with better intentions
            ch = cntl._channel
            if cntl._nretry < cntl.max_retry \
                    and (ch is None or ch.acquire_retry_token()):
                cntl.has_backup_request = True
                cntl._nretry += 1
                cntl.retried_count = cntl._nretry
                cntl._live_versions.add(cntl._nretry)
                cntl._issue_rpc()
            _idp.unlock(cntl._cid_base)
            return
        if code == int(Errno.ECANCELLED) or code == int(Errno.ERPCTIMEDOUT):
            cntl._finish_locked(code, text or "cancelled")
            return
        # socket-level failure of some attempt
        version = (call_id - cntl._cid_base) & ((1 << 36) - 1)
        if cntl._retry_locked(version, code):
            _idp.unlock(cntl._cid_base)
            return
        if cntl._live_versions:
            # another attempt (e.g. the original besides a failed backup)
            # is still in flight — let it decide the call's fate; keep
            # this failure so a never-issued backoff version retiring
            # last can still report the real error
            cntl._last_attempt_error = (code, text)
            _idp.unlock(cntl._cid_base)
            return
        cntl._finish_locked(code, text)

    # -- response path -----------------------------------------------------

    def _on_response(self, msg: RpcMessage) -> None:
        """Runs with the id LOCKED. ≈ OnVersionedRPCReturned."""
        version = msg.meta.correlation_id - self._cid_base
        if version not in self._live_versions:
            if msg.meta.ici_desc:
                # discarding a response carrying a posted descriptor:
                # return the peer's window credit
                from ..ici.endpoint import ack_unused
                ack_unused(msg.meta, msg.socket_id or self._sending_sid)
            _idp.unlock(self._cid_base)      # stale attempt's response
            return
        shm_view = shm_settle = None
        m = msg.meta
        from .naming_service import global_lame_ducks as _gld
        if m.lame_duck:
            # the answering server is draining: drop it from LB
            # selection NOW (no breaker penalty — the response itself
            # is still consumed below, whatever it carries)
            _gld().mark(self.attempt_remotes.get(version,
                                                 self.remote_side))
        elif not m.error_code:
            # clean response: a restarted successor on the same address
            # sheds its predecessor's mark (no-op when unmarked)
            _gld().clear(self.attempt_remotes.get(version,
                                                  self.remote_side))
        if m.shm_offer or m.shm_accept or m.shm_desc or self._shm_offered \
                or self._shm_slot is not None:
            # shm data plane: learn accepts/offers, settle the staged
            # request slot, resolve a response descriptor (error
            # responses prove nothing about capability — offered_now
            # only on success)
            from ..transport import shm_ring as _shm
            s = Socket.address(msg.socket_id or self._sending_sid)
            if s is not None:
                try:
                    shm_view, shm_settle = _shm.client_on_response_meta(
                        s, m, offered_now=(self._shm_offered
                                           and not m.error_code),
                        staged_slot=self._shm_slot,
                        retired=self._shm_retired)
                except _shm.ShmDescriptorError as e:
                    # peer protocol violation — fail loudly, never hand
                    # user code a silently empty attachment
                    self._shm_slot = None
                    self._finish_locked(int(Errno.ERESPONSE), str(e))
                    return
                self._shm_slot = None
        code = msg.meta.error_code
        if code == _ELAMEDUCK and not m.lame_duck:
            # an ELAMEDUCK rejection IS the drain signal even when the
            # response meta lost the TLV (proxy stripped unknown tags)
            from .naming_service import global_lame_ducks
            global_lame_ducks().mark(
                self.attempt_remotes.get(version, self.remote_side))
        if code != 0:
            if self._retry_locked(version, code):
                _idp.unlock(self._cid_base)
                return
            self._finish_locked(code, msg.meta.error_text)
            return
        if self._stream_to_create is not None and msg.meta.stream_id:
            # the accepted stream rides the connection that answered
            self._stream_to_create._bind(
                msg.socket_id or self._sending_sid,
                msg.meta.stream_id,
                peer_window=msg.meta.stream_window)
        try:
            attachment = msg.split_attachment()
        except ValueError as e:
            if msg.meta.ici_desc:
                # the malformed response still carried a posted
                # descriptor: return the peer's window credit
                from ..ici.endpoint import ack_unused
                ack_unused(msg.meta, msg.socket_id or self._sending_sid)
            self._finish_locked(int(Errno.ERESPONSE), str(e))
            return
        if msg.meta.ici_domain:
            s = Socket.address(msg.socket_id or self._sending_sid)
            if s is not None:
                s.ici_peer_domain = msg.meta.ici_domain
        if msg.meta.ici_desc:
            from ..ici.endpoint import split_device_attachment
            attachment, self.response_device_attachment = \
                split_device_attachment(msg.meta, attachment,
                                        msg.socket_id or self._sending_sid)
        if shm_view is not None:
            # the response attachment rode shared memory: wrap the
            # resolved zero-copy view (the frame carried no att bytes).
            # LIFETIME: the backing ring slot is recycled when this
            # IOBuf is dropped (finalizer-bound settle) — raw views
            # extracted via backing_views()/as_contiguous() must not
            # outlive the attachment IOBuf
            from ..transport import shm_ring as _shm
            attachment = _shm.wrap_view_iobuf(shm_view, shm_settle)
        raw = msg.payload.to_bytes()
        if msg.meta.compress_type:
            raw = compress_mod.decompress(raw, msg.meta.compress_type)
            if raw is None:
                self._finish_locked(Errno.ERESPONSE,
                                    "undecompressable response")
                return
        try:
            self.response = parse_payload(raw, self._response_type)
        except Exception as e:
            self._finish_locked(Errno.ERESPONSE,
                                f"response parse failed: {e}")
            return
        self.response_attachment = attachment
        self._finish_locked(0, "")

    def _on_plain_response(self, cid: int, buf, natt: int, dom,
                           sock) -> None:
        """Native-lane completion of a PLAIN success response (cid /
        attachment-size / ici-domain meta only — the engine's demux
        guarantees the shape).  Runs with the id LOCKED; mirrors
        ``_on_response``'s success arm minus everything a plain meta
        cannot carry (errors, stream grants, descriptors, compression,
        shm tags — those fall back to the classic demux wholesale)."""
        version = cid - self._cid_base
        if version not in self._live_versions:
            _idp.unlock(self._cid_base)      # stale attempt's response
            return
        if self._shm_offered or self._shm_slot is not None:
            # a plain success answers this attempt's staged slot/offer
            # exactly like the blocking lanes' plain path: settle the
            # slot; an unanswered offer marks the peer capability-less
            from ..transport import shm_ring as _shm
            _shm.client_complete(self._shm_slot)
            self._shm_slot = None
            if self._shm_offered:
                _shm.client_saw_plain_response(sock)
        if dom:
            sock.ici_peer_domain = dom
        body = memoryview(buf)
        attachment = IOBuf()
        if natt:
            # the engine already bounded natt <= len(body)
            attachment.append_user_data(body[len(body) - natt:])
            body = body[:len(body) - natt]
        try:
            self.response = parse_payload(bytes(body),
                                          self._response_type)
        except Exception as e:
            self._finish_locked(Errno.ERESPONSE,
                                f"response parse failed: {e}")
            return
        self.response_attachment = attachment
        self._finish_locked(0, "")

    # -- completion --------------------------------------------------------

    def _finish_locked(self, code: int, text: str) -> None:
        """Final rendezvous: set results, destroy the id (wakes sync
        joiners), then run the async done callback if any."""
        self._error_code = int(code)
        self._error_text = text
        self.latency_us = monotonic_us() - self._begin_us
        if self._shm_slot is not None or self._shm_retired:
            # settle the staged slot when the call ended without
            # response-meta processing (timeout, cancel, socket
            # failure), plus slots retired by backup/retry restages —
            # the call's end is the earliest point their on-wire
            # descriptors are plausibly quiescent (the one remaining
            # window: an orphaned attempt's frame still unread when its
            # slot is recycled — narrowed by later attempts declining
            # the shm lane, see client_prepare multi_attempt)
            from ..transport import shm_ring as _shm
            _shm.client_complete(self._shm_slot)
            self._shm_slot = None
            if self._shm_retired:
                for s in self._shm_retired:
                    _shm.client_complete(s)
                self._shm_retired = None
        if self._stream_to_create is not None and (
                code != 0
                or not self._stream_to_create._established.is_set()):
            # establishment failed — or succeeded without the server
            # accepting the stream: the pending stream dies with it
            self._stream_to_create._close_local(notify_peer=False)
        if self._timeout_timer:
            global_timer_thread().unschedule(self._timeout_timer)
        if self._backup_timer:
            global_timer_thread().unschedule(self._backup_timer)
        # per-attempt connections: the successful final pooled socket goes
        # back to the pool; every other attempt's socket is released (it
        # may carry an unconsumed in-flight response — not reusable)
        for sid in self._attempt_sids:
            s = Socket.address(sid)
            if (sid == self._sending_sid and code == 0
                    and self.connection_type == "pooled"
                    and s is not None and not s.correlation_id):
                # correlation_id != 0 marks an HTTP request still
                # unanswered on this connection (a losing backup
                # attempt): pooling it would deliver the late response
                # to the next unrelated call
                return_pooled_socket(sid)
                continue
            if s is not None:
                s.release()
        ch = self._channel
        if ch is not None and ch.load_balancer is not None:
            ch.load_balancer.feedback(self)
        elif ch is not None and ch.options.enable_circuit_breaker \
                and self.remote_side is not None:
            # single-server channels have no LB to route feedback, but
            # the breaker map is global (keyed by endpoint): health
            # observed here must still inform every cluster channel
            # sharing this backend
            from .circuit_breaker import global_circuit_breaker_map
            global_circuit_breaker_map().on_call(
                self.remote_side, self._error_code, self.latency_us)
        if ch is not None and code == 0:
            ch.on_call_success()       # refill the retry budget
        _idp.unlock_and_destroy(self._cid_base)
        self._signal_ended()
        done = self._done
        if done is not None:
            try:
                done(self)
            except Exception:
                LOG.exception("rpc done callback raised")

    def _finish_locked_or_now(self, code: int, text: str,
                              locked: bool) -> None:
        if locked:
            self._finish_locked(code, text)
        else:
            _idp.error(self._cid_base, int(code), text)


def process_rpc_response(msg: RpcMessage, sock: Socket) -> None:
    """Entry from the client InputMessenger (≈ ProcessRpcResponse,
    baidu_rpc_protocol.cpp:565)."""
    cid = msg.meta.correlation_id
    sock.remove_inflight(cid)
    ok, cntl = _idp.lock(cid)
    if not ok or cntl is None:
        if ok:
            _idp.unlock(cid)
        if msg.meta.ici_desc:
            from ..ici.endpoint import ack_unused
            ack_unused(msg.meta, getattr(sock, "id", 0))
        return                          # late response of a finished call
    cntl._on_response(msg)


def process_http_response(msg, sock: Socket) -> None:
    """Client side of the HTTP protocol: the in-flight call is identified
    by the connection (no multiplexing)."""
    cid = sock.correlation_id
    if not cid:
        return
    sock.correlation_id = 0
    sock.remove_inflight(cid)       # response delivery claims the id
    ok, cntl = _idp.lock(cid)
    if not ok or cntl is None:
        if ok:
            _idp.unlock(cid)
        return
    if msg.headers.get("x-lame-duck"):
        # HTTP spelling of the drain signal (rides success AND 503
        # responses): remove the node from LB selection, keep the
        # response
        from .naming_service import global_lame_ducks
        global_lame_ducks().mark(cntl.remote_side)
    if msg.status_code != 200:
        rpc_code = msg.headers.get("x-rpc-error-code")
        code = int(rpc_code) if rpc_code and rpc_code.isdigit() \
            else int(Errno.EHTTP)
        cntl._finish_locked(code,
                            f"HTTP {msg.status_code}: "
                            f"{msg.body[:200].decode('latin1', 'replace')}")
        return
    body = msg.body
    att_size = msg.headers.get("x-rpc-attachment-size")
    if att_size and att_size.isdigit():
        n = int(att_size)
        if 0 < n <= len(body):
            cntl.response_attachment = IOBuf(body[len(body) - n:])
            body = body[:len(body) - n]
    try:
        cntl.response = parse_payload(body, cntl._response_type)
    except Exception as e:
        cntl._finish_locked(Errno.ERESPONSE, f"response parse failed: {e}")
        return
    cntl._finish_locked(0, "")


def start_cancel(call_id: int) -> None:
    """≈ brpc::StartCancel(CallId): asynchronous, idempotent."""
    _idp.error(call_id, int(Errno.ECANCELLED), "cancelled by caller")
