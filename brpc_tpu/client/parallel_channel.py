"""ParallelChannel & SelectiveChannel — channel combinators.

≈ /root/reference/src/brpc/parallel_channel.h:94,127,168 and
selective_channel.h:52,69:

- **ParallelChannel** fans one call out to every sub-channel
  concurrently; a ``call_mapper(index, sub_channel, request)`` shapes the
  per-branch request (return ``SKIP`` to drop a branch), a
  ``response_merger(responses)`` folds branch responses; the call fails
  once more than ``fail_limit`` branches fail.
- **SelectiveChannel** load-balances whole calls over heterogeneous
  sub-channels with independent retry: a failed branch moves to another
  sub-channel (the failed one is excluded for that call).

On an ICI mesh, the fan-out data path is the mesh transport's
scatter/all_gather (see brpc_tpu.parallel) — these classes are the
host-side control plane with identical semantics over sockets.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

from ..butil.status import Errno
from ..butil.time_utils import monotonic_us
from ..deadline import cap_timeout_ms
from .channel import Channel
from .controller import Controller


def _leg_budget_ms(begin_us: int, timeout_ms: Optional[int]
                   ) -> Optional[int]:
    """The fan-out shares ONE budget: a leg launched ``elapsed`` after
    the fan-out began gets ``timeout_ms - elapsed``, not a fresh copy of
    the full timeout (a slow first leg must not let later legs run the
    total call past the caller's deadline).  ≤ 0 means the budget is
    spent — the leg fails fast.  None/unset timeouts pass through."""
    if not timeout_ms or timeout_ms <= 0:
        return timeout_ms
    return int(timeout_ms - (monotonic_us() - begin_us) // 1000)

SKIP = object()          # call_mapper return: skip this sub-channel


def default_call_mapper(index: int, sub_channel, request):
    return request


def default_response_merger(responses: List[Any]):
    return responses


class ParallelChannel:
    def __init__(self, fail_limit: int = -1):
        self._subs: List[tuple] = []
        self.fail_limit = fail_limit

    def add_channel(self, channel,
                    call_mapper: Optional[Callable] = None) -> None:
        """The fan-out merger is per-call (call_method's ``merger=``),
        not per-channel as in the reference — one merger over the ordered
        branch responses covers the same use cases."""
        self._subs.append((channel, call_mapper or default_call_mapper))

    @property
    def channel_count(self) -> int:
        return len(self._subs)

    def call_method(self, method_full: str, request: Any,
                    response_type: Any = None,
                    done: Optional[Callable] = None,
                    cntl: Optional[Controller] = None,
                    merger: Optional[Callable] = None) -> Controller:
        c = cntl or Controller()
        # deadline inheritance: a fan-out issued from a deadline'd
        # handler shares the upstream's remaining budget
        c.timeout_ms, amb_expired = cap_timeout_ms(c.timeout_ms)
        if amb_expired:
            c._fail_before_launch(int(Errno.ERPCTIMEDOUT),
                                  "inherited deadline already expired "
                                  "(doomed fan-out failed fast)", done)
            return c
        begin_us = monotonic_us()        # the ONE fan-out budget anchor
        merger = merger or default_response_merger
        branches: List[tuple] = []       # (index, sub, mapped_request)
        for i, (sub, mapper) in enumerate(self._subs):
            mapped = mapper(i, sub, request)
            if mapped is SKIP:
                continue
            branches.append((i, sub, mapped))
        if not branches:
            c._fail_before_launch(Errno.EPCHANFINISH, "all branches skipped",
                                  done)
            return c

        n = len(branches)
        fail_limit = self.fail_limit if self.fail_limit >= 0 else n

        if c.trace_id:
            # traced fan-out: one ROOT client span for the whole
            # scatter-gather; every branch parents to it (each branch
            # opens its own client span under the root, and the
            # sub-servers' spans parent to their branch) — one trace id
            # explains the entire call tree, stitched at /rpcz
            from ..rpcz import start_client_span
            root = start_client_span(f"ParallelChannel.{method_full}",
                                     c.trace_id, c.span_id)
            if root is not None:
                root.annotate(f"fan-out: {n} branches")
                c._client_span = root       # finished by _signal_ended
                c.span_id = root.span_id

        if done is None:
            # scatter-gather fast lane: all requests on the wire first,
            # then collect — no per-branch dispatcher/fiber machinery
            from . import fast_call
            left = _leg_budget_ms(begin_us, c.timeout_ms)
            if left is not None and c.timeout_ms and left <= 0:
                # the whole budget went to mapping/screening: nothing
                # may be sent (every leg would be doomed work)
                c._fail_before_launch(int(Errno.ERPCTIMEDOUT),
                                      "fan-out budget exhausted before "
                                      "any leg launched", done)
                return c
            sub_cntls = []
            scatter = []
            for i, sub, mapped in branches:
                # POOLED leg controllers (reset-on-reuse): the legs are
                # internal — completed, read and recycled inside this
                # call, so the fan-out stops paying an allocation + GC
                # churn per branch per call
                sc = Controller.obtain()
                # legs share the fan-out's remaining budget, not a
                # fresh copy of the full timeout
                sc.timeout_ms = left
                sc.max_retry = c.max_retry
                # branches are unary one-shots: exclusive pooled
                # connections let one thread own all the reads
                sc.connection_type = "pooled"
                # trace context flows to every branch; run_scatter
                # opens the per-branch client span under the root
                sc.trace_id = c.trace_id
                sc.span_id = c.span_id
                sub_cntls.append(sc)
                scatter.append((sub, sc, method_full, mapped,
                                response_type))
            if fast_call.run_scatter(scatter, left):
                failed = sum(1 for sc in sub_cntls if sc.failed)
                if failed > 0 and (failed >= fail_limit or failed == n):
                    codes = [sc.error_code for sc in sub_cntls
                             if sc.failed]
                    texts = [sc.error_text for sc in sub_cntls
                             if sc.failed]
                    c.set_failed(Errno.ETOOMANYFAILS,
                                 f"{failed}/{n} branches failed "
                                 f"(codes={codes[:4]}, first={texts[:1]})")
                else:
                    try:
                        c.response = merger(
                            [sc.response if not sc.failed else None
                             for sc in sub_cntls])
                    except Exception as e:
                        c.set_failed(Errno.EINTERNAL, f"merger raised: {e}")
                for sc in sub_cntls:
                    # responses/errors extracted above: the legs are
                    # dead weight now — back to the free list
                    sc.recycle()
                c._signal_ended()
                return c

        state = {
            "remaining": n, "failed": 0,
            "responses": [None] * n,
            "sub_cntls": [None] * n,
            "finished": False,
        }
        lock = threading.Lock()
        finished_evt = threading.Event()

        def finish() -> None:
            failed = state["failed"]
            if failed > 0 and (failed >= fail_limit or failed == n):
                codes = [sc.error_code for sc in state["sub_cntls"]
                         if sc is not None and sc.failed]
                texts = [sc.error_text for sc in state["sub_cntls"]
                         if sc is not None and sc.failed]
                c.set_failed(Errno.ETOOMANYFAILS,
                             f"{failed}/{n} branches failed "
                             f"(codes={codes[:4]}, first={texts[:1]})")
            else:
                try:
                    c.response = merger(list(state["responses"]))
                except Exception as e:
                    c.set_failed(Errno.EINTERNAL, f"merger raised: {e}")
            c._signal_ended()
            finished_evt.set()
            if done is not None:
                done(c)

        def on_branch_done(slot: int):
            def cb(sub_cntl: Controller) -> None:
                with lock:
                    if state["finished"]:
                        return
                    state["sub_cntls"][slot] = sub_cntl
                    if sub_cntl.failed:
                        state["failed"] += 1
                    else:
                        state["responses"][slot] = sub_cntl.response
                    state["remaining"] -= 1
                    fails_exceeded = (state["failed"] >= fail_limit
                                      and fail_limit > 0)
                    if state["remaining"] == 0 or fails_exceeded:
                        state["finished"] = True
                    else:
                        return
                finish()
            return cb

        for slot, (i, sub, mapped) in enumerate(branches):
            sub_cntl = Controller()
            # remaining-minus-elapsed: legs launch sequentially, and a
            # slow earlier launch already spent part of the one budget
            left = _leg_budget_ms(begin_us, c.timeout_ms)
            if left is not None and c.timeout_ms and left <= 0:
                sub_cntl._fail_before_launch(
                    int(Errno.ERPCTIMEDOUT),
                    "fan-out budget exhausted before this leg launched",
                    on_branch_done(slot))
                continue
            sub_cntl.timeout_ms = left
            sub_cntl.max_retry = c.max_retry
            # trace context flows to every branch; call_method opens
            # the per-branch client span under the root
            sub_cntl.trace_id = c.trace_id
            sub_cntl.span_id = c.span_id
            sub.call_method(method_full, mapped, response_type,
                            done=on_branch_done(slot), cntl=sub_cntl)
        if done is None:
            finished_evt.wait()
        return c


class SelectiveChannel:
    """Round-robin over sub-channels; each call picks one, failures move
    the call to another sub-channel (independent retry across channels).
    Sub-channels are typically cluster channels with their own LB, so
    channel-level selection stays simple by design."""

    def __init__(self, max_retry: int = 3):
        self._subs: List[Channel] = []
        self.max_retry = max_retry
        self._counter_lock = threading.Lock()
        self._rr = 0

    def add_channel(self, channel) -> int:
        self._subs.append(channel)
        return len(self._subs) - 1

    def _pick(self, excluded: set) -> Optional[int]:
        n = len(self._subs)
        with self._counter_lock:
            for _ in range(n):
                idx = self._rr % n
                self._rr += 1
                if idx not in excluded:
                    return idx
        return None

    def call_method(self, method_full: str, request: Any,
                    response_type: Any = None,
                    done: Optional[Callable] = None,
                    cntl: Optional[Controller] = None) -> Controller:
        c = cntl or Controller()
        if not self._subs:
            c._fail_before_launch(Errno.EINTERNAL, "no sub channels", done)
            return c
        # deadline inheritance + one shared budget across sub-channel
        # attempts: attempt k+1 gets what attempt k left, not a fresh
        # copy of the full timeout
        c.timeout_ms, amb_expired = cap_timeout_ms(c.timeout_ms)
        if amb_expired:
            c._fail_before_launch(int(Errno.ERPCTIMEDOUT),
                                  "inherited deadline already expired "
                                  "(doomed call failed fast)", done)
            return c
        begin_us = monotonic_us()
        excluded: set = set()
        attempts = min(self.max_retry + 1, len(self._subs))

        def attempt(k: int) -> None:
            idx = self._pick(excluded)
            if idx is None:
                c.set_failed(Errno.ETOOMANYFAILS, "all sub channels failed")
                c._signal_ended()
                if done is not None:
                    done(c)
                return
            left = _leg_budget_ms(begin_us, c.timeout_ms)
            if left is not None and c.timeout_ms and left <= 0:
                c.set_failed(Errno.ERPCTIMEDOUT,
                             "budget exhausted across sub-channel "
                             "attempts")
                c._signal_ended()
                if done is not None:
                    done(c)
                return
            sub_cntl = Controller()
            sub_cntl.timeout_ms = left

            def cb(sc: Controller) -> None:
                if not sc.failed:
                    c.response = sc.response
                    c.response_attachment = sc.response_attachment
                    c.remote_side = sc.remote_side
                    c._signal_ended()
                    if done is not None:
                        done(c)
                    return
                excluded.add(idx)
                if k + 1 < attempts:
                    attempt(k + 1)
                else:
                    c.set_failed(sc.error_code, sc.error_text)
                    c._signal_ended()
                    if done is not None:
                        done(c)

            self._subs[idx].call_method(method_full, request,
                                        response_type, done=cb,
                                        cntl=sub_cntl)

        attempt(0)
        if done is None:
            c.join()
        return c
