"""Memcached client — text protocol
(≈ /root/reference/src/brpc/memcache.h + policy/memcache_binary_protocol;
the reference speaks the binary protocol, this client speaks the text
protocol — same capability surface: get/set/add/replace/delete/incr/decr
with flags + exptime + CAS).
"""

from __future__ import annotations

import socket as _socket
import threading
from typing import Dict, Optional, Tuple

from ..butil.endpoint import EndPoint, parse_endpoint


class MemcacheError(Exception):
    pass


class MemcacheClient:
    def __init__(self, addr, timeout_s: float = 2.0):
        self._remote: EndPoint = addr if isinstance(addr, EndPoint) \
            else parse_endpoint(str(addr))
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        self._sock: Optional[_socket.socket] = None
        self._buf = b""

    def _ensure(self) -> None:
        if self._sock is None:
            s = _socket.create_connection(self._remote.to_sockaddr(),
                                          timeout=self._timeout_s)
            s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            self._sock = s
            self._buf = b""

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("memcached closed the connection")
            self._buf += chunk
        line, _, self._buf = self._buf.partition(b"\r\n")
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("memcached closed the connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    # -- storage ------------------------------------------------------------

    def _store(self, verb: str, key: str, value: bytes, flags: int,
               exptime: int, cas: Optional[int] = None) -> bool:
        data = value if isinstance(value, bytes) else str(value).encode()
        head = f"{verb} {key} {flags} {exptime} {len(data)}"
        if cas is not None:
            head += f" {cas}"
        with self._lock:
            self._ensure()
            self._sock.sendall(head.encode() + b"\r\n" + data + b"\r\n")
            resp = self._read_line()
        if resp == b"STORED":
            return True
        if resp in (b"NOT_STORED", b"EXISTS", b"NOT_FOUND"):
            return False
        raise MemcacheError(resp.decode("utf-8", "replace"))

    def set(self, key: str, value, flags: int = 0, exptime: int = 0) -> bool:
        return self._store("set", key, value, flags, exptime)

    def add(self, key: str, value, flags: int = 0, exptime: int = 0) -> bool:
        return self._store("add", key, value, flags, exptime)

    def replace(self, key: str, value, flags: int = 0,
                exptime: int = 0) -> bool:
        return self._store("replace", key, value, flags, exptime)

    def cas(self, key: str, value, cas_id: int, flags: int = 0,
            exptime: int = 0) -> bool:
        return self._store("cas", key, value, flags, exptime, cas_id)

    # -- retrieval -----------------------------------------------------------

    def get(self, key: str) -> Optional[bytes]:
        out = self.gets(key)
        return out[0] if out is not None else None

    def gets(self, key: str) -> Optional[Tuple[bytes, int, Optional[int]]]:
        """(value, flags, cas_id) or None."""
        with self._lock:
            self._ensure()
            self._sock.sendall(f"gets {key}\r\n".encode())
            out: Dict[str, Tuple[bytes, int, Optional[int]]] = {}
            while True:
                line = self._read_line()
                if line == b"END":
                    break
                parts = line.split()
                if parts[0] != b"VALUE":
                    raise MemcacheError(line.decode("utf-8", "replace"))
                k = parts[1].decode()
                flags, n = int(parts[2]), int(parts[3])
                cas_id = int(parts[4]) if len(parts) > 4 else None
                data = self._read_exact(n)
                self._read_exact(2)      # trailing \r\n
                out[k] = (data, flags, cas_id)
        return out.get(key)

    # -- misc ----------------------------------------------------------------

    def delete(self, key: str) -> bool:
        with self._lock:
            self._ensure()
            self._sock.sendall(f"delete {key}\r\n".encode())
            return self._read_line() == b"DELETED"

    def _arith(self, verb: str, key: str, delta: int) -> Optional[int]:
        with self._lock:
            self._ensure()
            self._sock.sendall(f"{verb} {key} {delta}\r\n".encode())
            resp = self._read_line()
        if resp == b"NOT_FOUND":
            return None
        if resp.isdigit():
            return int(resp)
        raise MemcacheError(resp.decode("utf-8", "replace"))

    def incr(self, key: str, delta: int = 1) -> Optional[int]:
        return self._arith("incr", key, delta)

    def decr(self, key: str, delta: int = 1) -> Optional[int]:
        return self._arith("decr", key, delta)

    def version(self) -> str:
        with self._lock:
            self._ensure()
            self._sock.sendall(b"version\r\n")
            line = self._read_line()
        return line.decode("utf-8", "replace")
