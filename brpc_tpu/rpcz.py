"""rpcz — per-RPC span tracing.

≈ /root/reference/src/brpc/span.h:47-84 + builtin/rpcz_service.cpp:
spans are rate-limited samples (bvar Collector, collector.h:57-72) so
tracing can stay always-on; trace context (trace_id/span_id/parent) rides
the tpu_std meta; storage is an in-memory bounded store browsable at
/rpcz (the reference uses leveldb — deliberately simpler here, same
capability surface: recent spans by id/time, annotations).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from .butil.fast_rand import fast_rand
from .butil.flags import define_flag, get_flag, any_value
from .bvar.collector import Collected, Collector

define_flag("enable_rpcz", True, "collect per-RPC spans", any_value)
define_flag("rpcz_keep_spans", 2048, "max spans kept in memory",
            lambda v: v > 0)
define_flag("rpcz_max_samples_per_second", 1000,
            "rpcz sampling budget (traced calls always record)",
            lambda v: int(v) >= 0)

_span_seq = itertools.count(1)


class Span(Collected):
    __slots__ = ("trace_id", "span_id", "parent_span_id", "full_method",
                 "remote_side", "received_us", "start_us", "end_us",
                 "error_code", "request_size", "response_size",
                 "annotations", "is_server")

    def __init__(self, full_method: str, trace_id: int = 0,
                 parent_span_id: int = 0, is_server: bool = True):
        self.trace_id = trace_id or fast_rand()
        self.span_id = next(_span_seq)
        self.parent_span_id = parent_span_id
        self.full_method = full_method
        self.remote_side = ""
        self.received_us = int(time.time() * 1e6)
        self.start_us = self.received_us
        self.end_us = 0
        self.error_code = 0
        self.request_size = 0
        self.response_size = 0
        self.annotations: List[tuple] = []
        self.is_server = is_server

    def annotate(self, text: str) -> None:
        """≈ TRACEPRINTF (src/brpc/traceprintf.h)."""
        self.annotations.append((int(time.time() * 1e6), text))

    def finish(self, error_code: int = 0) -> None:
        self.end_us = int(time.time() * 1e6)
        self.error_code = error_code
        global_span_store().add(self)

    @property
    def latency_us(self) -> int:
        return (self.end_us or int(time.time() * 1e6)) - self.received_us

    def describe(self) -> Dict:
        return {
            "trace_id": f"{self.trace_id:x}",
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "method": self.full_method,
            "remote": self.remote_side,
            "received_us": self.received_us,
            "latency_us": self.latency_us,
            "error_code": self.error_code,
            "request_size": self.request_size,
            "response_size": self.response_size,
            "side": "server" if self.is_server else "client",
            "annotations": [
                {"us": ts, "text": txt} for ts, txt in self.annotations],
        }


class SpanStore:
    """Bounded recent-span store, indexed by trace id."""

    def __init__(self):
        self._lock = threading.Lock()
        self._spans: Deque[Span] = deque()
        # rate limiter: at most ~1000 spans/s retained (collector.h role)
        self._collector = Collector()

    def add(self, span: Span) -> None:
        if not self._collector.submit(span):
            return                        # over the rate budget: sampled out
        self._collector.drain()           # used purely as a rate limiter
        keep = get_flag("rpcz_keep_spans", 2048)
        with self._lock:
            self._spans.append(span)
            while len(self._spans) > keep:
                self._spans.popleft()

    def recent(self, limit: int = 100) -> List[Span]:
        with self._lock:
            return list(self._spans)[-limit:]

    def by_trace(self, trace_id: int) -> List[Span]:
        with self._lock:
            return [s for s in self._spans if s.trace_id == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


_store: Optional[SpanStore] = None
_store_lock = threading.Lock()


def global_span_store() -> SpanStore:
    global _store
    with _store_lock:
        if _store is None:
            _store = SpanStore()
        return _store


def rpcz_enabled() -> bool:
    return bool(get_flag("enable_rpcz", True))


_sample_window = [0.0, 0, 1000]    # window start (s), taken, budget


def start_server_span(full_method: str, meta, remote_side) -> Optional[Span]:
    """Called by the dispatch layer per request (None when disabled or
    over the sampling budget).  Like the reference's Collector-budgeted
    rpcz sampling (/root/reference/src/bvar/collector.cpp), at most
    ``rpcz_max_samples_per_second`` spans are recorded per second so
    tracing never dominates the request path; traced calls (non-zero
    trace_id) always record."""
    if not rpcz_enabled():
        return None
    w = _sample_window
    if not meta.trace_id:
        import time as _time
        now = _time.monotonic()
        if now - w[0] >= 1.0:
            w[0] = now
            w[1] = 0
            w[2] = int(get_flag("rpcz_max_samples_per_second", 1000))
        if w[1] >= w[2]:
            return None
        w[1] += 1
    span = Span(full_method, trace_id=meta.trace_id,
                parent_span_id=meta.span_id, is_server=True)
    span.remote_side = str(remote_side or "")
    return span
