"""rpcz — per-RPC span tracing.

≈ /root/reference/src/brpc/span.h:47-84 + builtin/rpcz_service.cpp:
spans are rate-limited samples (bvar Collector, collector.h:57-72) so
tracing can stay always-on; trace context (trace_id/span_id/parent)
rides EVERY wire protocol — the tpu_std meta TLVs, a W3C
``traceparent`` header on HTTP/1.1, and the same header over gRPC/h2
(HPACK) — so one trace id explains a whole cross-protocol call tree.
Storage is an in-memory bounded store (trace-id indexed) browsable at
/rpcz (the reference uses leveldb — deliberately simpler here, same
capability surface: recent spans by id/time, annotations); the
cross-process stitcher lives in rpcz_stitch.py.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from .butil.fast_rand import fast_rand
from .butil.flags import define_flag, get_flag, any_value
from .bvar.collector import Collected, Collector

define_flag("enable_rpcz", True, "collect per-RPC spans", any_value)
define_flag("rpcz_keep_spans", 2048, "max spans kept in memory",
            lambda v: v > 0)
define_flag("rpcz_max_samples_per_second", 1000,
            "rpcz sampling budget (traced calls always record)",
            lambda v: int(v) >= 0)
define_flag("rpcz_dir", "",
            "also persist spans to sqlite files here (one per process) "
            "— post-mortem time-range browsing survives the process; "
            "'' = in-memory only", any_value)
define_flag("rpcz_db_max_spans", 200_000,
            "per-process cap on persisted spans (oldest trimmed)",
            lambda v: int(v) > 0)

# span ids must stay unique ACROSS processes for stitched traces (a
# child span in another rank links back by parent_span_id alone): seed
# the per-process counter into a random 48-bit window instead of 1, so
# two ranks' sequences virtually never collide while ids stay compact
# enough for sqlite/JSON round trips
_span_seq = itertools.count((fast_rand() & ((1 << 47) - 1)) | (1 << 47))


class Span(Collected):
    __slots__ = ("trace_id", "span_id", "parent_span_id", "full_method",
                 "remote_side", "received_us", "start_us", "end_us",
                 "error_code", "request_size", "response_size",
                 "annotations", "is_server", "forced", "mono_ns")

    def __init__(self, full_method: str, trace_id: int = 0,
                 parent_span_id: int = 0, is_server: bool = True):
        # an explicit trace context means someone is following THIS
        # call: it must never be sampled out, whatever the budget
        self.forced = bool(trace_id)
        self.trace_id = trace_id or fast_rand()
        self.span_id = next(_span_seq)
        self.parent_span_id = parent_span_id
        self.full_method = full_method
        self.remote_side = ""
        self.received_us = int(time.time() * 1e6)
        self.start_us = self.received_us
        self.end_us = 0
        # CLOCK_MONOTONIC anchor: comparable across processes on ONE
        # host (same clock since boot) — the stitcher uses it to flag
        # wall-clock skew instead of silently mis-ordering spans
        self.mono_ns = time.monotonic_ns()
        self.error_code = 0
        self.request_size = 0
        self.response_size = 0
        self.annotations: List[tuple] = []
        self.is_server = is_server

    def annotate(self, text: str) -> None:
        """≈ TRACEPRINTF (src/brpc/traceprintf.h)."""
        self.annotations.append((int(time.time() * 1e6), text))

    def finish(self, error_code: int = 0) -> None:
        self.end_us = int(time.time() * 1e6)
        self.error_code = error_code
        global_span_store().add(self)

    @property
    def latency_us(self) -> int:
        return (self.end_us or int(time.time() * 1e6)) - self.received_us

    def describe(self) -> Dict:
        return {
            "trace_id": f"{self.trace_id:x}",
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "method": self.full_method,
            "remote": self.remote_side,
            "received_us": self.received_us,
            "start_us": self.start_us,
            "end_us": self.end_us,
            "latency_us": self.latency_us,
            "mono_ns": self.mono_ns,
            "error_code": self.error_code,
            "request_size": self.request_size,
            "response_size": self.response_size,
            "side": "server" if self.is_server else "client",
            "annotations": [
                {"us": ts, "text": txt} for ts, txt in self.annotations],
        }


class SpanStore:
    """Bounded recent-span store, indexed by trace id; optionally
    mirrored to a per-process sqlite file for post-mortem browsing."""

    def __init__(self):
        self._lock = threading.Lock()
        self._spans: Deque[Span] = deque()
        # trace_id -> spans, maintained on add/evict: by_trace is the
        # stitcher's hot query and must not scan the whole deque
        self._by_trace: Dict[int, List[Span]] = {}
        # rate limiter: at most ~1000 spans/s retained (collector.h role)
        self._collector = Collector()
        self._pending: List[Span] = []      # awaiting the disk flusher
        self._flusher: Optional[threading.Thread] = None

    def add(self, span: Span) -> None:
        if not span.forced and not self._collector.submit(span):
            return                        # over the rate budget: sampled out
        self._collector.drain()           # used purely as a rate limiter
        keep = get_flag("rpcz_keep_spans", 2048)
        with self._lock:
            self._spans.append(span)
            self._by_trace.setdefault(span.trace_id, []).append(span)
            while len(self._spans) > keep:
                old = self._spans.popleft()
                lst = self._by_trace.get(old.trace_id)
                if lst is not None:
                    # eviction order matches insertion order, so the
                    # evictee is (almost always) the list head
                    if lst and lst[0] is old:
                        lst.pop(0)
                    else:
                        try:
                            lst.remove(old)
                        except ValueError:
                            pass
                    if not lst:
                        del self._by_trace[old.trace_id]
            if get_flag("rpcz_dir", ""):
                self._pending.append(span)
                if self._flusher is None:
                    self._flusher = threading.Thread(
                        target=_flush_loop, args=(self,),
                        name="rpcz-flush", daemon=True)
                    self._flusher.start()

    def take_pending(self) -> List[Span]:
        with self._lock:
            out, self._pending = self._pending, []
            return out

    def recent(self, limit: int = 100) -> List[Span]:
        with self._lock:
            return list(self._spans)[-limit:]

    def by_trace(self, trace_id: int, limit: int = 0) -> List[Span]:
        with self._lock:
            spans = list(self._by_trace.get(trace_id, ()))
        return spans[-limit:] if limit else spans

    def flush_now(self) -> None:
        """Synchronously persist anything pending (tests, shutdown)."""
        _flush_pending(self)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._by_trace.clear()
            self._pending.clear()


# -- persistence (≈ span.cpp:306-319's leveldb pair: the reference keys
# spans by time in one db and by id in another; sqlite gives both
# indexes in one file, and a dead rank's file stays browsable) ---------

_FLUSH_PERIOD_S = 1.0


def _to_i64(v: int) -> int:
    """uint64 ids (fast_rand trace ids) -> sqlite's signed INTEGER.
    Without this, ~half of all random trace ids overflow the bind and
    the whole flush batch rolls back."""
    v &= (1 << 64) - 1
    return v - (1 << 64) if v >= (1 << 63) else v


def _from_i64(v: int) -> int:
    return v + (1 << 64) if v < 0 else v


def _db_path() -> Optional[str]:
    import os
    d = str(get_flag("rpcz_dir", "") or "")
    if not d:
        return None
    os.makedirs(d, exist_ok=True)
    return f"{d}/rpcz.{os.getpid()}.db"


def _open_db(path: str):
    import sqlite3
    # check_same_thread=False: the flusher thread owns steady-state
    # writes, but flush_now() (portal requests, shutdown) flushes from
    # other threads — _db_lock serializes all access
    db = sqlite3.connect(path, timeout=5.0, check_same_thread=False)
    db.execute("""CREATE TABLE IF NOT EXISTS spans (
        received_us INTEGER, trace_id INTEGER, span_id INTEGER,
        parent_span_id INTEGER, method TEXT, remote TEXT,
        latency_us INTEGER, error_code INTEGER, request_size INTEGER,
        response_size INTEGER, side TEXT, annotations TEXT)""")
    db.execute("CREATE INDEX IF NOT EXISTS idx_time "
               "ON spans (received_us)")
    db.execute("CREATE INDEX IF NOT EXISTS idx_trace ON spans (trace_id)")
    return db


# cached writer connection: reopening + CREATE + COUNT(*) per 1s flush
# is pure overhead — keep the handle and track the row count
# incrementally (COUNT runs once per open)
_db_lock = threading.Lock()
_db_conn = None
_db_conn_path: Optional[str] = None
_db_rows = 0


def _flush_pending(store: "SpanStore") -> None:
    """Persist pending spans.  Never raises and never kills the caller:
    a broken rpcz_dir drops the batch (logged) instead of growing
    _pending forever."""
    global _db_conn, _db_conn_path, _db_rows
    import json as _json
    try:
        path = _db_path()
    except OSError:
        from .butil.logging_util import LOG
        LOG.exception("rpcz_dir unusable; dropping pending spans")
        store.take_pending()
        return
    if path is None:
        store.take_pending()      # dir cleared while spans were pending
        return
    spans = store.take_pending()
    if not spans:
        return
    try:
        with _db_lock:
            if _db_conn is None or _db_conn_path != path:
                if _db_conn is not None:
                    _db_conn.close()
                _db_conn = _open_db(path)
                _db_conn_path = path
                (_db_rows,) = _db_conn.execute(
                    "SELECT COUNT(*) FROM spans").fetchone()
            db = _db_conn
            with db:
                db.executemany(
                    "INSERT INTO spans VALUES (?,?,?,?,?,?,?,?,?,?,?,?)",
                    [(s.received_us, _to_i64(s.trace_id),
                      _to_i64(s.span_id), _to_i64(s.parent_span_id),
                      s.full_method, s.remote_side,
                      s.latency_us, s.error_code, s.request_size,
                      s.response_size,
                      "server" if s.is_server else "client",
                      _json.dumps(s.annotations)) for s in spans])
                _db_rows += len(spans)
                cap = int(get_flag("rpcz_db_max_spans", 200_000))
                if _db_rows > cap:
                    db.execute(
                        "DELETE FROM spans WHERE rowid IN (SELECT rowid "
                        "FROM spans ORDER BY received_us LIMIT ?)",
                        (_db_rows - cap,))
                    _db_rows = cap
    except Exception:                      # persistence must never take
        from .butil.logging_util import LOG  # down the serving path
        LOG.exception("rpcz flush failed")
        with _db_lock:
            if _db_conn is not None:
                try:
                    _db_conn.close()
                except Exception:
                    pass
            _db_conn = None
            _db_conn_path = None


def _flush_loop(store: "SpanStore") -> None:
    while True:
        time.sleep(_FLUSH_PERIOD_S)
        try:
            _flush_pending(store)
        except Exception:          # belt-and-braces: the flusher thread
            pass                   # must survive anything


def browse_persisted(start_us: int = 0, end_us: int = 0,
                     limit: int = 100, trace_id: int = 0,
                     rpcz_dir: str = "") -> List[Dict]:
    """Time-range browse across every rpcz db in the directory —
    including files left by DEAD processes (the post-mortem story the
    in-memory store cannot tell).  Results newest-first."""
    import glob
    import json as _json
    import os
    import sqlite3
    d = str(rpcz_dir or get_flag("rpcz_dir", "") or "")
    if not d or not os.path.isdir(d):
        return []
    where, args = [], []
    if start_us:
        where.append("received_us >= ?")
        args.append(int(start_us))
    if end_us:
        where.append("received_us <= ?")
        args.append(int(end_us))
    if trace_id:
        where.append("trace_id = ?")
        args.append(_to_i64(int(trace_id)))
    q = "SELECT * FROM spans"
    if where:
        q += " WHERE " + " AND ".join(where)
    q += " ORDER BY received_us DESC LIMIT ?"
    out: List[Dict] = []
    for path in sorted(glob.glob(os.path.join(d, "rpcz.*.db"))):
        db = None
        try:
            db = sqlite3.connect(path, timeout=5.0)
            db.row_factory = sqlite3.Row
            for row in db.execute(q, args + [int(limit)]):
                rec = dict(row)
                rec["trace_id"] = f"{_from_i64(rec['trace_id']):x}"
                rec["span_id"] = _from_i64(rec["span_id"])
                rec["parent_span_id"] = _from_i64(rec["parent_span_id"])
                try:
                    rec["annotations"] = [
                        {"us": ts, "text": txt}
                        for ts, txt in _json.loads(rec["annotations"])]
                except (ValueError, TypeError):
                    rec["annotations"] = []
                rec["source_db"] = os.path.basename(path)
                out.append(rec)
        except sqlite3.Error:
            continue                       # unreadable/corrupt db: skip
        finally:
            if db is not None:             # close even when a mid-query
                db.close()                 # error skips to the except
    out.sort(key=lambda r: r["received_us"], reverse=True)
    return out[:limit]


_store: Optional[SpanStore] = None
_store_lock = threading.Lock()


def global_span_store() -> SpanStore:
    global _store
    with _store_lock:
        if _store is None:
            _store = SpanStore()
        return _store


def rpcz_enabled() -> bool:
    return bool(get_flag("enable_rpcz", True))


# flag-cached mirror of rpcz_enabled for the per-request fast paths
# (one list read instead of a flags-table lookup per call); resynced by
# the watcher on every live flip
from .butil.flags import watch_flag as _watch_flag

_rpcz_live = [bool(get_flag("enable_rpcz", True))]
_watch_flag("enable_rpcz",
            lambda v: _rpcz_live.__setitem__(0, bool(v)))


def passive_server_span(full_method: str, remote_side) -> Optional["Span"]:
    """The slim fast template's span gate for UNTRACED requests: same
    budgeted passive sampling as :func:`start_server_span`, with the
    enabled check flag-cached (traced requests never reach this — the
    shim routes them through the full gate, which always records)."""
    if not _rpcz_live[0] or not _passive_sample_gate():
        return None
    span = Span(full_method, trace_id=0, parent_span_id=0,
                is_server=True)
    span.remote_side = str(remote_side or "")
    return span


_sample_window = [0.0, 0, 1000]    # window start (s), taken, budget


def _passive_sample_gate() -> bool:
    """One-per-second-window budget check shared by every passive
    sampling entry point — True takes one slot from this second's
    ``rpcz_max_samples_per_second`` budget."""
    import time as _time
    w = _sample_window
    now = _time.monotonic()
    if now - w[0] >= 1.0:
        w[0] = now
        w[1] = 0
        w[2] = int(get_flag("rpcz_max_samples_per_second", 1000))
    if w[1] >= w[2]:
        return False
    w[1] += 1
    return True


def start_server_span(full_method: str, meta, remote_side) -> Optional[Span]:
    """Called by the dispatch layer per request (None when disabled or
    over the sampling budget).  Like the reference's Collector-budgeted
    rpcz sampling (/root/reference/src/bvar/collector.cpp), at most
    ``rpcz_max_samples_per_second`` spans are recorded per second so
    tracing never dominates the request path; traced calls (non-zero
    trace_id) always record."""
    if not rpcz_enabled():
        return None
    if not meta.trace_id and not _passive_sample_gate():
        return None
    span = Span(full_method, trace_id=meta.trace_id,
                parent_span_id=meta.span_id, is_server=True)
    span.remote_side = str(remote_side or "")
    return span


def start_client_span(full_method: str, trace_id: int,
                      parent_span_id: int = 0) -> Optional[Span]:
    """Client-side span for an EXPLICITLY traced call (cntl.trace_id
    set): forced spans always record, so the caller's half of the round
    trip shows up next to the server span it parents.  Untraced calls
    return None — passive client sampling would put span churn on the
    latency fast lanes, and the server side already samples those."""
    if not rpcz_enabled() or not trace_id:
        return None
    return Span(full_method, trace_id=trace_id,
                parent_span_id=parent_span_id, is_server=False)


def backdate_span(span: Optional[Span], recv_mono_ns) -> None:
    """Stamp a slim-lane span with the ENGINE's receive timestamp: the
    C++ loop records CLOCK_MONOTONIC ns when it parses the frame — the
    same clock as Python's ``time.monotonic_ns()`` — and passes it
    through the shim call.  ``received_us`` moves back by the elapsed
    monotonic delta, so the span covers the native queueing/batching
    delay instead of starting at shim entry; ``start_us`` keeps the
    shim-entry time, making the queueing visible as received->start.
    The monotonic anchor moves to the engine timestamp with it."""
    if span is None or not recv_mono_ns:
        return
    delta_us = (time.monotonic_ns() - recv_mono_ns) // 1000
    if delta_us > 0:
        span.received_us -= delta_us
        span.mono_ns = recv_mono_ns


# -- W3C trace-context mapping (https://www.w3.org/TR/trace-context/) --
#
# HTTP/1.1 and gRPC/h2 carry the trace context as a ``traceparent``
# header instead of meta TLVs:
#
#     traceparent: 00-<32 hex trace-id>-<16 hex parent-id>-<2 hex flags>
#
# The internal model is 64-bit ids (fast_rand), so the 128-bit wire
# trace-id keeps our id in its LOW 64 bits; a foreign 128-bit id from
# an external W3C peer is truncated to its low 64 bits consistently on
# every hop, which preserves linkage within this system.

def format_traceparent(trace_id: int, span_id: int) -> str:
    """``traceparent`` header value for an outbound call: the caller's
    span id rides as the parent-id field (exactly the tpu_std meta's
    trace_id/span_id pair re-spelled)."""
    return (f"00-{trace_id & ((1 << 128) - 1):032x}"
            f"-{span_id & ((1 << 64) - 1):016x}-01")


def parse_traceparent(value) -> Optional[tuple]:
    """``(trace_id, parent_span_id)`` from a traceparent header value
    (str or bytes), or None when malformed.  Unknown versions are
    accepted if the first four fields parse (per spec: treat like 00)."""
    if value is None:
        return None
    if isinstance(value, (bytes, bytearray, memoryview)):
        try:
            value = bytes(value).decode("ascii")
        except UnicodeDecodeError:
            return None
    parts = value.strip().split("-")
    if len(parts) < 4 or len(parts[0]) != 2 or len(parts[1]) != 32 \
            or len(parts[2]) != 16:
        return None
    try:
        int(parts[0], 16)
        trace = int(parts[1], 16)
        parent = int(parts[2], 16)
    except ValueError:
        return None
    if trace == 0:
        return None                    # all-zero trace-id is invalid
    return trace & ((1 << 64) - 1), parent
