"""HTTP/1.x protocol — served on the same port as every other protocol.

≈ /root/reference/src/brpc/policy/http_rpc_protocol.cpp +
details/http_message.* (capability, fresh parser): requests route either
to RPC methods (``/Service/Method``, body = payload, JSON or raw) or to
the builtin observability portal; the client side packs RPC calls as
HTTP for interop. HTTP/1.1 keep-alive, content-length and chunked
bodies, case-insensitive headers.
"""

from __future__ import annotations

import struct
from time import monotonic_ns as _monotonic_ns
from typing import Dict, List, Optional, Tuple

from ..butil.iobuf import IOBuf
from .base import (ParseError, ParseResult, Protocol,
                   ProtocolType, max_body_size, register_protocol)

_METHODS = (b"GET ", b"POST", b"PUT ", b"DELE", b"HEAD", b"OPTI", b"PATC")
_MAX_HEADER = 16 * 1024

STATUS_REASONS = {
    200: "OK", 204: "No Content", 301: "Moved Permanently",
    302: "Found", 400: "Bad Request", 403: "Forbidden",
    404: "Not Found", 405: "Method Not Allowed",
    500: "Internal Server Error", 501: "Not Implemented",
    503: "Service Unavailable",
}


class HttpHeaders:
    """Case-ignored header map (≈ case_ignored_flat_map for HTTP headers,
    SURVEY.md §2.1). Preserves insertion order for serialization."""

    def __init__(self):
        self._items: List[Tuple[str, str]] = []
        self._index: Dict[str, int] = {}

    def set(self, key: str, value: str) -> None:
        k = key.lower()
        if k in self._index:
            self._items[self._index[k]] = (key, value)
        else:
            self._index[k] = len(self._items)
            self._items.append((key, value))

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        idx = self._index.get(key.lower())
        return self._items[idx][1] if idx is not None else default

    def items(self):
        return list(self._items)

    def __contains__(self, key: str) -> bool:
        return key.lower() in self._index


class HttpMessage:
    __slots__ = ("is_request", "method", "path", "query_string",
                 "version", "status_code", "reason", "headers", "body",
                 "socket_id", "recv_us")

    def __init__(self):
        self.is_request = True
        self.method = ""
        self.path = "/"
        self.query_string = ""
        self.version = "HTTP/1.1"
        self.status_code = 200
        self.reason = "OK"
        self.headers = HttpHeaders()
        self.body = b""
        self.socket_id = 0
        # arrival anchor for the deadline plane (x-deadline-ms):
        # construction ≈ parse time on every ingest path
        self.recv_us = _monotonic_ns() // 1000

    @property
    def keep_alive(self) -> bool:
        conn = (self.headers.get("connection") or "").lower()
        if self.version == "HTTP/1.0":
            return conn == "keep-alive"
        return conn != "close"

    def query(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for pair in self.query_string.split("&"):
            if not pair:
                continue
            k, _, v = pair.partition("=")
            out[_unquote(k)] = _unquote(v)
        return out


def _unquote(s: str) -> str:
    from urllib.parse import unquote_plus
    return unquote_plus(s)


def _parse_headers(block: bytes) -> Optional[HttpHeaders]:
    headers = HttpHeaders()
    for line in block.split(b"\r\n"):
        if not line:
            continue
        k, sep, v = line.partition(b":")
        if not sep:
            return None
        try:
            headers.set(k.decode("latin1").strip(),
                        v.decode("latin1").strip())
        except UnicodeDecodeError:
            return None
    return headers


def _decode_chunked(data: bytes) -> Optional[Tuple[bytes, int]]:
    """Returns (body, consumed) or None if incomplete/invalid."""
    body = bytearray()
    off = 0
    while True:
        end = data.find(b"\r\n", off)
        if end < 0:
            return None
        try:
            size = int(data[off:end].split(b";")[0], 16)
        except ValueError:
            return None
        off = end + 2
        if size == 0:
            trailer_end = data.find(b"\r\n", off)
            if trailer_end < 0:
                return None
            # skip trailers until blank line
            while data[off:off + 2] != b"\r\n":
                nxt = data.find(b"\r\n", off)
                if nxt < 0:
                    return None
                off = nxt + 2
            return bytes(body), off + 2
        if len(data) < off + size + 2:
            return None
        body += data[off:off + size]
        off += size + 2


def parse(source: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    avail = len(source)
    if avail < 4:
        return ParseResult.not_enough_data() if _maybe_http(
            source.fetch(avail)) else ParseResult.try_others()
    head4 = source.fetch(4)
    if not _maybe_http(head4):
        return ParseResult.try_others()
    # peek only the header region first — copying the whole buffered body
    # on every nibble would make large uploads O(n^2)
    window = source.fetch(min(avail, _MAX_HEADER))
    header_end = window.find(b"\r\n\r\n")
    if header_end < 0:
        # Commitment check (mirrors the native engine's sniff rule): a
        # 4-byte method-token prefix is not proof of HTTP — a complete
        # first line without the version marker (redis "GET k\r\n", any
        # colliding protocol) must yield to the other handlers instead
        # of holding the connection against a CRLFCRLF that never comes.
        nl = window.find(b"\n")
        if nl >= 0 and b" HTTP/1." not in window[:nl] \
                and not window.startswith(b"HTTP/1."):
            return ParseResult.try_others()
        if avail > _MAX_HEADER:
            return ParseResult.absolutely_wrong()
        return ParseResult.not_enough_data()
    start_line, _, rest = window[:header_end].partition(b"\r\n")
    headers = _parse_headers(rest)
    if headers is None:
        return ParseResult.absolutely_wrong()

    msg = HttpMessage()
    msg.socket_id = getattr(sock, "id", 0)
    parts = start_line.split(None, 2)
    if start_line.startswith(b"HTTP/"):
        msg.is_request = False
        if len(parts) < 2:
            return ParseResult.absolutely_wrong()
        msg.version = parts[0].decode("latin1")
        try:
            msg.status_code = int(parts[1])
        except ValueError:
            return ParseResult.absolutely_wrong()
        msg.reason = parts[2].decode("latin1") if len(parts) > 2 else ""
    else:
        if len(parts) < 3:
            return ParseResult.absolutely_wrong()
        msg.method = parts[0].decode("latin1").upper()
        target = parts[1].decode("latin1")
        msg.version = parts[2].decode("latin1")
        msg.path, _, msg.query_string = target.partition("?")
    msg.headers = headers

    body_start = header_end + 4
    te = (headers.get("transfer-encoding") or "").lower()
    if "chunked" in te:
        # chunked needs the raw stream; copy past the header only here
        tail = source.fetch(min(avail, body_start + max_body_size()))
        decoded = _decode_chunked(tail[body_start:])
        if decoded is None:
            if avail >= body_start + max_body_size():
                return ParseResult.too_big()
            return ParseResult.not_enough_data()
        msg.body, consumed = decoded
        total = body_start + consumed
    else:
        try:
            clen = int(headers.get("content-length") or "0")
        except ValueError:
            return ParseResult.absolutely_wrong()
        if clen < 0:
            return ParseResult.absolutely_wrong()
        if clen > max_body_size():
            return ParseResult.too_big()
        total = body_start + clen
        if avail < total:
            return ParseResult.not_enough_data()   # no body copy yet
        if total <= len(window):
            msg.body = window[body_start:total]
        else:
            msg.body = source.fetch(total)[body_start:]
    source.pop_front(total)
    return ParseResult.make_message(msg)


def _maybe_http(prefix: bytes) -> bool:
    if not prefix:
        return False
    for m in _METHODS + (b"HTTP",):
        n = min(len(prefix), len(m))
        if prefix[:n] == m[:n]:
            return True
    return False


def build_response(status: int = 200, body: bytes = b"",
                   content_type: str = "text/plain",
                   headers: Optional[List[Tuple[str, str]]] = None,
                   keep_alive: bool = True) -> IOBuf:
    reason = STATUS_REASONS.get(status, "Unknown")
    lines = [f"HTTP/1.1 {status} {reason}",
             f"Content-Length: {len(body)}",
             f"Content-Type: {content_type}"]
    if not keep_alive:
        lines.append("Connection: close")
    for k, v in headers or []:
        lines.append(f"{k}: {v}")
    head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin1")
    out = IOBuf(head)
    if body:
        out.append(body)
    return out


def build_request(method: str, path: str, body: bytes = b"",
                  host: str = "", content_type: str =
                  "application/octet-stream",
                  headers: Optional[List[Tuple[str, str]]] = None) -> IOBuf:
    lines = [f"{method} {path} HTTP/1.1",
             f"Host: {host or 'localhost'}",
             f"Content-Length: {len(body)}",
             f"Content-Type: {content_type}"]
    for k, v in headers or []:
        lines.append(f"{k}: {v}")
    head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin1")
    out = IOBuf(head)
    if body:
        out.append(body)
    return out


def _process_request(msg: HttpMessage, sock, server) -> None:
    from ..server.http_dispatch import handle_http_request
    handle_http_request(msg, sock, server)


def _process_response(msg: HttpMessage, sock) -> None:
    from ..client.controller import process_http_response
    process_http_response(msg, sock)


HTTP = Protocol(
    ProtocolType.HTTP, "http", parse,
    process_request=_process_request,
    process_response=_process_response,
)
register_protocol(HTTP)

from ..transport.input_messenger import client_messenger  # noqa: E402

client_messenger().add_handler(HTTP)
