"""Protocol plugin API + registry.

Fresh design following the reference's 3-step protocol recipe
(/root/reference/src/brpc/protocol.h:71-75): implement parse/process
callbacks, pick an id, register. Differences from the reference:

- callbacks are plain Python callables on a dataclass-like object;
- ``parse`` returns a :class:`ParseResult` carrying either a cut message
  or a :class:`ParseError` telling the messenger to wait for more bytes /
  try other protocols / fail the connection;
- messages cut by ``parse`` are arbitrary objects owned by the protocol
  (the framed pb-RPC protocol cuts an ``RpcMessage`` with meta + payload
  IOBuf views — zero-copy all the way to user code).
"""

from __future__ import annotations

import enum
import threading
from typing import Any, Callable, Dict, List, Optional


class ProtocolType(enum.IntEnum):
    """Wire protocol ids (≈ /root/reference/src/brpc/options.proto:38-67).
    Values are this framework's own; names keep the reference vocabulary
    where capabilities overlap."""

    UNKNOWN = 0
    TPU_STD = 1          # framed pb-RPC, the default (≈ baidu_std)
    STREAMING_RPC = 2
    HTTP = 3             # HTTP/1.x (+ restful + JSON bridge)
    H2 = 4               # HTTP/2 + gRPC
    REDIS = 5
    MEMCACHE = 6
    THRIFT = 7
    ESP = 8
    NSHEAD = 9
    MESH = 10            # device-mesh collective transport frames
    ICI_ACK = 11         # device-attachment redemption acks (ici/)


class ParseError(enum.IntEnum):
    """Outcome codes for Protocol.parse (≈ protocol.h ParseError)."""

    OK = 0
    TRY_OTHERS = 1        # bytes don't look like this protocol at all
    NOT_ENOUGH_DATA = 2   # prefix matches; wait for more bytes
    ABSOLUTELY_WRONG = 3  # prefix matches but the frame is broken: fail fd
    TOO_BIG_DATA = 4      # frame exceeds max_body_size: fail fd


class ParseResult:
    """Either a successfully cut message or an error telling the input
    messenger what to do next."""

    __slots__ = ("error", "message")

    def __init__(self, error: ParseError = ParseError.OK,
                 message: Any = None):
        self.error = error
        self.message = message

    @property
    def ok(self) -> bool:
        return self.error == ParseError.OK

    @staticmethod
    def make_message(msg: Any) -> "ParseResult":
        return ParseResult(ParseError.OK, msg)

    @staticmethod
    def not_enough_data() -> "ParseResult":
        return ParseResult(ParseError.NOT_ENOUGH_DATA)

    @staticmethod
    def try_others() -> "ParseResult":
        return ParseResult(ParseError.TRY_OTHERS)

    @staticmethod
    def absolutely_wrong() -> "ParseResult":
        return ParseResult(ParseError.ABSOLUTELY_WRONG)

    @staticmethod
    def too_big(limit: int = 0) -> "ParseResult":
        return ParseResult(ParseError.TOO_BIG_DATA)


# 64 MB default, mirroring the reference (src/brpc/protocol.cpp:44).
MAX_BODY_SIZE = 64 * 1024 * 1024


def max_body_size() -> int:
    """Current frame-size cap — live-tunable via /flags/max_body_size."""
    from ..butil.flags import get_flag
    return get_flag("max_body_size", MAX_BODY_SIZE)


class Protocol:
    """Struct-of-callbacks protocol plugin
    (≈ /root/reference/src/brpc/protocol.h:92-146).

    parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult
        Cut ONE message off ``source`` (mutating it). ``arg`` is the
        server (server side) or None (client side).
    serialize_request(request, controller) -> IOBuf | None
        Turn the user request object into payload bytes. Runs once per
        RPC (not per retry). On failure, set error on controller.
    pack_request(payload: IOBuf, controller, correlation_id) -> IOBuf
        Frame the serialized payload for one attempt (adds header/meta).
    process_request(msg, socket, server) -> None
        Server-side: full service dispatch for one cut message.
    process_response(msg, socket) -> None
        Client-side: rendezvous with the waiting call via correlation id.
    verify(msg) -> bool
        Server-side auth check on first message of a connection.
    """

    __slots__ = ("type", "name", "parse", "serialize_request",
                 "pack_request", "process_request", "process_response",
                 "verify", "support_client", "support_server",
                 "process_inline")

    def __init__(self, type: ProtocolType, name: str,
                 parse: Callable,
                 process_request: Optional[Callable] = None,
                 process_response: Optional[Callable] = None,
                 serialize_request: Optional[Callable] = None,
                 pack_request: Optional[Callable] = None,
                 verify: Optional[Callable] = None,
                 process_inline: bool = False):
        self.type = type
        self.name = name
        self.parse = parse
        self.process_request = process_request
        self.process_response = process_response
        self.serialize_request = serialize_request
        self.pack_request = pack_request
        self.verify = verify
        self.support_client = process_response is not None
        self.support_server = process_request is not None
        # True = the messenger must process messages on the reading task
        # in arrival order (protocols with ordered semantics — streams);
        # processing must then be cheap/non-blocking
        self.process_inline = process_inline


_registry_lock = threading.Lock()
_registry: Dict[ProtocolType, Protocol] = {}


def register_protocol(proto: Protocol) -> None:
    """≈ RegisterProtocol (/root/reference/src/brpc/protocol.h:186).
    Re-registering the same type raises — protocols are process-global."""
    with _registry_lock:
        if proto.type in _registry:
            raise ValueError(f"protocol {proto.type!r} already registered")
        _registry[proto.type] = proto


def get_protocol(ptype: ProtocolType) -> Optional[Protocol]:
    return _registry.get(ptype)


def list_protocols() -> List[Protocol]:
    with _registry_lock:
        return list(_registry.values())
