"""json2pb — JSON ⇄ protobuf conversion for the HTTP bridge.

≈ /root/reference/src/json2pb/ (json_to_pb.cpp / pb_to_json.cpp): HTTP
clients POST JSON at a method whose ``@method(request_type=...)`` is a
protobuf Message class and the bridge converts both directions; the
framed-RPC path keeps carrying binary pb untouched.  Built on the real
``google.protobuf.json_format`` (no hand-rolled schema walker — the
runtime is baked into this image)."""

from __future__ import annotations

from typing import Any, Optional

try:
    from google.protobuf import json_format
    from google.protobuf.message import Message
    _HAVE_PB = True
except ImportError:                      # pragma: no cover
    json_format = None
    Message = ()                          # type: ignore[assignment]
    _HAVE_PB = False


def is_pb_class(cls: Any) -> bool:
    return _HAVE_PB and isinstance(cls, type) and issubclass(cls, Message)


def json_to_pb(data: bytes, message_cls) -> Any:
    """JSON bytes → a protobuf message instance (raises on mismatch)."""
    msg = message_cls()
    json_format.Parse(data.decode("utf-8"), msg)
    return msg


def pb_to_json(msg: Any) -> bytes:
    return json_format.MessageToJson(msg).encode("utf-8")


def maybe_parse_request(raw: bytes, request_type,
                        content_type: str) -> Optional[Any]:
    """HTTP bridge hook: JSON body + pb request type ⇒ converted message;
    None means 'not a json2pb case, use the normal parser'."""
    if not is_pb_class(request_type):
        return None
    ct = (content_type or "").lower()
    if "json" not in ct and not (raw[:1] in (b"{", b"[")):
        return None
    return json_to_pb(raw, request_type)


def maybe_encode_response(response: Any) -> Optional[bytes]:
    """HTTP bridge hook: pb message response ⇒ JSON bytes."""
    if _HAVE_PB and isinstance(response, Message):
        return pb_to_json(response)
    return None
