"""Protocol layer — pluggable struct-of-callbacks wire protocols.

Capability parity with the reference's protocol registry
(/root/reference/src/brpc/protocol.h:77-196): a protocol is a bundle of
callbacks (parse / serialize_request / pack_request / process_request /
process_response / verify), registered by name+id, and the transport's
input messenger tries registered parsers to auto-detect the wire format
on a shared port.
"""

from .base import (
    ParseError,
    ParseResult,
    Protocol,
    ProtocolType,
    get_protocol,
    list_protocols,
    register_protocol,
)

__all__ = [
    "ParseError",
    "ParseResult",
    "Protocol",
    "ProtocolType",
    "get_protocol",
    "list_protocols",
    "register_protocol",
]
