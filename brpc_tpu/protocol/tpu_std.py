"""tpu_std — the default framed pb-RPC protocol.

Capability parity with baidu_std
(/root/reference/src/brpc/policy/baidu_rpc_protocol.cpp:58,101-105):

    [ "TRPC" ][ u32 body_size ][ u32 meta_size ]  -- 12-byte header
    [ meta (RpcMeta TLV) ][ payload ][ attachment ]

where body_size = meta_size + len(payload) + len(attachment). The
attachment rides uncompressed after the (possibly compressed) payload —
the zero-copy side channel for bulk bytes (tensors!) that must not pass
through a serializer.

Server dispatch and client rendezvous live in brpc_tpu.server / .client;
this module owns framing only (the reference's layering: protocol parse
vs ProcessRpcRequest policy glue).
"""

from __future__ import annotations

import struct
from time import monotonic_ns as _monotonic_ns
from typing import Any, Optional

from ..butil.iobuf import IOBuf
from .base import (ParseResult, Protocol, ProtocolType,
                   max_body_size, register_protocol)
from .meta import RpcMeta

MAGIC = b"TRPC"
HEADER_SIZE = 12


class RpcMessage:
    """One cut frame: meta + payload IOBuf (attachment still inside;
    split by the dispatch layer using meta.attachment_size)."""

    __slots__ = ("meta", "payload", "socket_id", "recv_us")

    def __init__(self, meta: RpcMeta, payload: IOBuf, socket_id: int = 0):
        self.meta = meta
        self.payload = payload
        self.socket_id = socket_id
        # arrival anchor for the deadline plane: construction time IS
        # the parse time on every ingest path (messenger cut, native
        # bridge) — queueing between here and dispatch counts against
        # the request's propagated remaining budget
        self.recv_us = _monotonic_ns() // 1000

    def split_attachment(self) -> IOBuf:
        """Cut the attachment tail off the payload; returns it (empty if
        none).  Raises ValueError when the declared size exceeds the
        body — a malformed frame the dispatch layer answers EREQUEST."""
        n = self.meta.attachment_size
        if n > len(self.payload):
            raise ValueError("attachment size exceeds body")
        if n <= 0:
            return IOBuf()
        body_len = len(self.payload) - n
        body = self.payload.cutn(body_len)
        attachment = self.payload
        self.payload = body
        return attachment


def parse(source: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    """≈ ParseRpcMessage (baidu_rpc_protocol.cpp:95)."""
    avail = len(source)
    if avail < HEADER_SIZE:
        got = source.fetch(min(4, avail))
        if MAGIC.startswith(got):
            return ParseResult.not_enough_data()
        return ParseResult.try_others()
    header = source.fetch(HEADER_SIZE)
    if header[:4] != MAGIC:
        return ParseResult.try_others()
    body_size, meta_size = struct.unpack_from("<II", header, 4)
    limit = max_body_size()
    if body_size > limit:
        return ParseResult.too_big(limit)
    if meta_size > body_size:
        return ParseResult.absolutely_wrong()
    if avail < HEADER_SIZE + body_size:
        return ParseResult.not_enough_data()
    source.pop_front(HEADER_SIZE)
    meta_bytes = source.fetch(meta_size)
    source.pop_front(meta_size)
    meta = RpcMeta.decode(meta_bytes)
    if meta is None:
        return ParseResult.absolutely_wrong()
    payload = source.cutn(body_size - meta_size)
    sid = getattr(sock, "id", 0)
    return ParseResult.make_message(RpcMessage(meta, payload, sid))


def pack_frame(meta: RpcMeta, payload: IOBuf,
               attachment: Optional[IOBuf] = None,
               extra_meta: bytes = b"") -> IOBuf:
    """Frame one message. ``attachment`` is appended after the payload and
    its size recorded in the meta (zero-copy: the attachment IOBuf's
    blocks are shared, not copied).  ``extra_meta`` is pre-encoded TLV
    bytes appended verbatim inside the meta region (the shm data plane
    encodes its offer/accept/release/descriptor TLVs once and every
    lane splices them in — meta.decode parses them back into fields)."""
    if attachment is not None and len(attachment) > 0:
        meta.attachment_size = len(attachment)
    meta_bytes = meta.encode()
    if extra_meta:
        meta_bytes += extra_meta
    body_size = len(meta_bytes) + len(payload) + meta.attachment_size
    out = IOBuf(MAGIC + struct.pack("<II", body_size, len(meta_bytes)))
    out.append(meta_bytes)
    out.append_iobuf(payload)
    if attachment is not None and len(attachment) > 0:
        out.append_iobuf(attachment)
    return out


def serialize_payload(obj: Any) -> IOBuf:
    """User object → payload IOBuf. bytes-likes pass through; protobuf-shaped
    objects (SerializeToString) and this framework's light messages
    (serialize()) are supported."""
    if isinstance(obj, IOBuf):
        return obj
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return IOBuf(obj)
    if hasattr(obj, "SerializeToString"):
        return IOBuf(obj.SerializeToString())
    if hasattr(obj, "serialize"):
        return IOBuf(obj.serialize())
    if obj is None:
        return IOBuf()
    raise TypeError(f"cannot serialize {type(obj).__name__} as RPC payload")


def parse_payload(data: bytes, response_type: Any) -> Any:
    """Payload bytes → user object of ``response_type`` (None = raw
    bytes)."""
    if response_type is None or response_type in (bytes, bytearray):
        return data
    if response_type is IOBuf:
        return IOBuf(data)
    if hasattr(response_type, "FromString"):
        return response_type.FromString(data)
    inst = response_type()
    if hasattr(inst, "ParseFromString"):
        inst.ParseFromString(data)
        return inst
    if hasattr(inst, "parse"):
        inst.parse(data)
        return inst
    raise TypeError(f"cannot parse payload into {response_type!r}")


def _process_request(msg: RpcMessage, sock, server) -> None:
    # late import: server layer sits above the protocol layer
    from ..server.rpc_dispatch import process_rpc_request
    process_rpc_request(msg, sock, server)


def _process_response(msg: RpcMessage, sock) -> None:
    from ..client.controller import process_rpc_response
    process_rpc_response(msg, sock)


TPU_STD = Protocol(
    ProtocolType.TPU_STD, "tpu_std", parse,
    process_request=_process_request,
    process_response=_process_response,
)
register_protocol(TPU_STD)

# client-side connections must understand tpu_std responses
from ..transport.input_messenger import client_messenger  # noqa: E402

client_messenger().add_handler(TPU_STD)
