"""HTTP/2 (RFC 7540) connection session — framing + state, both sides.

Capability parity with the reference's H2Context/H2StreamContext
(/root/reference/src/brpc/policy/http2_rpc_protocol.cpp, 1,835 LoC) at
the scope gRPC interop needs: connection preface, SETTINGS exchange,
HEADERS/CONTINUATION with HPACK, DATA with connection+stream flow
control, WINDOW_UPDATE, PING, RST_STREAM, GOAWAY.

Fresh design: one :class:`H2Session` drives both client and server
ends.  ``feed(bytes)`` consumes wire bytes and returns a list of
events; every send_* method appends to an output buffer the caller
drains with ``take_output()`` and writes to its transport — the
session never touches sockets (easy to test byte-for-byte and to ride
either the Python or native transport).
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, List, Optional, Tuple

from .hpack import Decoder as HpackDecoder
from .hpack import Encoder as HpackEncoder
from .hpack import HpackError

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

# frame types
F_DATA = 0x0
F_HEADERS = 0x1
F_PRIORITY = 0x2
F_RST_STREAM = 0x3
F_SETTINGS = 0x4
F_PUSH_PROMISE = 0x5
F_PING = 0x6
F_GOAWAY = 0x7
F_WINDOW_UPDATE = 0x8
F_CONTINUATION = 0x9

# flags
FLAG_END_STREAM = 0x1
FLAG_ACK = 0x1
FLAG_END_HEADERS = 0x4
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20

# settings ids
S_HEADER_TABLE_SIZE = 0x1
S_ENABLE_PUSH = 0x2
S_MAX_CONCURRENT_STREAMS = 0x3
S_INITIAL_WINDOW_SIZE = 0x4
S_MAX_FRAME_SIZE = 0x5
S_MAX_HEADER_LIST_SIZE = 0x6

DEFAULT_WINDOW = 65535
RECV_WINDOW = 4 * 1024 * 1024      # what we advertise

# error codes
E_NO_ERROR = 0x0
E_PROTOCOL = 0x1
E_FLOW_CONTROL = 0x3
E_REFUSED = 0x7


class H2Error(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = code


class _Stream:
    __slots__ = ("id", "send_window", "pending", "end_after_pending",
                 "trailers", "headers_done", "closed_local",
                 "closed_remote")

    def __init__(self, sid: int, send_window: int):
        self.id = sid
        self.send_window = send_window
        self.pending = bytearray()     # data waiting for window
        self.end_after_pending = False
        self.trailers: Optional[List[Tuple[str, str]]] = None
        self.headers_done = False
        self.closed_local = False
        self.closed_remote = False


class H2Session:
    """Events returned by feed():
    ("headers", sid, [(name, value)], end_stream)
    ("data", sid, bytes, end_stream)
    ("rst", sid, error_code)
    ("goaway", last_sid, error_code, debug_bytes)
    ("ping", payload)          # already acked internally
    """

    def __init__(self, is_server: bool):
        self.is_server = is_server
        self._buf = bytearray()
        self._out = bytearray()
        self._hp_enc = HpackEncoder()
        self._hp_dec = HpackDecoder()
        self._streams: Dict[int, _Stream] = {}
        self._next_sid = 2 if is_server else 1
        self._preface_seen = not is_server
        self._preface_sent = False
        self.peer_initial_window = DEFAULT_WINDOW
        self.conn_send_window = DEFAULT_WINDOW
        self.conn_recv_consumed = 0
        self.max_frame_size = 16384
        self._hdr_accum: Optional[Tuple[int, bytearray, int]] = None
        self.goaway_received = False
        self.lock = threading.RLock()   # callers serialize on this

    # -- output ------------------------------------------------------------

    def take_output(self) -> bytes:
        out = bytes(self._out)
        del self._out[:]
        return out

    def _frame(self, ftype: int, flags: int, sid: int,
               payload: bytes = b"") -> None:
        self._out += struct.pack(">I", len(payload))[1:]
        self._out.append(ftype)
        self._out.append(flags)
        self._out += struct.pack(">I", sid & 0x7FFFFFFF)
        self._out += payload

    def start(self) -> None:
        """Queue the preface (client) + initial SETTINGS + window."""
        if self._preface_sent:
            return
        self._preface_sent = True
        if not self.is_server:
            self._out += PREFACE
        settings = struct.pack(">HI", S_INITIAL_WINDOW_SIZE, RECV_WINDOW)
        settings += struct.pack(">HI", S_MAX_CONCURRENT_STREAMS, 1024)
        self._frame(F_SETTINGS, 0, 0, settings)
        # grow the connection receive window
        self._frame(F_WINDOW_UPDATE, 0, 0,
                    struct.pack(">I", RECV_WINDOW - DEFAULT_WINDOW))

    # -- send side ---------------------------------------------------------

    def next_stream_id(self) -> int:
        sid = self._next_sid
        self._next_sid += 2
        return sid

    def _stream(self, sid: int) -> _Stream:
        st = self._streams.get(sid)
        if st is None:
            st = self._streams[sid] = _Stream(sid, self.peer_initial_window)
        return st

    def send_headers(self, sid: int, headers: List[Tuple[str, str]],
                     end_stream: bool = False) -> None:
        st = self._stream(sid)
        if st.pending or (st.end_after_pending and not st.closed_local):
            # DATA is still window-blocked: these headers are trailers
            # and MUST follow it — defer to the pump (frames on a stream
            # are ordered; emitting now would truncate the response)
            st.trailers = list(headers)
            if not end_stream:
                raise H2Error(E_PROTOCOL,
                              "non-trailing HEADERS after pending DATA")
            self._pump_stream(st)
            return
        block = self._hp_enc.encode(headers)
        flags = FLAG_END_HEADERS | (FLAG_END_STREAM if end_stream else 0)
        self._frame(F_HEADERS, flags, sid, block)
        if end_stream:
            st.closed_local = True

    MAX_PENDING = 64 << 20      # per-stream window-blocked buffer cap

    def send_data(self, sid: int, data: bytes,
                  end_stream: bool = False) -> None:
        st = self._stream(sid)
        if len(st.pending) + len(data) > self.MAX_PENDING:
            # a peer sitting on its window must not buffer us to death:
            # reset the stream instead of accumulating unboundedly
            self.send_rst(sid, E_FLOW_CONTROL)
            raise H2Error(E_FLOW_CONTROL,
                          f"stream {sid} window-blocked beyond "
                          f"{self.MAX_PENDING} pending bytes")
        st.pending += data
        st.end_after_pending = st.end_after_pending or end_stream
        self._pump_stream(st)

    def _pump_stream(self, st: _Stream) -> None:
        while st.pending:
            allowed = min(len(st.pending), st.send_window,
                          self.conn_send_window, self.max_frame_size)
            if allowed <= 0:
                return                 # wait for WINDOW_UPDATE
            chunk = bytes(st.pending[:allowed])
            del st.pending[:allowed]
            st.send_window -= allowed
            self.conn_send_window -= allowed
            # END_STREAM rides the last DATA only when no trailers follow
            last = not st.pending and st.end_after_pending \
                and st.trailers is None
            self._frame(F_DATA, FLAG_END_STREAM if last else 0,
                        st.id, chunk)
            if last:
                st.closed_local = True
        if st.trailers is not None:
            block = self._hp_enc.encode(st.trailers)
            st.trailers = None
            self._frame(F_HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM,
                        st.id, block)
            st.closed_local = True
            st.end_after_pending = False
        elif st.end_after_pending and not st.closed_local:
            self._frame(F_DATA, FLAG_END_STREAM, st.id, b"")
            st.closed_local = True

    def send_rst(self, sid: int, code: int = E_NO_ERROR) -> None:
        self._frame(F_RST_STREAM, 0, sid, struct.pack(">I", code))
        self._streams.pop(sid, None)

    def send_goaway(self, code: int = E_NO_ERROR) -> None:
        last = max(self._streams) if self._streams else 0
        self._frame(F_GOAWAY, 0, 0, struct.pack(">II", last, code))

    # -- receive side ------------------------------------------------------

    def feed(self, data: bytes) -> List[tuple]:
        self._buf += data
        events: List[tuple] = []
        if not self._preface_seen:
            if len(self._buf) < len(PREFACE):
                if PREFACE.startswith(bytes(self._buf)):
                    return events
                raise H2Error(E_PROTOCOL, "bad preface")
            if bytes(self._buf[:len(PREFACE)]) != PREFACE:
                raise H2Error(E_PROTOCOL, "bad preface")
            del self._buf[:len(PREFACE)]
            self._preface_seen = True
            self.start()
        while len(self._buf) >= 9:
            length = int.from_bytes(self._buf[0:3], "big")
            ftype = self._buf[3]
            flags = self._buf[4]
            sid = int.from_bytes(self._buf[5:9], "big") & 0x7FFFFFFF
            if length > (1 << 24) - 1 or length > 16 * 1024 * 1024:
                raise H2Error(E_PROTOCOL, "frame too large")
            if len(self._buf) < 9 + length:
                break
            payload = bytes(self._buf[9:9 + length])
            del self._buf[:9 + length]
            self._on_frame(ftype, flags, sid, payload, events)
        return events

    def _on_frame(self, ftype: int, flags: int, sid: int,
                  payload: bytes, events: List[tuple]) -> None:
        if self._hdr_accum is not None and ftype != F_CONTINUATION:
            raise H2Error(E_PROTOCOL, "expected CONTINUATION")
        if ftype == F_SETTINGS:
            self._on_settings(flags, payload)
        elif ftype == F_HEADERS:
            body = payload
            if flags & FLAG_PADDED:
                pad = body[0]
                body = body[1:len(body) - pad]
            if flags & FLAG_PRIORITY:
                body = body[5:]
            if flags & FLAG_END_HEADERS:
                self._emit_headers(sid, body, flags, events)
            else:
                self._hdr_accum = (sid, bytearray(body), flags)
        elif ftype == F_CONTINUATION:
            if self._hdr_accum is None or self._hdr_accum[0] != sid:
                raise H2Error(E_PROTOCOL, "stray CONTINUATION")
            self._hdr_accum[1].extend(payload)
            if flags & FLAG_END_HEADERS:
                _sid, block, hflags = self._hdr_accum
                self._hdr_accum = None
                self._emit_headers(_sid, bytes(block), hflags, events)
        elif ftype == F_DATA:
            body = payload
            if flags & FLAG_PADDED:
                pad = body[0]
                body = body[1:len(body) - pad]
            end = bool(flags & FLAG_END_STREAM)
            st = self._stream(sid)
            if end:
                st.closed_remote = True
            # replenish both windows right away (we buffer upstream)
            if len(payload):
                self._frame(F_WINDOW_UPDATE, 0, 0,
                            struct.pack(">I", len(payload)))
                if not end:
                    self._frame(F_WINDOW_UPDATE, 0, sid,
                                struct.pack(">I", len(payload)))
            events.append(("data", sid, body, end))
        elif ftype == F_WINDOW_UPDATE:
            (inc,) = struct.unpack(">I", payload[:4])
            inc &= 0x7FFFFFFF
            if sid == 0:
                self.conn_send_window += inc
                for st in list(self._streams.values()):
                    self._pump_stream(st)
            else:
                st = self._stream(sid)
                st.send_window += inc
                self._pump_stream(st)
        elif ftype == F_PING:
            if not (flags & FLAG_ACK):
                self._frame(F_PING, FLAG_ACK, 0, payload)
            events.append(("ping", payload))
        elif ftype == F_RST_STREAM:
            (code,) = struct.unpack(">I", payload[:4])
            self._streams.pop(sid, None)
            events.append(("rst", sid, code))
        elif ftype == F_GOAWAY:
            last, code = struct.unpack(">II", payload[:8])
            self.goaway_received = True
            events.append(("goaway", last, code, payload[8:]))
        # PRIORITY / PUSH_PROMISE / unknown: ignored

    def _emit_headers(self, sid: int, block: bytes, flags: int,
                      events: List[tuple]) -> None:
        try:
            headers = self._hp_dec.decode(block)
        except HpackError as e:
            raise H2Error(E_PROTOCOL, f"hpack: {e}")
        end = bool(flags & FLAG_END_STREAM)
        st = self._stream(sid)
        st.headers_done = True
        if end:
            st.closed_remote = True
        events.append(("headers", sid, headers, end))

    def _on_settings(self, flags: int, payload: bytes) -> None:
        if flags & FLAG_ACK:
            return
        for off in range(0, len(payload) - 5, 6):
            ident, value = struct.unpack_from(">HI", payload, off)
            if ident == S_INITIAL_WINDOW_SIZE:
                delta = value - self.peer_initial_window
                self.peer_initial_window = value
                for st in list(self._streams.values()):
                    st.send_window += delta
                    if delta > 0:
                        # RFC 7540 §6.9.2: the extra window is granted by
                        # the SETTINGS itself; no WINDOW_UPDATE will come
                        self._pump_stream(st)
            elif ident == S_MAX_FRAME_SIZE:
                self.max_frame_size = max(16384, min(value, 1 << 24))
            elif ident == S_HEADER_TABLE_SIZE:
                # the peer's DECODER table cap: our encoder must not
                # index beyond it (it may shrink, e.g. to 0)
                self._hp_enc.set_max_table_size(value)
        self._frame(F_SETTINGS, FLAG_ACK, 0)

    def close_stream(self, sid: int) -> None:
        """Forget a stream once its output is fully framed; a stream
        still holding window-blocked DATA/trailers stays registered so
        WINDOW_UPDATE can finish it."""
        st = self._streams.get(sid)
        if st is None:
            return
        if not st.pending and st.trailers is None:
            del self._streams[sid]
