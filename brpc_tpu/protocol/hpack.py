"""HPACK (RFC 7541) — header compression for HTTP/2.

Capability parity with /root/reference/src/brpc/details/hpack.cpp (881
LoC): integer/string primitives, indexed + literal representations,
dynamic table with eviction, Huffman coding both ways.  Fresh Python
design: the decoder drives a flat (bit_len, code)->symbol map instead
of a tree; the encoder Huffman-codes a string only when strictly
shorter, like the reference.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .hpack_tables import HUFFMAN_CODES, STATIC_TABLE

DEFAULT_TABLE_SIZE = 4096
_EOS = 256

# (bit_len, code) -> symbol, for the linear decoder
_DECODE: Dict[Tuple[int, int], int] = {
    (blen, code): sym for sym, (code, blen) in enumerate(HUFFMAN_CODES)
}
_MIN_BITS = min(b for _, b in HUFFMAN_CODES)

# static table index helpers (1-based per the RFC)
_STATIC_BY_PAIR = {(n, v): i + 1 for i, (n, v) in enumerate(STATIC_TABLE)}
_STATIC_BY_NAME: Dict[str, int] = {}
for i, (n, _v) in enumerate(STATIC_TABLE):
    _STATIC_BY_NAME.setdefault(n, i + 1)


class HpackError(Exception):
    pass


# -- primitives ------------------------------------------------------------

def encode_int(value: int, prefix_bits: int, flags: int = 0) -> bytes:
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([flags | value])
    out = bytearray([flags | limit])
    value -= limit
    while value >= 128:
        out.append(0x80 | (value & 0x7F))
        value >>= 7
    out.append(value)
    return bytes(out)


def decode_int(data: bytes, pos: int, prefix_bits: int) -> Tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    if pos >= len(data):
        raise HpackError("truncated integer")
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise HpackError("truncated varint")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if not (b & 0x80):
            return value, pos
        if shift > 62:
            raise HpackError("varint overflow")


def huffman_encode(data: bytes) -> bytes:
    acc = 0
    nbits = 0
    out = bytearray()
    for byte in data:
        code, blen = HUFFMAN_CODES[byte]
        acc = (acc << blen) | code
        nbits += blen
        while nbits >= 8:
            nbits -= 8
            out.append((acc >> nbits) & 0xFF)
    if nbits:
        # pad with the EOS prefix (all ones)
        pad = 8 - nbits
        out.append(((acc << pad) | ((1 << pad) - 1)) & 0xFF)
    return bytes(out)


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    acc = 0
    nbits = 0
    for byte in data:
        acc = (acc << 8) | byte
        nbits += 8
        while nbits >= _MIN_BITS:
            sym = None
            # try the shortest code first; codes are ≤ 30 bits
            for blen in range(_MIN_BITS, min(nbits, 30) + 1):
                code = (acc >> (nbits - blen)) & ((1 << blen) - 1)
                sym = _DECODE.get((blen, code))
                if sym is not None:
                    if sym == _EOS:
                        raise HpackError("EOS in huffman stream")
                    out.append(sym)
                    nbits -= blen
                    acc &= (1 << nbits) - 1
                    break
            if sym is None:
                break                  # need more bits
    # remaining bits must be an all-ones EOS prefix (≤ 7 bits)
    if nbits > 7 or (nbits and acc != (1 << nbits) - 1):
        raise HpackError("bad huffman padding")
    return bytes(out)


def _encode_string(s: bytes, huffman: bool = True) -> bytes:
    if huffman:
        h = huffman_encode(s)
        if len(h) < len(s):
            return encode_int(len(h), 7, 0x80) + h
    return encode_int(len(s), 7, 0x00) + s


def _decode_string(data: bytes, pos: int) -> Tuple[bytes, int]:
    if pos >= len(data):
        raise HpackError("truncated string")
    huff = bool(data[pos] & 0x80)
    length, pos = decode_int(data, pos, 7)
    raw = data[pos:pos + length]
    if len(raw) != length:
        raise HpackError("truncated string body")
    pos += length
    return (huffman_decode(raw) if huff else raw), pos


# -- dynamic table ---------------------------------------------------------

class _DynTable:
    def __init__(self, max_size: int = DEFAULT_TABLE_SIZE):
        self.entries: List[Tuple[str, str]] = []   # newest first
        self.size = 0
        self.max_size = max_size

    @staticmethod
    def _entry_size(name: str, value: str) -> int:
        return len(name) + len(value) + 32          # RFC 7541 §4.1

    def add(self, name: str, value: str) -> None:
        need = self._entry_size(name, value)
        while self.entries and self.size + need > self.max_size:
            en, ev = self.entries.pop()
            self.size -= self._entry_size(en, ev)
        if need <= self.max_size:
            self.entries.insert(0, (name, value))
            self.size += need

    def resize(self, max_size: int) -> None:
        self.max_size = max_size
        while self.entries and self.size > self.max_size:
            en, ev = self.entries.pop()
            self.size -= self._entry_size(en, ev)

    def get(self, index: int) -> Tuple[str, str]:
        """index is 1-based across static+dynamic (RFC §2.3.3)."""
        if 1 <= index <= len(STATIC_TABLE):
            return STATIC_TABLE[index - 1]
        di = index - len(STATIC_TABLE) - 1
        if 0 <= di < len(self.entries):
            return self.entries[di]
        raise HpackError(f"index {index} out of range")

    def find(self, name: str, value: str) -> Tuple[int, bool]:
        """(index, exact) — 0 when absent."""
        exact = _STATIC_BY_PAIR.get((name, value))
        if exact:
            return exact, True
        for i, (en, ev) in enumerate(self.entries):
            if en == name and ev == value:
                return len(STATIC_TABLE) + 1 + i, True
        ni = _STATIC_BY_NAME.get(name)
        if ni:
            return ni, False
        for i, (en, _ev) in enumerate(self.entries):
            if en == name:
                return len(STATIC_TABLE) + 1 + i, False
        return 0, False


# -- encoder / decoder -----------------------------------------------------

class Encoder:
    def __init__(self, max_table_size: int = DEFAULT_TABLE_SIZE):
        self._table = _DynTable(max_table_size)
        self._pending_resize: Optional[int] = None

    def set_max_table_size(self, size: int) -> None:
        """Peer-imposed decoder cap (SETTINGS_HEADER_TABLE_SIZE): resize
        our table and signal the change in the next header block
        (RFC 7541 §4.2 dynamic table size update)."""
        size = min(size, DEFAULT_TABLE_SIZE)
        if size != self._table.max_size:
            self._table.resize(size)
            self._pending_resize = size

    def encode(self, headers: List[Tuple[str, str]]) -> bytes:
        out = bytearray()
        if self._pending_resize is not None:
            out += encode_int(self._pending_resize, 5, 0x20)
            self._pending_resize = None
        for name, value in headers:
            name = name.lower()
            idx, exact = self._table.find(name, value)
            if exact:
                out += encode_int(idx, 7, 0x80)          # indexed
                continue
            sensitive = name in ("authorization", "cookie", "set-cookie")
            if sensitive:
                # literal, never indexed
                out += encode_int(idx if idx else 0, 4, 0x10)
            else:
                # literal with incremental indexing
                out += encode_int(idx if idx else 0, 6, 0x40)
                self._table.add(name, value)
            if not idx:
                out += _encode_string(name.encode("latin1"))
            out += _encode_string(value.encode("latin1"))
        return bytes(out)


class Decoder:
    def __init__(self, max_table_size: int = DEFAULT_TABLE_SIZE):
        self._table = _DynTable(max_table_size)

    def decode(self, data: bytes) -> List[Tuple[str, str]]:
        headers: List[Tuple[str, str]] = []
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:                                  # indexed
                idx, pos = decode_int(data, pos, 7)
                if idx == 0:
                    raise HpackError("indexed 0")
                headers.append(self._table.get(idx))
            elif b & 0x40:                                # literal + index
                idx, pos = decode_int(data, pos, 6)
                name, value, pos = self._literal(data, pos, idx)
                self._table.add(name, value)
                headers.append((name, value))
            elif b & 0x20:                                # table resize
                size, pos = decode_int(data, pos, 5)
                self._table.resize(size)
            else:                                         # literal no index
                idx, pos = decode_int(data, pos, 4)
                name, value, pos = self._literal(data, pos, idx)
                headers.append((name, value))
        return headers

    def _literal(self, data: bytes, pos: int, idx: int):
        if idx:
            name = self._table.get(idx)[0]
        else:
            raw, pos = _decode_string(data, pos)
            name = raw.decode("latin1")
        rawv, pos = _decode_string(data, pos)
        return name, rawv.decode("latin1"), pos
