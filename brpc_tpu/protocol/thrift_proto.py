"""Thrift framed transport + binary-protocol message layer.

Capability parity with /root/reference/src/brpc/policy/thrift_protocol.cpp
(+ thrift_message.h): CALL/REPLY/EXCEPTION envelopes over the framed
transport, seqid matching, serving on the SHARED port next to every
other protocol.  Struct payloads stay opaque bytes — apps bring their
own generated codecs (the reference links real thrift for the same
reason); :class:`TBinary` covers the primitive read/writes tests and
simple handlers need.

Wire: [u32 frame_len][0x8001 version | message_type][name][seqid][body]
"""

from __future__ import annotations

import struct
from typing import Any, Optional, Tuple

from ..butil.iobuf import IOBuf
from ..butil.logging_util import LOG
from .base import (ParseResult, Protocol, ProtocolType, max_body_size,
                   register_protocol)

VERSION_1 = 0x80010000
M_CALL = 1
M_REPLY = 2
M_EXCEPTION = 3
M_ONEWAY = 4

# TApplicationException codes
EX_UNKNOWN_METHOD = 1
EX_INTERNAL_ERROR = 6


class TBinary:
    """Minimal TBinaryProtocol writer/reader for primitives + the
    TApplicationException struct."""

    @staticmethod
    def write_string(b: bytes) -> bytes:
        return struct.pack(">i", len(b)) + b

    @staticmethod
    def read_string(data: bytes, off: int) -> Tuple[bytes, int]:
        (n,) = struct.unpack_from(">i", data, off)
        off += 4
        return data[off:off + n], off + n

    @staticmethod
    def write_field(ftype: int, fid: int, payload: bytes) -> bytes:
        return struct.pack(">bh", ftype, fid) + payload

    STOP = b"\x00"

    @staticmethod
    def app_exception(code: int, message: str) -> bytes:
        """TApplicationException struct: 1:string message, 2:i32 type."""
        msg = message.encode()
        return (TBinary.write_field(11, 1, TBinary.write_string(msg))
                + TBinary.write_field(8, 2, struct.pack(">i", code))
                + TBinary.STOP)

    @staticmethod
    def read_app_exception(data: bytes) -> Tuple[int, str]:
        off, code, msg = 0, 0, ""
        while off < len(data):
            ftype = data[off]
            if ftype == 0:
                break
            (fid,) = struct.unpack_from(">h", data, off + 1)
            off += 3
            if ftype == 11:
                raw, off = TBinary.read_string(data, off)
                if fid == 1:
                    msg = raw.decode("utf-8", "replace")
            elif ftype == 8:
                (v,) = struct.unpack_from(">i", data, off)
                off += 4
                if fid == 2:
                    code = v
            else:
                break
        return code, msg


def pack_message(mtype: int, name: str, seqid: int, body: bytes) -> bytes:
    inner = (struct.pack(">I", VERSION_1 | mtype)
             + TBinary.write_string(name.encode())
             + struct.pack(">i", seqid) + body)
    return struct.pack(">I", len(inner)) + inner


def unpack_message(frame: bytes) -> Tuple[int, str, int, bytes]:
    (verty,) = struct.unpack_from(">I", frame, 0)
    if verty & 0xFFFF0000 != VERSION_1:
        raise ValueError("bad thrift version")
    mtype = verty & 0xFF
    name, off = TBinary.read_string(frame, 4)
    (seqid,) = struct.unpack_from(">i", frame, off)
    return mtype, name.decode("utf-8", "replace"), seqid, frame[off + 4:]


class ThriftMessage:
    __slots__ = ("mtype", "method", "seqid", "body")

    def __init__(self, mtype: int, method: str, seqid: int, body: bytes):
        self.mtype = mtype
        self.method = method
        self.seqid = seqid
        self.body = body


def parse(source: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    avail = len(source)
    if avail < 8:
        head = source.fetch(min(8, avail))
        # prefix check: [len>0 with high byte 0][0x80 0x01 ...]
        if len(head) >= 1 and head[0] != 0:
            return ParseResult.try_others()
        if len(head) >= 5 and head[4] != 0x80:
            return ParseResult.try_others()
        if len(head) >= 6 and head[5] != 0x01:
            return ParseResult.try_others()
        return ParseResult.not_enough_data()
    head = source.fetch(8)
    (flen,) = struct.unpack_from(">I", head, 0)
    if head[4] != 0x80 or head[5] != 0x01:
        return ParseResult.try_others()
    if flen > max_body_size():
        return ParseResult.too_big()
    if avail < 4 + flen:
        return ParseResult.not_enough_data()
    source.pop_front(4)
    frame = source.cutn(flen).to_bytes()
    try:
        mtype, method, seqid, body = unpack_message(frame)
    except (ValueError, struct.error):
        return ParseResult.absolutely_wrong()
    return ParseResult.make_message(ThriftMessage(mtype, method, seqid,
                                                  body))


def _process_request(msg: ThriftMessage, sock, server) -> None:
    svc = server.services.get("thrift")
    if svc is None or msg.mtype not in (M_CALL, M_ONEWAY):
        sock.write(IOBuf(pack_message(
            M_EXCEPTION, msg.method, msg.seqid,
            TBinary.app_exception(EX_UNKNOWN_METHOD,
                                  "no thrift service registered"))))
        return
    try:
        reply = svc.handle(msg.method, msg.body)
    except KeyError:
        if msg.mtype != M_ONEWAY:
            sock.write(IOBuf(pack_message(
                M_EXCEPTION, msg.method, msg.seqid,
                TBinary.app_exception(EX_UNKNOWN_METHOD,
                                      f"unknown method {msg.method}"))))
        return
    except Exception as e:      # noqa: BLE001 — must answer
        LOG.exception("thrift method %s raised", msg.method)
        if msg.mtype != M_ONEWAY:
            sock.write(IOBuf(pack_message(
                M_EXCEPTION, msg.method, msg.seqid,
                TBinary.app_exception(EX_INTERNAL_ERROR,
                                      f"{type(e).__name__}: {e}"))))
        return
    if msg.mtype != M_ONEWAY:
        sock.write(IOBuf(pack_message(M_REPLY, msg.method, msg.seqid,
                                      reply or TBinary.STOP)))


THRIFT = Protocol(
    ProtocolType.THRIFT, "thrift", parse,
    process_request=_process_request,
)
register_protocol(THRIFT)


class ThriftClient:
    """Framed-binary thrift client: call(method, body_bytes) ->
    reply body bytes; raises ThriftApplicationError on EXCEPTION."""

    def __init__(self, addr, timeout_s: float = 2.0):
        import socket as _socket

        from ..butil.endpoint import EndPoint, parse_endpoint
        self._remote = addr if isinstance(addr, EndPoint) \
            else parse_endpoint(str(addr))
        self._timeout_s = timeout_s
        self._sock = None
        self._seq = 0
        import threading
        self._lock = threading.Lock()

    def _ensure(self):
        if self._sock is None:
            import socket as _socket
            s = _socket.create_connection(self._remote.to_sockaddr(),
                                          timeout=self._timeout_s)
            s.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            self._sock = s

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def _read_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("thrift server closed the connection")
            out += chunk
        return out

    def call(self, method: str, body: bytes = b"\x00",
             oneway: bool = False) -> Optional[bytes]:
        with self._lock:
            self._ensure()
            self._seq += 1
            seq = self._seq
            mtype = M_ONEWAY if oneway else M_CALL
            self._sock.sendall(pack_message(mtype, method, seq, body))
            if oneway:
                return None
            (flen,) = struct.unpack(">I", self._read_exact(4))
            frame = self._read_exact(flen)
        mtype, name, seqid, rbody = unpack_message(frame)
        if seqid != seq:
            raise ConnectionError(f"seqid mismatch {seqid} != {seq}")
        if mtype == M_EXCEPTION:
            code, msg = TBinary.read_app_exception(rbody)
            raise ThriftApplicationError(code, msg)
        return rbody


class ThriftApplicationError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code
        self.message = message
