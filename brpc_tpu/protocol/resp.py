"""RESP (REdis Serialization Protocol) — server protocol + codec.

Capability parity with the reference's redis support
(/root/reference/src/brpc/redis.h, policy/redis_protocol.cpp): the
SHARED serving port speaks RESP when the server registered a redis
service — redis-cli can talk to an RPC server directly.  The service is
any object with ``on_command(args: list[bytes])`` returning a reply:

    bytes / bytearray  -> bulk string
    str                -> simple string (+OK style)
    int                -> :integer
    None               -> nil bulk
    RedisError("msg")  -> -ERR style error
    list/tuple         -> array (recursively encoded)

Register it as ``server.add_service(obj, name="redis")`` — objects with
``on_command`` are exempt from RPC-method extraction.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from ..butil.iobuf import IOBuf
from ..butil.logging_util import LOG
from .base import (ParseError, ParseResult, Protocol, ProtocolType,
                   max_body_size, register_protocol)


class RedisError(Exception):
    """Reply as a RESP error without killing the connection."""


# -- codec ------------------------------------------------------------------

def encode_reply(obj: Any) -> bytes:
    if isinstance(obj, RedisError):
        msg = str(obj).replace("\r", " ").replace("\n", " ")
        if not msg.upper().startswith(("ERR", "WRONGTYPE", "MOVED")):
            msg = "ERR " + msg
        return b"-" + msg.encode() + b"\r\n"
    if isinstance(obj, bool):
        return b":1\r\n" if obj else b":0\r\n"
    if isinstance(obj, int):
        return b":%d\r\n" % obj
    if isinstance(obj, str):
        return b"+" + obj.encode() + b"\r\n"
    if isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        return b"$%d\r\n" % len(b) + b + b"\r\n"
    if obj is None:
        return b"$-1\r\n"
    if isinstance(obj, (list, tuple)):
        out = b"*%d\r\n" % len(obj)
        return out + b"".join(encode_reply(x) for x in obj)
    raise TypeError(f"cannot encode {type(obj).__name__} as RESP")


def decode_one(data: bytes, off: int = 0) -> Tuple[Optional[Any], int]:
    """Decode one RESP value.  Returns (value, new_offset);
    (None, off) with new_offset == off means incomplete.  Errors decode
    as RedisError instances, nil as the _NIL sentinel."""
    if off >= len(data):
        return None, off
    end = data.find(b"\r\n", off)
    if end < 0:
        return None, off
    t = data[off:off + 1]
    line = data[off + 1:end]
    nxt = end + 2
    if t == b"+":
        return line.decode("utf-8", "replace"), nxt
    if t == b"-":
        return RedisError(line.decode("utf-8", "replace")), nxt
    if t == b":":
        return int(line), nxt
    if t == b"$":
        n = int(line)
        if n < 0:
            return _NIL, nxt
        if len(data) < nxt + n + 2:
            return None, off
        return data[nxt:nxt + n], nxt + n + 2
    if t == b"*":
        n = int(line)
        if n < 0:
            return _NIL, nxt
        items = []
        pos = nxt
        for _ in range(n):
            v, pos2 = decode_one(data, pos)
            if pos2 == pos and v is None:
                return None, off
            items.append(None if v is _NIL else v)
            pos = pos2
        return items, pos
    raise ValueError(f"bad RESP type byte {t!r}")


class _Nil:
    def __repr__(self):
        return "<redis nil>"


_NIL = _Nil()
NIL = _NIL


def encode_command(*args) -> bytes:
    """Client side: command as a RESP array of bulk strings."""
    out = b"*%d\r\n" % len(args)
    for a in args:
        b = a if isinstance(a, bytes) else str(a).encode()
        out += b"$%d\r\n" % len(b) + b + b"\r\n"
    return out


# -- server protocol on the shared port -------------------------------------

class RespCommand:
    __slots__ = ("args",)

    def __init__(self, args: List[bytes]):
        self.args = args


def parse(source: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    avail = len(source)
    first = source.fetch(1)
    if first != b"*":
        return ParseResult.try_others()
    if arg is None or "redis" not in getattr(arg, "services", {}):
        return ParseResult.try_others()   # no redis service registered
    data = source.to_bytes()
    try:
        val, pos = decode_one(data, 0)
    except (ValueError, UnicodeDecodeError):
        return ParseResult.absolutely_wrong()
    if pos == 0 and val is None:
        if avail > max_body_size():
            return ParseResult.too_big()
        return ParseResult.not_enough_data()
    source.pop_front(pos)
    if not isinstance(val, list) or not all(
            isinstance(x, (bytes, bytearray)) for x in val):
        return ParseResult.absolutely_wrong()
    return ParseResult.make_message(RespCommand([bytes(x) for x in val]))


def _process_request(msg: RespCommand, sock, server) -> None:
    svc = server.services.get("redis")
    if svc is None:
        sock.write(IOBuf(encode_reply(RedisError("ERR no redis service"))))
        return
    try:
        reply = svc.on_command(msg.args)
    except RedisError as e:
        reply = e
    except Exception as e:       # noqa: BLE001 — server must answer
        LOG.exception("redis command %r raised", msg.args[:1])
        reply = RedisError(f"ERR internal: {type(e).__name__}")
    try:
        sock.write(IOBuf(encode_reply(reply)))
    except TypeError:
        sock.write(IOBuf(encode_reply(
            RedisError("ERR unencodable reply from service"))))


RESP = Protocol(
    ProtocolType.REDIS, "redis", parse,
    process_request=_process_request,
    process_inline=True,        # redis pipelining is order-sensitive
)
register_protocol(RESP)
