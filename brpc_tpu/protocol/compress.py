"""Compression registry (≈ /root/reference/src/brpc/compress.h and
policy/gzip_compress.cpp): CompressType → {compress, decompress} handlers,
applied to the RPC payload (never the meta). Snappy is registered only if
the optional python-snappy is importable (the image may not ship it)."""

from __future__ import annotations

import gzip as _gzip
import zlib as _zlib
from typing import Callable, Dict, Optional, Tuple

from .meta import CompressType

_handlers: Dict[int, Tuple[Callable[[bytes], bytes],
                           Callable[[bytes], bytes]]] = {}


def register_compress(ctype: int, compress: Callable[[bytes], bytes],
                      decompress: Callable[[bytes], bytes]) -> None:
    _handlers[ctype] = (compress, decompress)


def compress(data: bytes, ctype: int) -> Optional[bytes]:
    if ctype == CompressType.NONE:
        return data
    h = _handlers.get(ctype)
    return h[0](data) if h else None


def decompress(data: bytes, ctype: int) -> Optional[bytes]:
    if ctype == CompressType.NONE:
        return data
    h = _handlers.get(ctype)
    return h[1](data) if h else None


def supported(ctype: int) -> bool:
    return ctype == CompressType.NONE or ctype in _handlers


register_compress(CompressType.GZIP,
                  lambda d: _gzip.compress(d, compresslevel=6),
                  _gzip.decompress)
register_compress(CompressType.ZLIB, _zlib.compress, _zlib.decompress)

try:                                    # optional, not baked in the image
    import snappy as _snappy            # type: ignore

    register_compress(CompressType.SNAPPY, _snappy.compress,
                      _snappy.decompress)
except ImportError:
    pass
