"""Stream frame protocol — parse + dispatch to Stream objects.

≈ /root/reference/src/brpc/policy/streaming_rpc_protocol.cpp:42-148:
frames ride the same connection as the RPC that established the stream;
dispatch is by destination stream id, symmetric on both sides.
"""

from __future__ import annotations

import struct

from ..butil.iobuf import IOBuf
from .base import (ParseResult, Protocol, ProtocolType, max_body_size,
                   register_protocol)

MAGIC = b"TSTR"
HEADER = 17            # 4 magic + 1 flags + 8 dest id + 4 len

F_DATA = 0
F_FEEDBACK = 1
F_CLOSE = 2            # graceful FIN
F_RST = 3              # abortive


def parse(source: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    avail = len(source)
    if avail < HEADER:
        got = source.fetch(min(4, avail))
        if MAGIC.startswith(got):
            return ParseResult.not_enough_data()
        return ParseResult.try_others()
    head = source.fetch(HEADER)
    if head[:4] != MAGIC:
        return ParseResult.try_others()
    flags, dest, ln = struct.unpack_from("<BQI", head, 4)
    if ln > max_body_size():
        return ParseResult.too_big()
    if avail < HEADER + ln:
        return ParseResult.not_enough_data()
    source.pop_front(HEADER)
    if flags == F_DATA and ln >= 8192:
        # zero-copy: large payloads share the portal's blocks (the
        # reference hands handlers butil::IOBuf* for the same reason);
        # small messages materialize to bytes for handler ergonomics
        payload = source.cutn(ln)
    else:
        payload = source.fetch(ln)
        source.pop_front(ln)
    return ParseResult.make_message((flags, dest, payload))


def _dispatch(msg, sock) -> None:
    from ..streaming import find_stream

    flags, dest, payload = msg
    stream = find_stream(dest)
    if stream is None:
        return                      # stream already closed; drop
    # A stream is bound to exactly one connection; frames for it arriving
    # on any OTHER socket are forged/misrouted (a peer guessing ids) and
    # must be dropped — the reference gets this for free because its
    # StreamIds are versioned SocketIds (src/brpc/stream.cpp).
    if stream.socket_id and sock is not None \
            and getattr(sock, "id", stream.socket_id) != stream.socket_id:
        return
    stream.on_frame(flags, payload)


def _process_request(msg, sock, server) -> None:
    _dispatch(msg, sock)


def _process_response(msg, sock) -> None:
    _dispatch(msg, sock)


STREAMING = Protocol(
    ProtocolType.STREAMING_RPC, "streaming_rpc", parse,
    process_request=_process_request,
    process_response=_process_response,
    # frames are ordered within a stream: dispatch on the reading task
    # (cheap — a push into the stream's ExecutionQueue)
    process_inline=True,
)
register_protocol(STREAMING)

from ..transport.input_messenger import client_messenger  # noqa: E402

client_messenger().add_handler(STREAMING)
