"""HTTP/2 server protocol + gRPC semantics on the shared port.

Capability parity with /root/reference/src/brpc/policy/http2_rpc_protocol.cpp
+ src/brpc/grpc.*: the same port that speaks tpu_std/HTTP/1/streaming
also accepts h2 connections (detected by the client preface).  Requests
with content-type ``application/grpc`` get full gRPC unary semantics
(5-byte message framing, ``/package.Service/Method`` routing into the
regular service registry, grpc-status/grpc-message trailers,
grpc-timeout); other h2 requests are served the builtin portal pages —
the JSON/RPC bridge stays on HTTP/1.

The oracle for this implementation is the real ``grpcio`` package
(tests/test_grpc_interop.py): a grpcio client calls this server and a
grpcio server answers this framework's h2 client.
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, List, Optional, Tuple

from ..butil.iobuf import IOBuf
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..butil.time_utils import monotonic_us
from .base import (ParseResult, Protocol, ProtocolType, max_body_size,
                   register_protocol)
from .h2_session import PREFACE, E_PROTOCOL, H2Error, H2Session

GRPC_CT = "application/grpc"

# Errno -> grpc-status (status.proto codes); default UNKNOWN(2)
_ERRNO_TO_GRPC = {
    0: 0,
    int(Errno.ENOSERVICE): 12,      # UNIMPLEMENTED
    int(Errno.ENOMETHOD): 12,
    int(Errno.EREQUEST): 3,         # INVALID_ARGUMENT
    int(Errno.ERPCAUTH): 16,        # UNAUTHENTICATED
    int(Errno.ELIMIT): 8,           # RESOURCE_EXHAUSTED
    int(Errno.EOVERCROWDED): 8,
    int(Errno.ERPCTIMEDOUT): 4,     # DEADLINE_EXCEEDED
    int(Errno.EINTERNAL): 13,       # INTERNAL
}


def grpc_status_of(errno_code: int) -> int:
    return _ERRNO_TO_GRPC.get(int(errno_code), 2)


_GRPC_TO_ERRNO = {
    0: 0,
    3: int(Errno.EREQUEST),
    4: int(Errno.ERPCTIMEDOUT),
    8: int(Errno.ELIMIT),
    12: int(Errno.ENOMETHOD),
    13: int(Errno.EINTERNAL),
    14: int(Errno.EFAILEDSOCKET),
    16: int(Errno.ERPCAUTH),
}


def errno_of_grpc_status(status: int) -> int:
    return _GRPC_TO_ERRNO.get(int(status), int(Errno.EINTERNAL))


def pack_grpc_message(payload: bytes) -> bytes:
    return b"\x00" + struct.pack(">I", len(payload)) + payload


def unpack_grpc_messages(buf: bytearray) -> List[bytes]:
    """Cut complete length-prefixed messages off ``buf`` (mutates)."""
    out = []
    while len(buf) >= 5:
        compressed = buf[0]
        (ln,) = struct.unpack_from(">I", buf, 1)
        if len(buf) < 5 + ln:
            break
        if compressed:
            raise H2Error(E_PROTOCOL, "compressed grpc message "
                                      "(no grpc-encoding negotiated)")
        out.append(bytes(buf[5:5 + ln]))
        del buf[:5 + ln]
    return out


class H2Request:
    __slots__ = ("stream_id", "headers", "body", "conn")

    def __init__(self, stream_id: int, headers: List[Tuple[str, str]],
                 body: bytes, conn: "H2ServerConn"):
        self.stream_id = stream_id
        self.headers = headers
        self.body = body
        self.conn = conn

    def header(self, name: str) -> str:
        for n, v in self.headers:
            if n == name:
                return v
        return ""


class H2ServerConn:
    """Per-connection server state: the session + request assembly."""

    def __init__(self, sock):
        self.session = H2Session(is_server=True)
        self.sock_id = sock.id
        self._assembling: Dict[int, dict] = {}
        self.ready: List[H2Request] = []
        self.lock = threading.Lock()

    def feed(self, data: bytes) -> None:
        with self.lock:
            events = self.session.feed(data)
            for ev in events:
                kind = ev[0]
                if kind == "headers":
                    _, sid, headers, end = ev
                    st = self._assembling.setdefault(
                        sid, {"headers": [], "body": bytearray()})
                    if st["headers"]:
                        st["trailers"] = headers      # request trailers
                    else:
                        st["headers"] = headers
                    if end:
                        self._complete(sid)
                elif kind == "data":
                    _, sid, body, end = ev
                    st = self._assembling.get(sid)
                    if st is None:
                        continue
                    st["body"] += body
                    if len(st["body"]) > max_body_size():
                        self.session.send_rst(sid, E_PROTOCOL)
                        del self._assembling[sid]
                        continue
                    if end:
                        self._complete(sid)
                elif kind == "rst":
                    self._assembling.pop(ev[1], None)

    def _complete(self, sid: int) -> None:
        st = self._assembling.pop(sid, None)
        if st is None:
            return
        self.ready.append(H2Request(sid, st["headers"],
                                    bytes(st["body"]), self))

    # -- response writers (serialized by self.lock) -----------------------

    def flush(self, sock) -> None:
        # take_output must be under the lock: two responses finishing
        # concurrently could otherwise clear each other's queued frames
        with self.lock:
            out = self.session.take_output()
        if out and not sock.failed:
            sock.write(IOBuf(out))

    def send_grpc_response(self, sock, sid: int, payload: Optional[bytes],
                           status: int, message: str = "") -> None:
        with self.lock:
            if status == 0 and payload is not None:
                self.session.send_headers(sid, [
                    (":status", "200"), ("content-type", GRPC_CT)])
                self.session.send_data(sid, pack_grpc_message(payload))
                self.session.send_headers(
                    sid, [("grpc-status", "0")], end_stream=True)
            else:
                self.session.send_headers(sid, [
                    (":status", "200"), ("content-type", GRPC_CT),
                    ("grpc-status", str(status)),
                    ("grpc-message", message or "")], end_stream=True)
            self.session.close_stream(sid)
        self.flush(sock)

    def send_http_response(self, sock, sid: int, status: int, body: bytes,
                           ctype: str = "text/plain",
                           extra: Optional[List[Tuple[str, str]]] = None
                           ) -> None:
        with self.lock:
            headers = [(":status", str(status)), ("content-type", ctype),
                       ("content-length", str(len(body)))]
            headers += list(extra or [])
            self.session.send_headers(sid, headers, end_stream=not body)
            if body:
                self.session.send_data(sid, body, end_stream=True)
            self.session.close_stream(sid)
        self.flush(sock)


def parse(source: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    conn: Optional[H2ServerConn] = getattr(sock, "h2_conn", None)
    if conn is None:
        avail = len(source)
        probe = source.fetch(min(len(PREFACE), avail))
        if not PREFACE.startswith(probe):
            return ParseResult.try_others()
        if avail < len(PREFACE):
            return ParseResult.not_enough_data()
        conn = H2ServerConn(sock)
        sock.h2_conn = conn
    data = source.to_bytes()
    source.clear()
    try:
        if data:
            conn.feed(data)
    except H2Error as e:
        LOG.warning("h2 connection error: %s", e)
        with conn.lock:
            conn.session.send_goaway(e.code)
        conn.flush(sock)
        return ParseResult.absolutely_wrong()
    conn.flush(sock)                      # settings acks, window updates
    if conn.ready:
        first = conn.ready.pop(0)
        # one gulp can complete SEVERAL multiplexed streams, but the
        # messenger collects one message per parse and stops at an empty
        # source — dispatch the extras ourselves, one fiber each
        if conn.ready:
            from ..fiber import runtime as fiber_runtime
            extras, conn.ready = conn.ready, []
            for req in extras:
                fiber_runtime.spawn(_process_request, req, sock, arg,
                                    name="h2_request")
        return ParseResult.make_message(first)
    return ParseResult.not_enough_data()


def _process_request(req: H2Request, sock, server) -> None:
    ct = req.header("content-type")
    if ct.startswith(GRPC_CT):
        _process_grpc(req, sock, server)
        return
    # generic h2: builtin portal pages (the HTTP/1 path keeps the full
    # JSON bridge; internal-port gating applies identically)
    from ..protocol.http import HttpMessage
    from ..server.builtin import route_builtin

    path = req.header(":path")
    msg = HttpMessage()
    msg.is_request = True
    msg.method = req.header(":method") or "GET"
    msg.path, _, msg.query_string = path.partition("?")
    msg.body = req.body
    from ..server.http_dispatch import portal_restricted
    parts = [p for p in msg.path.split("/") if p]
    if portal_restricted(server, sock, parts[0] if parts else ""):
        req.conn.send_http_response(sock, req.stream_id, 403,
                                    b"restricted to the internal port\n")
        return
    try:
        status, ctype, body, extra = route_builtin(server, msg)
    except Exception as e:
        LOG.exception("builtin page %s raised (h2)", path)
        status, ctype, body, extra = 500, "text/plain", \
            f"internal error: {e}\n".encode(), []
    req.conn.send_http_response(sock, req.stream_id, status, body,
                                ctype, extra)


def _process_grpc(req: H2Request, sock, server) -> None:
    from ..server.controller import ServerController
    from ..protocol.meta import RpcMeta
    from ..protocol.tpu_std import parse_payload, serialize_payload

    path = req.header(":path")
    parts = [p for p in path.split("/") if p]
    if len(parts) != 2:
        req.conn.send_grpc_response(sock, req.stream_id, None, 12,
                                    f"malformed path {path!r}")
        return
    svc_full, method = parts
    entry = server.find_method(svc_full, method)
    if entry is None and "." in svc_full:
        # grpc clients address /package.Service/Method; our registry is
        # keyed by bare service name
        entry = server.find_method(svc_full.rsplit(".", 1)[-1], method)
    if entry is None:
        req.conn.send_grpc_response(sock, req.stream_id, None, 12,
                                    f"unknown method {path}")
        return
    if not server.on_request_in():
        req.conn.send_grpc_response(sock, req.stream_id, None, 8,
                                    "server max_concurrency")
        return
    if not entry.status.on_requested():
        server.on_request_out()
        req.conn.send_grpc_response(sock, req.stream_id, None, 8,
                                    "method max_concurrency")
        return

    buf = bytearray(req.body)
    try:
        messages = unpack_grpc_messages(buf)
    except H2Error as e:
        entry.status.on_responded(int(Errno.EREQUEST), 0)
        server.on_request_out()
        req.conn.send_grpc_response(sock, req.stream_id, None, 12, str(e))
        return
    payload = messages[0] if messages else b""

    meta = RpcMeta()
    meta.service_name = svc_full
    meta.method_name = method

    def send(cntl: ServerController, response) -> None:
        latency_us = monotonic_us() - cntl.begin_time_us
        entry.status.on_responded(cntl.error_code, latency_us)
        server.on_request_out()
        if cntl.failed:
            req.conn.send_grpc_response(
                sock, req.stream_id, None,
                grpc_status_of(cntl.error_code), cntl.error_text)
            return
        try:
            body = serialize_payload(response).to_bytes()
        except TypeError as e:
            req.conn.send_grpc_response(sock, req.stream_id, None, 13,
                                        f"serialize: {e}")
            return
        req.conn.send_grpc_response(sock, req.stream_id, body, 0)

    cntl = ServerController(meta, sock.remote_side, sock.id, send)
    cntl.server = server
    try:
        request = parse_payload(payload, entry.request_type)
    except Exception as e:
        cntl.set_failed(Errno.EREQUEST, f"request parse failed: {e}")
        cntl.finish(None)
        return
    try:
        response = entry.fn(cntl, request)
    except Exception as e:
        LOG.exception("grpc method %s raised", entry.status.full_name)
        cntl.set_failed(Errno.EINTERNAL, f"{type(e).__name__}: {e}")
        cntl.finish(None)
        return
    if cntl.is_async:
        return
    cntl.finish(response)


H2 = Protocol(
    ProtocolType.H2, "h2", parse,
    process_request=_process_request,
)
register_protocol(H2)
