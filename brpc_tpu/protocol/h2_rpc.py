"""HTTP/2 server protocol + gRPC semantics on the shared port.

Capability parity with /root/reference/src/brpc/policy/http2_rpc_protocol.cpp
+ src/brpc/grpc.*: the same port that speaks tpu_std/HTTP/1/streaming
also accepts h2 connections (detected by the client preface).  Requests
with content-type ``application/grpc`` get full gRPC unary semantics
(5-byte message framing, ``/package.Service/Method`` routing into the
regular service registry, grpc-status/grpc-message trailers,
grpc-timeout); other h2 requests are served the builtin portal pages —
the JSON/RPC bridge stays on HTTP/1.

The oracle for this implementation is the real ``grpcio`` package
(tests/test_grpc_interop.py): a grpcio client calls this server and a
grpcio server answers this framework's h2 client.
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, List, Optional, Tuple

from ..butil.iobuf import IOBuf
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..butil.time_utils import monotonic_us
from ..deadline import arm as _arm_deadline
from ..deadline import inherit_deadline as _inherit_deadline
from ..deadline import maybe_shed as _maybe_shed
from .base import (ParseResult, Protocol, ProtocolType, max_body_size,
                   register_protocol)
from .h2_session import (PREFACE, E_NO_ERROR, E_PROTOCOL, H2Error,
                         H2Session)

GRPC_CT = "application/grpc"

# Errno -> grpc-status (status.proto codes); default UNKNOWN(2)
_ERRNO_TO_GRPC = {
    0: 0,
    int(Errno.ENOSERVICE): 12,      # UNIMPLEMENTED
    int(Errno.ENOMETHOD): 12,
    int(Errno.EREQUEST): 3,         # INVALID_ARGUMENT
    int(Errno.ERPCAUTH): 16,        # UNAUTHENTICATED
    int(Errno.ELIMIT): 8,           # RESOURCE_EXHAUSTED
    int(Errno.EOVERCROWDED): 8,
    int(Errno.ERPCTIMEDOUT): 4,     # DEADLINE_EXCEEDED
    int(Errno.EINTERNAL): 13,       # INTERNAL
}


def grpc_status_of(errno_code: int) -> int:
    return _ERRNO_TO_GRPC.get(int(errno_code), 2)


_GRPC_TO_ERRNO = {
    0: 0,
    3: int(Errno.EREQUEST),
    4: int(Errno.ERPCTIMEDOUT),
    8: int(Errno.ELIMIT),
    12: int(Errno.ENOMETHOD),
    13: int(Errno.EINTERNAL),
    14: int(Errno.EFAILEDSOCKET),
    16: int(Errno.ERPCAUTH),
}


def errno_of_grpc_status(status: int) -> int:
    return _GRPC_TO_ERRNO.get(int(status), int(Errno.EINTERNAL))


def pack_grpc_message(payload: bytes) -> bytes:
    return b"\x00" + struct.pack(">I", len(payload)) + payload


_GRPC_TIMEOUT_UNIT_MS = {"H": 3600_000.0, "M": 60_000.0, "S": 1000.0,
                         "m": 1.0, "u": 1e-3, "n": 1e-6}


def parse_grpc_timeout(value: str) -> Optional[int]:
    """``grpc-timeout`` header (1-8 digits + one of HMSmun) → remaining
    milliseconds, or None when malformed.  Sub-millisecond values floor
    to 0 — which means expired-at-arrival, matching ``x-deadline-ms: 0``
    and distinct from an ABSENT header (no deadline)."""
    if not value or len(value) > 9:
        return None
    digits, unit = value[:-1], value[-1]
    if not digits.isdigit() or unit not in _GRPC_TIMEOUT_UNIT_MS:
        return None
    return int(int(digits) * _GRPC_TIMEOUT_UNIT_MS[unit])


def unpack_grpc_messages(buf: bytearray) -> List[bytes]:
    """Cut complete length-prefixed messages off ``buf`` (mutates)."""
    out = []
    while len(buf) >= 5:
        compressed = buf[0]
        (ln,) = struct.unpack_from(">I", buf, 1)
        if len(buf) < 5 + ln:
            break
        if compressed:
            raise H2Error(E_PROTOCOL, "compressed grpc message "
                                      "(no grpc-encoding negotiated)")
        out.append(bytes(buf[5:5 + ln]))
        del buf[:5 + ln]
    return out


def resolve_grpc_entry(server, path: str):
    """``/package.Service/Method`` → method entry (the registry is keyed
    by bare service name; package-qualified paths fall back)."""
    parts = [p for p in path.split("/") if p]
    if len(parts) != 2:
        return None
    svc_full, method = parts
    entry = server.find_method(svc_full, method)
    if entry is None and "." in svc_full:
        entry = server.find_method(svc_full.rsplit(".", 1)[-1], method)
    return entry


class H2Request:
    __slots__ = ("stream_id", "headers", "body", "conn", "recv_us")

    def __init__(self, stream_id: int, headers: List[Tuple[str, str]],
                 body: bytes, conn: "H2ServerConn"):
        self.stream_id = stream_id
        self.headers = headers
        self.body = body
        self.conn = conn
        # arrival anchor for the deadline plane (grpc-timeout): stamped
        # when the stream's END_STREAM completed assembly — fiber
        # queueing between here and dispatch counts against the budget
        self.recv_us = monotonic_us()

    def header(self, name: str) -> str:
        for n, v in self.headers:
            if n == name:
                return v
        return ""


class GrpcServerStream:
    """Live full-duplex gRPC stream on the server: the handler reads
    request messages by iterating, pushes responses with write(), and
    the dispatcher sends trailers when the handler returns.
    ≈ the reference's full-duplex h2 streams (grpc.h + the streaming
    paths of policy/http2_rpc_protocol.cpp)."""

    def __init__(self, conn: "H2ServerConn", sock, sid: int):
        self.conn = conn
        self.sock = sock
        self.sid = sid
        self._recv = bytearray()            # un-cut grpc message bytes
        self._msgs: List[bytes] = []
        self._buffered = 0                  # unread bytes (bounded)
        self._cond = threading.Condition()
        self._closed_remote = False
        self.cancelled = False              # peer RST: send nothing back
        self.framing_error = False          # bad message framing: status 12
        self._headers_sent = False

    # -- fed by the connection (under conn.lock) ---------------------------

    def _on_data(self, body: bytes, end: bool) -> None:
        with self._cond:
            self._recv += body
            self._buffered += len(body)
            if self._buffered > max_body_size():
                # same defense as the unary assembly path: a writer
                # outpacing the handler must not buffer unboundedly.
                # RST goes out now, so nothing more may be sent later.
                self.cancelled = True
                self._closed_remote = True
                self.conn.session.send_rst(self.sid, E_PROTOCOL)
                self._cond.notify_all()
                return
            try:
                self._msgs.extend(unpack_grpc_messages(self._recv))
            except H2Error:
                self.framing_error = True
                self._closed_remote = True
            if end:
                self._closed_remote = True
            self._cond.notify_all()

    def _on_rst(self) -> None:
        with self._cond:
            self.cancelled = True
            self._closed_remote = True
            self._cond.notify_all()

    # -- handler side ------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        msg = self.read()
        if msg is None:
            raise StopIteration
        return msg

    def read(self, timeout: Optional[float] = None) -> Optional[bytes]:
        """Next request message, or None when the client half-closed
        (or the stream was cancelled).  Raises TimeoutError on timeout —
        None strictly means end-of-stream."""
        from ..fiber.runtime import blocking
        with self._cond:
            with blocking():
                ok = self._cond.wait_for(
                    lambda: self._msgs or self._closed_remote
                    or self.cancelled, timeout)
            if self._msgs:
                msg = self._msgs.pop(0)
                self._buffered -= len(msg)
                return msg
            if not ok:
                raise TimeoutError("grpc stream read timed out")
            return None

    def write(self, payload: bytes) -> None:
        """Push one response message."""
        if self.cancelled or self.framing_error:
            return
        with self.conn.lock:
            self._send_headers_locked()
            self.conn.session.send_data(self.sid, pack_grpc_message(payload))
        self.conn.flush(self.sock)

    def _send_headers_locked(self) -> None:
        if not self._headers_sent:
            self._headers_sent = True
            self.conn.session.send_headers(self.sid, [
                (":status", "200"), ("content-type", GRPC_CT)])

    def _finish(self, status: int, message: str = "",
                final_payload: Optional[bytes] = None) -> None:
        if self.cancelled:
            # peer reset the stream: nothing may be sent on it
            with self.conn.lock:
                self.conn.live.pop(self.sid, None)
            return
        if self.framing_error and status == 0:
            status, message = 12, "malformed grpc message framing"
            final_payload = None
        with self.conn.lock:
            if status == 0:
                self._send_headers_locked()
                if final_payload is not None:
                    self.conn.session.send_data(
                        self.sid, pack_grpc_message(final_payload))
                self.conn.session.send_headers(
                    self.sid, [("grpc-status", "0")]
                    + ([("grpc-message", message)] if message else []),
                    end_stream=True)
            elif self._headers_sent:
                self.conn.session.send_headers(
                    self.sid, [("grpc-status", str(status)),
                               ("grpc-message", message or "")],
                    end_stream=True)
            else:
                self.conn.session.send_headers(self.sid, [
                    (":status", "200"), ("content-type", GRPC_CT),
                    ("grpc-status", str(status)),
                    ("grpc-message", message or "")], end_stream=True)
            self.conn.session.close_stream(self.sid)
            self.conn.live.pop(self.sid, None)
            self.conn._maybe_goaway_locked()
        self.conn.flush(self.sock)


class H2ServerConn:
    """Per-connection server state: the session + request assembly (and
    live streaming dispatch for @grpc_streaming methods)."""

    def __init__(self, sock, server=None):
        self.session = H2Session(is_server=True)
        self.sock_id = sock.id
        self.server = server
        self._sock = sock
        self._assembling: Dict[int, dict] = {}
        self.live: Dict[int, GrpcServerStream] = {}
        self.ready: List[H2Request] = []
        self.lock = threading.Lock()
        self._goaway_sent = False   # lame-duck GOAWAY: once per conn

    def _maybe_goaway_locked(self) -> None:
        """Operability plane, h2 spelling: while the server drains,
        the first response on each connection is followed by a
        NO_ERROR GOAWAY — the client finishes in-flight streams and
        re-connects elsewhere (the GOAWAY analogue of tpu_std's
        lame-duck TLV and HTTP/1.1's Connection: close).  Call with
        self.lock held, before take_output."""
        if self._goaway_sent:
            return
        srv = self.server
        if srv is not None and getattr(srv, "lame_duck_signal_on",
                                       False):
            self._goaway_sent = True
            self.session.send_goaway(E_NO_ERROR)

    def feed(self, data: bytes) -> None:
        spawn_live: List[Tuple[GrpcServerStream, object]] = []
        with self.lock:
            events = self.session.feed(data)
            for ev in events:
                kind = ev[0]
                if kind == "headers":
                    _, sid, headers, end = ev
                    if sid in self.live:
                        if end:                    # request trailers
                            self.live[sid]._on_data(b"", True)
                        continue
                    entry = None if end else self._streaming_entry(headers)
                    if entry is not None:
                        stream = GrpcServerStream(self, self._sock, sid)
                        self.live[sid] = stream
                        spawn_live.append((stream, (entry, headers)))
                        continue
                    st = self._assembling.setdefault(
                        sid, {"headers": [], "body": bytearray()})
                    if st["headers"]:
                        st["trailers"] = headers      # request trailers
                    else:
                        st["headers"] = headers
                    if end:
                        self._complete(sid)
                elif kind == "data":
                    _, sid, body, end = ev
                    live = self.live.get(sid)
                    if live is not None:
                        live._on_data(body, end)
                        continue
                    st = self._assembling.get(sid)
                    if st is None:
                        continue
                    st["body"] += body
                    if len(st["body"]) > max_body_size():
                        self.session.send_rst(sid, E_PROTOCOL)
                        del self._assembling[sid]
                        continue
                    if end:
                        self._complete(sid)
                elif kind == "rst":
                    self._assembling.pop(ev[1], None)
                    live = self.live.pop(ev[1], None)
                    if live is not None:
                        live._on_rst()
        for stream, ctx in spawn_live:
            from ..fiber import runtime as fiber_runtime
            # arrival anchor = now (the headers completed in THIS feed
            # batch): fiber queueing between here and admission counts
            # toward the CoDel sojourn
            fiber_runtime.spawn(_run_streaming_handler, stream, ctx[0],
                                ctx[1], self._sock, self.server,
                                monotonic_us(),
                                name="grpc_stream")

    def _streaming_entry(self, headers):
        """The method entry IFF this request addresses a @grpc_streaming
        method (dispatch must then start before END_STREAM)."""
        if self.server is None:
            return None
        hmap = dict(headers)
        if not hmap.get("content-type", "").startswith(GRPC_CT):
            return None
        entry = resolve_grpc_entry(self.server, hmap.get(":path", ""))
        return entry if entry is not None and entry.grpc_streaming else None

    def _complete(self, sid: int) -> None:
        st = self._assembling.pop(sid, None)
        if st is None:
            return
        self.ready.append(H2Request(sid, st["headers"],
                                    bytes(st["body"]), self))

    # -- response writers (serialized by self.lock) -----------------------

    def flush(self, sock) -> None:
        # take_output must be under the lock: two responses finishing
        # concurrently could otherwise clear each other's queued frames
        with self.lock:
            out = self.session.take_output()
        if out and not sock.failed:
            sock.write(IOBuf(out))

    def send_grpc_response(self, sock, sid: int, payload: Optional[bytes],
                           status: int, message: str = "") -> None:
        with self.lock:
            if status == 0 and payload is not None:
                self.session.send_headers(sid, [
                    (":status", "200"), ("content-type", GRPC_CT)])
                self.session.send_data(sid, pack_grpc_message(payload))
                self.session.send_headers(
                    sid, [("grpc-status", "0")], end_stream=True)
            else:
                self.session.send_headers(sid, [
                    (":status", "200"), ("content-type", GRPC_CT),
                    ("grpc-status", str(status)),
                    ("grpc-message", message or "")], end_stream=True)
            self.session.close_stream(sid)
            self._maybe_goaway_locked()
        self.flush(sock)

    def send_http_response(self, sock, sid: int, status: int, body: bytes,
                           ctype: str = "text/plain",
                           extra: Optional[List[Tuple[str, str]]] = None
                           ) -> None:
        with self.lock:
            headers = [(":status", str(status)), ("content-type", ctype),
                       ("content-length", str(len(body)))]
            headers += list(extra or [])
            self.session.send_headers(sid, headers, end_stream=not body)
            if body:
                self.session.send_data(sid, body, end_stream=True)
            self.session.close_stream(sid)
            self._maybe_goaway_locked()
        self.flush(sock)


def parse(source: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    conn: Optional[H2ServerConn] = getattr(sock, "h2_conn", None)
    if conn is None:
        avail = len(source)
        probe = source.fetch(min(len(PREFACE), avail))
        if not PREFACE.startswith(probe):
            return ParseResult.try_others()
        if avail < len(PREFACE):
            return ParseResult.not_enough_data()
        conn = H2ServerConn(sock, server=arg)
        sock.h2_conn = conn
    data = source.to_bytes()
    source.clear()
    try:
        if data:
            conn.feed(data)
    except H2Error as e:
        LOG.warning("h2 connection error: %s", e)
        with conn.lock:
            conn.session.send_goaway(e.code)
        conn.flush(sock)
        return ParseResult.absolutely_wrong()
    conn.flush(sock)                      # settings acks, window updates
    if conn.ready:
        first = conn.ready.pop(0)
        # one gulp can complete SEVERAL multiplexed streams, but the
        # messenger collects one message per parse and stops at an empty
        # source — dispatch the extras ourselves, one fiber each
        if conn.ready:
            from ..fiber import runtime as fiber_runtime
            extras, conn.ready = conn.ready, []
            for req in extras:
                fiber_runtime.spawn(_process_request, req, sock, arg,
                                    name="h2_request")
        return ParseResult.make_message(first)
    return ParseResult.not_enough_data()


def _run_streaming_handler(stream: GrpcServerStream, entry, headers,
                           sock, server, recv_us=None) -> None:
    """Fiber body for a @grpc_streaming method: admission, handler,
    trailers.  The handler sees (cntl, stream)."""
    from ..server.controller import ServerController
    from ..protocol.meta import RpcMeta
    from ..protocol.tpu_std import serialize_payload

    from ..server.admission import admit as _admit
    # overload plane: the shared admission stage (tenant from the
    # x-tenant HPACK header); rejections are RESOURCE_EXHAUSTED
    tenant_h = None
    for k, v in headers:
        if k == "x-tenant":
            tenant_h = v
            break
    rej = _admit(server, entry, "grpc", tenant_h, recv_us or None)
    if rej is not None:
        stream._finish(8, rej.text)
        return
    meta = RpcMeta()
    meta.service_name = entry.status.full_name.rsplit(".", 1)[0]
    meta.method_name = entry.method_name
    if tenant_h:
        meta.tenant = tenant_h.encode("utf-8", "replace")
    begin = monotonic_us()
    cntl = ServerController(meta, sock.remote_side, sock.id,
                            send_response=lambda c, r: None)
    cntl.server = server
    cntl.grpc_stream = stream
    try:
        ret = entry.fn(cntl, stream)
    except Exception as e:
        LOG.exception("grpc streaming method %s raised",
                      entry.status.full_name)
        cntl.set_failed(Errno.EINTERNAL, f"{type(e).__name__}: {e}")
        ret = None
    latency_us = monotonic_us() - begin
    entry.status.on_responded(cntl.error_code, latency_us)
    server.on_request_out(tenant=meta.tenant,
                          error_code=cntl.error_code,
                          latency_us=latency_us)
    if cntl.failed:
        stream._finish(grpc_status_of(cntl.error_code), cntl.error_text)
        return
    final = None
    if ret is not None:
        try:
            final = serialize_payload(ret).to_bytes()
        except TypeError as e:
            stream._finish(13, f"serialize: {e}")
            return
    stream._finish(0, final_payload=final)


def _process_request(req: H2Request, sock, server) -> None:
    ct = req.header("content-type")
    if ct.startswith(GRPC_CT):
        _process_grpc(req, sock, server)
        return
    # generic h2: builtin portal pages (the HTTP/1 path keeps the full
    # JSON bridge; internal-port gating applies identically)
    from ..protocol.http import HttpMessage
    from ..server.builtin import route_builtin

    path = req.header(":path")
    msg = HttpMessage()
    msg.is_request = True
    msg.method = req.header(":method") or "GET"
    msg.path, _, msg.query_string = path.partition("?")
    msg.body = req.body
    from ..server.http_dispatch import portal_restricted
    parts = [p for p in msg.path.split("/") if p]
    if portal_restricted(server, sock, parts[0] if parts else ""):
        req.conn.send_http_response(sock, req.stream_id, 403,
                                    b"restricted to the internal port\n")
        return
    try:
        status, ctype, body, extra = route_builtin(server, msg)
    except Exception as e:
        LOG.exception("builtin page %s raised (h2)", path)
        status, ctype, body, extra = 500, "text/plain", \
            f"internal error: {e}\n".encode(), []
    req.conn.send_http_response(sock, req.stream_id, status, body,
                                ctype, extra)


def _process_grpc(req: H2Request, sock, server) -> None:
    from ..server.controller import ServerController
    from ..protocol.meta import RpcMeta
    from ..protocol.tpu_std import parse_payload, serialize_payload

    path = req.header(":path")
    entry = resolve_grpc_entry(server, path)
    if entry is None:
        req.conn.send_grpc_response(sock, req.stream_id, None, 12,
                                    f"unknown method {path}")
        return
    if entry.grpc_streaming:
        # fully-assembled request on a streaming method (client sent
        # END_STREAM with HEADERS or in one gulp): run the handler with
        # a pre-closed stream carrying the buffered messages
        stream = GrpcServerStream(req.conn, sock, req.stream_id)
        with req.conn.lock:
            req.conn.live[req.stream_id] = stream
        stream._on_data(req.body, True)
        _run_streaming_handler(stream, entry, req.headers, sock, server,
                               recv_us=getattr(req, "recv_us", 0))
        return
    from ..server.admission import admit as _admit
    # overload plane: the shared admission stage — server cap, adaptive
    # method cap, CoDel sojourn (anchored at stream assembly), tenant
    # fair admission; rejections answer grpc-status 8
    # RESOURCE_EXHAUSTED (the ELIMIT row of the status map) before the
    # body is even unpacked
    tenant_h = req.header("x-tenant") or None
    rej = _admit(server, entry, "grpc", tenant_h,
                 getattr(req, "recv_us", 0) or None)
    if rej is not None:
        req.conn.send_grpc_response(sock, req.stream_id, None, 8,
                                    rej.text)
        return

    buf = bytearray(req.body)
    try:
        messages = unpack_grpc_messages(buf)
    except H2Error as e:
        entry.status.on_responded(int(Errno.EREQUEST), 0)
        server.on_request_out(tenant=tenant_h or b"")
        req.conn.send_grpc_response(sock, req.stream_id, None, 12, str(e))
        return
    payload = messages[0] if messages else b""

    meta = RpcMeta()
    meta.service_name = entry.status.full_name.rsplit(".", 1)[0]
    meta.method_name = entry.method_name
    if tenant_h:
        meta.tenant = tenant_h.encode("utf-8", "replace")
    tp_header = req.header("traceparent")
    if tp_header:
        from ..rpcz import parse_traceparent
        tp = parse_traceparent(tp_header)
        if tp is not None:
            # W3C trace context over HPACK → the internal trace model:
            # the server span parents to the caller's span id, exactly
            # like the tpu_std meta's trace/span TLVs
            meta.trace_id, meta.span_id = tp
    # grpc-timeout: the h2 spelling of tpu_std's remaining-deadline
    # TLV 13 (0 = already expired); kept in a local — meta.timeout_ms
    # == 0 conventionally means "none"
    dl_ms = parse_grpc_timeout(req.header("grpc-timeout"))
    if dl_ms is not None:
        meta.timeout_ms = dl_ms

    def send(cntl: ServerController, response) -> None:
        latency_us = monotonic_us() - cntl.begin_time_us
        entry.status.on_responded(cntl.error_code, latency_us)
        server.on_request_out(tenant=meta.tenant,
                              error_code=cntl.error_code,
                              latency_us=latency_us)
        span = cntl.span
        if cntl.failed:
            if span is not None:
                span.finish(cntl.error_code)
            req.conn.send_grpc_response(
                sock, req.stream_id, None,
                grpc_status_of(cntl.error_code), cntl.error_text)
            return
        try:
            body = serialize_payload(response).to_bytes()
        except TypeError as e:
            if span is not None:
                span.finish(int(Errno.EINTERNAL))
            req.conn.send_grpc_response(sock, req.stream_id, None, 13,
                                        f"serialize: {e}")
            return
        if span is not None:
            span.response_size = len(body)
            span.finish(0)
        req.conn.send_grpc_response(sock, req.stream_id, body, 0)

    cntl = ServerController(meta, sock.remote_side, sock.id, send)
    cntl.server = server
    from ..rpcz import start_server_span
    cntl.span = start_server_span(entry.status.full_name, meta,
                                  sock.remote_side)
    if cntl.span is not None:
        cntl.span.request_size = len(payload)
    if dl_ms is not None:
        # deadline plane: anchor grpc-timeout at stream assembly (fiber
        # queueing between END_STREAM and this dispatch counts against
        # it), then shed doomed work → DEADLINE_EXCEEDED trailers (the
        # ERPCTIMEDOUT→4 row of the status map) before the handler runs
        _arm_deadline(cntl, dl_ms, req.recv_us)
        if _maybe_shed(cntl, "grpc", entry.status.full_name):
            cntl.finish(None)
            return
    try:
        request = parse_payload(payload, entry.request_type)
    except Exception as e:
        cntl.set_failed(Errno.EREQUEST, f"request parse failed: {e}")
        cntl.finish(None)
        return
    try:
        with _inherit_deadline(cntl):
            response = entry.fn(cntl, request)
    except Exception as e:
        LOG.exception("grpc method %s raised", entry.status.full_name)
        cntl.set_failed(Errno.EINTERNAL, f"{type(e).__name__}: {e}")
        cntl.finish(None)
        return
    if cntl.is_async:
        return
    cntl.finish(response)


H2 = Protocol(
    ProtocolType.H2, "h2", parse,
    process_request=_process_request,
)
register_protocol(H2)
