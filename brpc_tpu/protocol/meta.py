"""RpcMeta — the framed-RPC meta block and its wire codec.

Capability parity with the reference's baidu_std RpcMeta
(/root/reference/src/brpc/policy/baidu_rpc_meta.proto): correlation id,
request (service/method/attachment) or response (error code/text) halves,
compression, auth, and trace context riding every frame.

Fresh design: the wire codec is a deterministic tag-length-value format
(not protobuf) so the framework has zero codegen dependencies for its own
control plane; payloads remain opaque bytes and MAY be protobuf — any
object with SerializeToString/ParseFromString plugs in at the user layer.
"""

from __future__ import annotations

import struct
from typing import Optional

# field tags (u8). 0 terminates.
# CONTRACT (machine-checked): engine.cpp's meta scans and the
# pre-encoded TLV_* prefixes below must agree with this registry (tag
# numbers AND fixed field widths) — `python -m brpc_tpu.tools.check`
# (tools/check/contracts.py) gates renumbering in tier-1.
_T_CORRELATION = 1      # u64
_T_COMPRESS = 2         # u8
_T_ATTACHMENT = 3       # u32 size of attachment tail within payload
_T_SERVICE = 4          # utf-8
_T_METHOD = 5           # utf-8
_T_ERROR_CODE = 6       # i32
_T_ERROR_TEXT = 7       # utf-8
_T_AUTH = 8             # bytes
_T_TRACE_ID = 9         # u64
_T_SPAN_ID = 10         # u64
_T_PARENT_SPAN = 11     # u64
_T_STREAM_ID = 12       # u64 (streaming rpc settlement)
_T_TIMEOUT_MS = 13      # u32 remaining-deadline propagation
_T_STREAM_WINDOW = 14   # u32 receiver buffer size (stream handshake)
_T_ICI_DOMAIN = 15      # bytes: sender's device-fabric domain id
_T_ICI_DESC = 16        # bytes: device attachment descriptor (ici/)
_T_ICI_CONN = 17        # bytes: initiator's connection nonce — the
                        # conn identity descriptor binding uses (address
                        # pairs disagree across proxies/NAT)
# shm data plane (transport/shm_ring.py — same-host attachments by
# descriptor instead of bytes, ≈ the reference's RDMA rkey exchange)
_T_SHM_OFFER = 18       # bytes: sender's ring spec (capability offer)
_T_SHM_ACCEPT = 19      # bytes: ring id the sender has mapped (confirm)
_T_SHM_RELEASE = 20     # bytes: slot credits returned to the ring owner
_T_SHM_DESC = 21        # bytes: (ring_id, slot, offset, len) — the
                        # attachment rides shared memory, not the frame
_T_TENANT = 22          # utf-8: caller's tenant identity (API key /
                        # ChannelOptions.tenant) — the overload plane's
                        # per-tenant fair-admission key.  Tolerated by
                        # every native lane (raw kinds ignore it, the
                        # slim shims enforce it — same contract as the
                        # remaining-deadline tag 13)
_T_LAME_DUCK = 23       # u8: RESPONSE-side drain signal — the server
                        # is lame-duck (draining toward restart).  The
                        # client removes the node from LB selection
                        # immediately (no breaker penalty) while still
                        # accepting this and every other in-flight
                        # response.  Appended by the classic send paths
                        # AND natively by engine.set_lame_duck — never
                        # scanned on requests


class CompressType:
    NONE = 0
    GZIP = 1
    ZLIB = 2
    SNAPPY = 3


def encode_tlv(tag: int, data: bytes) -> bytes:
    """One TLV field as wire bytes (for pre-encoded fast paths)."""
    return bytes([tag]) + struct.pack("<I", len(data)) + data


# pre-encoded TLV prefixes for the latency fast paths (client fast_call,
# server fast response) — single source of truth with the tag registry
TLV_CORRELATION = b"\x01\x08\x00\x00\x00"   # _T_CORRELATION, u64 follows
TLV_ATTACHMENT = b"\x03\x04\x00\x00\x00"    # _T_ATTACHMENT, u32 follows
TLV_TIMEOUT = b"\x0d\x04\x00\x00\x00"       # _T_TIMEOUT_MS, u32 follows
TLV_TRACE = b"\x09\x08\x00\x00\x00"         # _T_TRACE_ID, u64 follows
TLV_SPAN = b"\x0a\x08\x00\x00\x00"          # _T_SPAN_ID, u64 follows
LAME_DUCK_TLV = b"\x17\x01\x00\x00\x00\x01"  # _T_LAME_DUCK, u8 1 — the
#   COMPLETE pre-encoded TLV (tag 23 + len 1 + value — deliberately
#   NOT a TLV_* 5-byte prefix: nothing variable follows), spliced into
#   response metas while draining; engine.cpp's kDuckTlv mirrors it
TAG_SERVICE = _T_SERVICE
TAG_METHOD = _T_METHOD
TAG_AUTH = _T_AUTH
TAG_STREAM_ID = _T_STREAM_ID
TAG_STREAM_WINDOW = _T_STREAM_WINDOW
TAG_ICI_DOMAIN = _T_ICI_DOMAIN
TAG_ICI_DESC = _T_ICI_DESC
TAG_ICI_CONN = _T_ICI_CONN
TAG_SHM_OFFER = _T_SHM_OFFER
TAG_SHM_ACCEPT = _T_SHM_ACCEPT
TAG_SHM_RELEASE = _T_SHM_RELEASE
TAG_SHM_DESC = _T_SHM_DESC
TAG_TENANT = _T_TENANT
TAG_LAME_DUCK = _T_LAME_DUCK


class RpcMeta:
    __slots__ = ("correlation_id", "compress_type", "attachment_size",
                 "service_name", "method_name", "error_code", "error_text",
                 "auth_data", "trace_id", "span_id", "parent_span_id",
                 "stream_id", "timeout_ms", "stream_window",
                 "ici_domain", "ici_desc", "ici_conn", "timeout_present",
                 "shm_offer", "shm_accept", "shm_release", "shm_desc",
                 "tenant", "lame_duck")

    def __init__(self):
        self.correlation_id = 0
        self.compress_type = CompressType.NONE
        self.attachment_size = 0
        self.service_name = ""
        self.method_name = ""
        self.error_code = 0
        self.error_text = ""
        self.auth_data = b""
        self.trace_id = 0
        self.span_id = 0
        self.parent_span_id = 0
        self.stream_id = 0
        self.timeout_ms = 0
        # decode-side: tag 13 was on the wire (clients stamp ≥ 1, so a
        # crafted explicit 0 means expired-at-arrival — distinguishable
        # from an absent deadline, which also reads timeout_ms == 0)
        self.timeout_present = False
        self.stream_window = 0
        self.ici_domain = b""
        self.ici_desc = b""
        self.ici_conn = b""
        self.shm_offer = b""
        self.shm_accept = b""
        self.shm_release = b""
        self.shm_desc = b""
        self.tenant = b""
        self.lame_duck = 0

    @property
    def is_request(self) -> bool:
        return bool(self.method_name)

    # -- codec -------------------------------------------------------------

    def encode(self) -> bytes:
        out = bytearray()

        def put(tag: int, data: bytes) -> None:
            out.append(tag)
            out.extend(struct.pack("<I", len(data)))
            out.extend(data)

        if self.correlation_id:
            put(_T_CORRELATION, struct.pack("<Q", self.correlation_id))
        if self.compress_type:
            put(_T_COMPRESS, bytes([self.compress_type]))
        if self.attachment_size:
            put(_T_ATTACHMENT, struct.pack("<I", self.attachment_size))
        if self.service_name:
            put(_T_SERVICE, self.service_name.encode())
        if self.method_name:
            put(_T_METHOD, self.method_name.encode())
        if self.error_code:
            put(_T_ERROR_CODE, struct.pack("<i", self.error_code))
        if self.error_text:
            put(_T_ERROR_TEXT, self.error_text.encode())
        if self.auth_data:
            put(_T_AUTH, self.auth_data)
        if self.trace_id:
            put(_T_TRACE_ID, struct.pack("<Q", self.trace_id))
        if self.span_id:
            put(_T_SPAN_ID, struct.pack("<Q", self.span_id))
        if self.parent_span_id:
            put(_T_PARENT_SPAN, struct.pack("<Q", self.parent_span_id))
        if self.stream_id:
            put(_T_STREAM_ID, struct.pack("<Q", self.stream_id))
        if self.timeout_ms:
            put(_T_TIMEOUT_MS, struct.pack("<I", self.timeout_ms))
        if self.stream_window:
            put(_T_STREAM_WINDOW, struct.pack("<I", self.stream_window))
        if self.ici_domain:
            put(_T_ICI_DOMAIN, self.ici_domain)
        if self.ici_desc:
            put(_T_ICI_DESC, self.ici_desc)
        if self.ici_conn:
            put(_T_ICI_CONN, self.ici_conn)
        if self.shm_offer:
            put(_T_SHM_OFFER, self.shm_offer)
        if self.shm_accept:
            put(_T_SHM_ACCEPT, self.shm_accept)
        if self.shm_release:
            put(_T_SHM_RELEASE, self.shm_release)
        if self.shm_desc:
            put(_T_SHM_DESC, self.shm_desc)
        if self.tenant:
            put(_T_TENANT, self.tenant)
        if self.lame_duck:
            put(_T_LAME_DUCK, b"\x01")
        return bytes(out)

    @staticmethod
    def decode(data: bytes) -> Optional["RpcMeta"]:
        m = RpcMeta()
        off, end = 0, len(data)
        try:
            while off < end:
                tag = data[off]
                (ln,) = struct.unpack_from("<I", data, off + 1)
                off += 5
                field = data[off:off + ln]
                if len(field) != ln:
                    return None
                off += ln
                if tag == _T_CORRELATION:
                    (m.correlation_id,) = struct.unpack("<Q", field)
                elif tag == _T_COMPRESS:
                    m.compress_type = field[0]
                elif tag == _T_ATTACHMENT:
                    (m.attachment_size,) = struct.unpack("<I", field)
                elif tag == _T_SERVICE:
                    m.service_name = field.decode()
                elif tag == _T_METHOD:
                    m.method_name = field.decode()
                elif tag == _T_ERROR_CODE:
                    (m.error_code,) = struct.unpack("<i", field)
                elif tag == _T_ERROR_TEXT:
                    m.error_text = field.decode()
                elif tag == _T_AUTH:
                    m.auth_data = field
                elif tag == _T_TRACE_ID:
                    (m.trace_id,) = struct.unpack("<Q", field)
                elif tag == _T_SPAN_ID:
                    (m.span_id,) = struct.unpack("<Q", field)
                elif tag == _T_PARENT_SPAN:
                    (m.parent_span_id,) = struct.unpack("<Q", field)
                elif tag == _T_STREAM_ID:
                    (m.stream_id,) = struct.unpack("<Q", field)
                elif tag == _T_TIMEOUT_MS:
                    (m.timeout_ms,) = struct.unpack("<I", field)
                    m.timeout_present = True
                elif tag == _T_STREAM_WINDOW:
                    (m.stream_window,) = struct.unpack("<I", field)
                elif tag == _T_ICI_DOMAIN:
                    m.ici_domain = field
                elif tag == _T_ICI_DESC:
                    m.ici_desc = field
                elif tag == _T_ICI_CONN:
                    m.ici_conn = field
                elif tag == _T_SHM_OFFER:
                    m.shm_offer = field
                elif tag == _T_SHM_ACCEPT:
                    m.shm_accept = field
                elif tag == _T_SHM_RELEASE:
                    m.shm_release = field
                elif tag == _T_SHM_DESC:
                    m.shm_desc = field
                elif tag == _T_TENANT:
                    m.tenant = field
                elif tag == _T_LAME_DUCK:
                    m.lame_duck = field[0] if field else 1
                # unknown tags are skipped: forward compatibility
        except (struct.error, IndexError, UnicodeDecodeError):
            return None
        return m
