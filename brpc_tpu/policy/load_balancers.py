"""Builtin load-balancing policies, registered on import
(≈ /root/reference/src/brpc/global.cpp:368-376):

- ``rr`` / ``wrr``           round robin (+weighted by tag "w=N")
- ``random`` / ``wr``        (weighted) random
- ``c_murmurhash`` / ``c_md5``  consistent hashing (ketama ring,
  /root/reference/src/brpc/policy/consistent_hashing_load_balancer.cpp)
- ``la``                     locality-aware: lowest expected latency with
  inflight punishment (policy/locality_aware_load_balancer.h:41-80,
  docs/cn/lalb.md — algorithm shape, fresh implementation)
"""

from __future__ import annotations

import bisect
import hashlib
import itertools
import threading
import time
from typing import Dict, List, Optional

from ..butil.endpoint import EndPoint
from ..butil.fast_rand import fast_rand
from ..client.load_balancer import LoadBalancer, lb_registry
from ..client.naming_service import ServerNode


def _weight_of(node: ServerNode) -> int:
    for part in node.tag.split():
        if part.startswith("w="):
            try:
                return max(1, int(part[2:]))
            except ValueError:
                return 1
    return 1


class RoundRobinLB(LoadBalancer):
    def __init__(self):
        super().__init__()
        self._counter = itertools.count()

    def select(self, nodes, cntl):
        return nodes[next(self._counter) % len(nodes)]


class WeightedRoundRobinLB(LoadBalancer):
    def __init__(self):
        super().__init__()
        self._counter = itertools.count()
        self._cache_lock = threading.Lock()
        self._cache_src: Optional[tuple] = None
        self._cycle: List[ServerNode] = []

    def _expanded(self, nodes) -> List[ServerNode]:
        key = tuple(id(n) for n in nodes)
        with self._cache_lock:
            if key != self._cache_src:
                cycle: List[ServerNode] = []
                for n in nodes:
                    cycle.extend([n] * _weight_of(n))
                self._cache_src = key
                self._cycle = cycle
            return self._cycle

    def select(self, nodes, cntl):
        cycle = self._expanded(nodes)
        return cycle[next(self._counter) % len(cycle)]


class RandomLB(LoadBalancer):
    def select(self, nodes, cntl):
        return nodes[fast_rand() % len(nodes)]


class WeightedRandomLB(LoadBalancer):
    def select(self, nodes, cntl):
        weights = [_weight_of(n) for n in nodes]
        total = sum(weights)
        pick = fast_rand() % total
        for n, w in zip(nodes, weights):
            if pick < w:
                return n
            pick -= w
        return nodes[-1]


class ConsistentHashLB(LoadBalancer):
    """Ketama ring with virtual replicas; the key is the call's
    ``request_code`` (set by the user, ≈ cntl.set_request_code)."""

    REPLICAS = 100

    def __init__(self, hasher: str = "murmurhash"):
        super().__init__()
        self._hasher = hasher
        self._ring_lock = threading.Lock()
        self._ring_src: Optional[tuple] = None
        self._ring: List[int] = []
        self._ring_nodes: List[ServerNode] = []

    def _hash(self, data: bytes) -> int:
        if self._hasher == "md5":
            return int.from_bytes(hashlib.md5(data).digest()[:8], "little")
        # murmur-shaped 64-bit mix (fresh implementation)
        h = 0xC6A4A7935BD1E995
        for b in data:
            h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
            h ^= h >> 29
        return h

    def _build_ring(self, nodes):
        key = tuple(str(n) for n in nodes)
        with self._ring_lock:
            if key == self._ring_src:
                return self._ring, self._ring_nodes
            points: List[tuple] = []
            for n in nodes:
                base = str(n.endpoint).encode()
                for r in range(self.REPLICAS * _weight_of(n)):
                    points.append((self._hash(base + b"#%d" % r), n))
            points.sort(key=lambda p: p[0])
            self._ring = [p[0] for p in points]
            self._ring_nodes = [p[1] for p in points]
            self._ring_src = key
            return self._ring, self._ring_nodes

    def select(self, nodes, cntl):
        ring, ring_nodes = self._build_ring(nodes)
        if not ring:
            return None
        code = getattr(cntl, "request_code", 0) or 0
        h = self._hash(int(code).to_bytes(8, "little"))
        idx = bisect.bisect_left(ring, h) % len(ring)
        return ring_nodes[idx]


class LocalityAwareLB(LoadBalancer):
    """Pick the server with the best expected latency, punishing inflight
    depth: weight = 1 / (ema_latency_us * (1 + inflight * punish)).
    The reference's iterative lowest-expected-latency idea
    (locality_aware_load_balancer.h) without its tree structure."""

    PUNISH = 0.5
    ALPHA = 0.2
    DEFAULT_LATENCY_US = 50_000.0

    def __init__(self):
        super().__init__()
        self._stat_lock = threading.Lock()
        self._lat: Dict[EndPoint, float] = {}
        self._inflight: Dict[EndPoint, int] = {}

    def select(self, nodes, cntl):
        best, best_score = None, float("inf")
        with self._stat_lock:
            untried = [n for n in nodes if n.endpoint not in self._lat]
            if untried:
                # explore before exploiting — otherwise the first server
                # to report a latency wins all traffic forever
                best = untried[fast_rand() % len(untried)]
                self._inflight[best.endpoint] = \
                    self._inflight.get(best.endpoint, 0) + 1
                return best
            for n in nodes:
                lat = self._lat.get(n.endpoint, self.DEFAULT_LATENCY_US)
                inflight = self._inflight.get(n.endpoint, 0)
                score = lat * (1.0 + inflight * self.PUNISH)
                # small dither so equal servers share load
                score *= 1.0 + (fast_rand() % 128) / 1024.0
                if score < best_score:
                    best, best_score = n, score
            if best is not None:
                self._inflight[best.endpoint] = \
                    self._inflight.get(best.endpoint, 0) + 1
        return best

    def on_feedback(self, cntl):
        ep = cntl.remote_side
        # every attempt's select() incremented inflight; decrement them
        # all (retried calls touched several servers)
        attempts = list(getattr(cntl, "attempt_remotes", {}).values()) \
            or [ep]
        with self._stat_lock:
            for aep in attempts:
                n = self._inflight.get(aep, 0)
                if n > 0:
                    self._inflight[aep] = n - 1
            if cntl.error_code == 0:
                prev = self._lat.get(ep, self.DEFAULT_LATENCY_US)
                self._lat[ep] = prev + (cntl.latency_us - prev) * self.ALPHA
            else:
                # failures look slow: steer away without a hard ban
                # (the breaker handles hard isolation)
                prev = self._lat.get(ep, self.DEFAULT_LATENCY_US)
                self._lat[ep] = prev * 1.5


lb_registry().register("rr", RoundRobinLB)
lb_registry().register("wrr", WeightedRoundRobinLB)
lb_registry().register("random", RandomLB)
lb_registry().register("wr", WeightedRandomLB)
lb_registry().register("c_murmurhash", ConsistentHashLB)
lb_registry().register("c_md5", lambda: ConsistentHashLB("md5"))
lb_registry().register("la", LocalityAwareLB)
