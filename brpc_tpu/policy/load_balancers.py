"""Builtin load-balancing policies, registered on import
(≈ /root/reference/src/brpc/global.cpp:368-376):

- ``rr`` / ``wrr``           round robin (+weighted by tag "w=N")
- ``random`` / ``wr``        (weighted) random
- ``c_murmurhash`` / ``c_md5``  consistent hashing (ketama ring,
  /root/reference/src/brpc/policy/consistent_hashing_load_balancer.cpp)
- ``la``                     locality-aware: lowest expected latency with
  inflight punishment (policy/locality_aware_load_balancer.h:41-80,
  docs/cn/lalb.md — algorithm shape, fresh implementation)
"""

from __future__ import annotations

import bisect
import hashlib
import itertools
import threading
import time
from typing import Any, Dict, List, Optional

from ..butil.endpoint import EndPoint
from ..butil.fast_rand import fast_rand
from ..client.load_balancer import LoadBalancer, lb_registry
from ..client.naming_service import ServerNode


def _weight_of(node: ServerNode) -> int:
    for part in node.tag.split():
        if part.startswith("w="):
            try:
                return max(1, int(part[2:]))
            except ValueError:
                return 1
    return 1


class RoundRobinLB(LoadBalancer):
    def __init__(self):
        super().__init__()
        self._counter = itertools.count()

    def select(self, nodes, cntl):
        return nodes[next(self._counter) % len(nodes)]


class WeightedRoundRobinLB(LoadBalancer):
    def __init__(self):
        super().__init__()
        self._counter = itertools.count()
        self._cache_lock = threading.Lock()
        self._cache_src: Optional[tuple] = None
        self._cycle: List[ServerNode] = []

    def _expanded(self, nodes) -> List[ServerNode]:
        key = tuple(id(n) for n in nodes)
        with self._cache_lock:
            if key != self._cache_src:
                cycle: List[ServerNode] = []
                for n in nodes:
                    cycle.extend([n] * _weight_of(n))
                self._cache_src = key
                self._cycle = cycle
            return self._cycle

    def select(self, nodes, cntl):
        cycle = self._expanded(nodes)
        return cycle[next(self._counter) % len(cycle)]


class RandomLB(LoadBalancer):
    def select(self, nodes, cntl):
        return nodes[fast_rand() % len(nodes)]


class WeightedRandomLB(LoadBalancer):
    def select(self, nodes, cntl):
        weights = [_weight_of(n) for n in nodes]
        total = sum(weights)
        pick = fast_rand() % total
        for n, w in zip(nodes, weights):
            if pick < w:
                return n
            pick -= w
        return nodes[-1]


class ConsistentHashLB(LoadBalancer):
    """Ketama ring with virtual replicas; the key is the call's
    ``request_code`` (set by the user, ≈ cntl.set_request_code)."""

    REPLICAS = 100

    def __init__(self, hasher: str = "murmurhash"):
        super().__init__()
        self._hasher = hasher
        self._ring_lock = threading.Lock()
        self._ring_src: Optional[tuple] = None
        self._ring: List[int] = []
        self._ring_nodes: List[ServerNode] = []

    def _hash(self, data: bytes) -> int:
        if self._hasher == "md5":
            return int.from_bytes(hashlib.md5(data).digest()[:8], "little")
        # murmur-shaped 64-bit mix (fresh implementation)
        h = 0xC6A4A7935BD1E995
        for b in data:
            h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
            h ^= h >> 29
        return h

    def _build_ring(self, nodes):
        key = tuple(str(n) for n in nodes)
        with self._ring_lock:
            if key == self._ring_src:
                return self._ring, self._ring_nodes
            points: List[tuple] = []
            for n in nodes:
                base = str(n.endpoint).encode()
                for r in range(self.REPLICAS * _weight_of(n)):
                    points.append((self._hash(base + b"#%d" % r), n))
            points.sort(key=lambda p: p[0])
            self._ring = [p[0] for p in points]
            self._ring_nodes = [p[1] for p in points]
            self._ring_src = key
            return self._ring, self._ring_nodes

    def select(self, nodes, cntl):
        ring, ring_nodes = self._build_ring(nodes)
        if not ring:
            return None
        code = getattr(cntl, "request_code", 0) or 0
        h = self._hash(int(code).to_bytes(8, "little"))
        idx = bisect.bisect_left(ring, h) % len(ring)
        return ring_nodes[idx]


class WeightTree:
    """Fenwick (binary-indexed) tree over node weights with O(log n)
    update and O(log n) weighted-random pick — the reference's
    locality-aware weight tree shape
    (/root/reference/src/brpc/policy/locality_aware_load_balancer.h:41-80)
    re-expressed: total() is the root sum, pick descends by prefix sums.
    """

    def __init__(self, n: int = 0):
        self._n = 0
        self._bit: List[float] = []
        self._w: List[float] = []
        if n:
            self.resize(n)

    def resize(self, n: int) -> None:
        self._n = n
        self._bit = [0.0] * (n + 1)
        self._w = [0.0] * n

    def update(self, i: int, w: float) -> None:
        delta = w - self._w[i]
        if delta == 0.0:
            return
        self._w[i] = w
        j = i + 1
        while j <= self._n:
            self._bit[j] += delta
            j += j & (-j)

    def weight(self, i: int) -> float:
        return self._w[i]

    def total(self) -> float:
        return self._prefix(self._n)

    def _prefix(self, j: int) -> float:
        s = 0.0
        while j > 0:
            s += self._bit[j]
            j -= j & (-j)
        return s

    def pick(self, r: float) -> int:
        """Index i such that prefix(i) <= r < prefix(i+1); O(log n)
        Fenwick descent."""
        pos = 0
        mask = 1
        while mask * 2 <= self._n:
            mask *= 2
        while mask:
            nxt = pos + mask
            if nxt <= self._n and self._bit[nxt] <= r:
                pos = nxt
                r -= self._bit[nxt]
            mask //= 2
        return min(pos, self._n - 1)


class LocalityAwareLB(LoadBalancer):
    """Weighted-random by expected goodness: weight =
    1 / (ema_latency_us * (1 + inflight * punish)), maintained in a
    Fenwick weight tree so select and feedback are O(log n) — the shape
    that survives pod-scale server lists
    (≈ locality_aware_load_balancer.h:41-80)."""

    PUNISH = 0.5
    ALPHA = 0.2
    DEFAULT_LATENCY_US = 50_000.0

    def __init__(self):
        super().__init__()
        self._stat_lock = threading.Lock()
        self._lat: Dict[EndPoint, float] = {}
        self._inflight: Dict[EndPoint, int] = {}
        self._tree = WeightTree()
        self._eps: List[EndPoint] = []
        self._index: Dict[EndPoint, int] = {}
        self._by_ep: Dict[EndPoint, Any] = {}

    def _weight_of(self, ep: EndPoint) -> float:
        lat = self._lat.get(ep, self.DEFAULT_LATENCY_US)
        inflight = self._inflight.get(ep, 0)
        return 1e9 / (lat * (1.0 + inflight * self.PUNISH))

    def _rebuild_locked(self, nodes) -> None:
        self._eps = [n.endpoint for n in nodes]
        self._index = {ep: i for i, ep in enumerate(self._eps)}
        self._by_ep = {n.endpoint: n for n in nodes}
        self._tree.resize(len(self._eps))
        for i, ep in enumerate(self._eps):
            self._tree.update(i, self._weight_of(ep))

    def _bump_locked(self, ep: EndPoint) -> None:
        i = self._index.get(ep)
        if i is not None:
            self._tree.update(i, self._weight_of(ep))

    def select(self, nodes, cntl):
        with self._stat_lock:
            if len(nodes) != len(self._eps) or any(
                    n.endpoint not in self._index for n in nodes):
                self._rebuild_locked(nodes)
            total = self._tree._prefix(self._tree._n)
            if total <= 0:
                best = nodes[fast_rand() % len(nodes)]
            else:
                # a few weighted draws tolerate per-call exclusions
                # without rebuilding the tree
                excluded = getattr(cntl, "excluded_servers", None) or ()
                best = None
                for _ in range(4):
                    r = (fast_rand() % (1 << 30)) / float(1 << 30) * total
                    ep = self._eps[self._tree.pick(r)]
                    if ep not in excluded:
                        best = self._by_ep.get(ep)
                        break
                if best is None:
                    best = nodes[fast_rand() % len(nodes)]
            ep = best.endpoint
            self._inflight[ep] = self._inflight.get(ep, 0) + 1
            self._bump_locked(ep)
        return best

    def on_feedback(self, cntl):
        ep = cntl.remote_side
        # every attempt's select() incremented inflight; decrement them
        # all (retried calls touched several servers)
        attempts = list(getattr(cntl, "attempt_remotes", {}).values()) \
            or [ep]
        with self._stat_lock:
            for aep in attempts:
                n = self._inflight.get(aep, 0)
                if n > 0:
                    self._inflight[aep] = n - 1
                self._bump_locked(aep)
            if cntl.error_code == 0:
                prev = self._lat.get(ep, self.DEFAULT_LATENCY_US)
                self._lat[ep] = prev + (cntl.latency_us - prev) * self.ALPHA
            else:
                # failures look slow: steer away without a hard ban
                # (the breaker handles hard isolation)
                prev = self._lat.get(ep, self.DEFAULT_LATENCY_US)
                self._lat[ep] = prev * 1.5
            self._bump_locked(ep)


class DynPartLB(LoadBalancer):
    """Weighted-random by declared node weight
    (≈ /root/reference/src/brpc/policy/dynpart_load_balancer.cpp, which
    weights partitioned sub-channels by capacity): a node's ``w=<n>``
    tag token sets its weight (default 1), so heterogeneous partitions
    of a dynamically re-partitioning cluster receive proportional
    traffic."""

    @staticmethod
    def _weight(node) -> int:
        for token in (node.tag or "").split():
            if token.startswith("w="):
                try:
                    return max(0, int(token[2:]))
                except ValueError:
                    return 1
        return 1

    def select(self, nodes, cntl):
        total = sum(self._weight(n) for n in nodes)
        if total <= 0:
            return nodes[fast_rand() % len(nodes)]
        r = fast_rand() % total
        for n in nodes:
            r -= self._weight(n)
            if r < 0:
                return n
        return nodes[-1]


lb_registry().register("rr", RoundRobinLB)
lb_registry().register("dynpart", DynPartLB)
lb_registry().register("wrr", WeightedRoundRobinLB)
lb_registry().register("random", RandomLB)
lb_registry().register("wr", WeightedRandomLB)
lb_registry().register("c_murmurhash", ConsistentHashLB)
lb_registry().register("c_md5", lambda: ConsistentHashLB("md5"))
lb_registry().register("la", LocalityAwareLB)
