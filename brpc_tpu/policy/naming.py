"""Builtin naming services (≈ /root/reference/src/brpc/policy/
{list,file,domain}_naming_service.cpp + this build's mesh topology NS),
registered under their URL schemes on import (≈ global.cpp:354-365).

- ``list://h1:p1[ tag],h2:p2``  static list, tags after spaces
- ``file:///path``              one server per line, reloaded on change
- ``dns://host:port``           periodic resolution, all A records
- ``mesh://name``               device coordinates of an ICI mesh — the
                                TPU topology source (peers = chips)
"""

from __future__ import annotations

import os
import socket as _socket
from typing import List, Optional, Sequence

from ..butil.endpoint import EndPoint
from ..client.naming_service import (NamingService, ServerNode,
                                     naming_registry, parse_server_line)


class ListNamingService(NamingService):
    """Static: the url itself is the list; no refresh needed."""

    def __init__(self):
        super().__init__()
        self.refresh_interval_s = 0
        self._nodes: List[ServerNode] = []

    def start(self, url_path: str) -> int:
        nodes = []
        for part in url_path.split(","):
            node = parse_server_line(part)
            if part.strip() and node is None:
                return -1
            if node is not None:
                nodes.append(node)
        if not nodes:
            return -1
        self._nodes = nodes
        self.push(nodes)
        return 0

    def fetch_servers(self) -> Sequence[ServerNode]:
        return self._nodes


class FileNamingService(NamingService):
    def __init__(self):
        super().__init__()
        self._path = ""
        self._mtime = 0.0

    def start(self, url_path: str) -> int:
        path = url_path
        if not path.startswith("/") and os.path.exists("/" + path):
            path = "/" + path        # file:///abs/path → rest lacks one /
        self._path = path
        if not os.path.exists(self._path):
            return -1
        return super().start(url_path)

    def fetch_servers(self) -> Optional[Sequence[ServerNode]]:
        try:
            mtime = os.path.getmtime(self._path)
            with open(self._path) as f:
                lines = f.readlines()
        except OSError:
            return None             # keep previous list
        self._mtime = mtime
        return [n for n in map(parse_server_line, lines) if n is not None]


class DnsNamingService(NamingService):
    def __init__(self):
        super().__init__()
        self.refresh_interval_s = 30.0
        self._host = ""
        self._port = 0

    def start(self, url_path: str) -> int:
        host, _, port = url_path.partition(":")
        if not host:
            return -1
        self._host = host
        try:
            self._port = int(port) if port else 80
        except ValueError:
            return -1
        return super().start(url_path)

    def fetch_servers(self) -> Optional[Sequence[ServerNode]]:
        try:
            infos = _socket.getaddrinfo(self._host, self._port,
                                        _socket.AF_INET,
                                        _socket.SOCK_STREAM)
        except OSError:
            return None
        seen, nodes = set(), []
        for _, _, _, _, sockaddr in infos:
            ep = EndPoint(host=sockaddr[0], port=sockaddr[1])
            if ep not in seen:
                seen.add(ep)
                nodes.append(ServerNode(ep))
        return nodes


class MeshNamingService(NamingService):
    """Peers = device coordinates of an ICI mesh: with N chips the
    "cluster" is ici://<name>/0..N-1, each tagged ``i/N`` so
    PartitionChannel can shard key-spaces straight onto the mesh."""

    def __init__(self):
        super().__init__()
        self.refresh_interval_s = 0      # topology is static per process
        self._name = ""

    def start(self, url_path: str) -> int:
        from ..parallel.mesh_transport import global_mesh_transport
        self._name = url_path or "mesh0"
        mt = global_mesh_transport()
        n = mt.n_peers
        self.push([ServerNode(EndPoint(mesh=self._name, device_index=i),
                              tag=f"{i}/{n}") for i in range(n)])
        return 0

    def fetch_servers(self) -> Sequence[ServerNode]:
        return self.current


naming_registry().register("list", ListNamingService)
naming_registry().register("file", FileNamingService)
naming_registry().register("dns", DnsNamingService)
naming_registry().register("mesh", MeshNamingService)

# watch:// — long-poll remote membership (fleet controller); its own
# module: it owns a thread and degrade-to-file machinery
from . import remote_naming as _remote_naming          # noqa: E402,F401
