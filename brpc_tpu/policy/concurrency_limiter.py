"""Concurrency limiters
(≈ /root/reference/src/brpc/concurrency_limiter.h:29-52 and
policy/auto_concurrency_limiter.h:28,55-63):

- **constant**: fixed in-flight cap ("constant:100" or an int);
- **auto**: gradient/Vegas-style adaptive limit — tracks a smoothed
  no-load latency estimate; when recent latency inflates beyond it the
  limit shrinks, when the pipeline is full and latency is flat it grows.
  Fresh implementation of the reference's algorithm *shape* (EMA minimum
  latency + qps-driven limit), not its code.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional


class ConcurrencyLimiter:
    """Plugin interface: max_concurrency() read per-request;
    on_responded(error_code, latency_us) feeds the controller."""

    kind = "custom"          # portal label ("auto"/"timeout"/"constant")

    def max_concurrency(self) -> int:
        raise NotImplementedError

    def on_responded(self, error_code: int, latency_us: float) -> None:
        pass


class ConstantLimiter(ConcurrencyLimiter):
    kind = "constant"

    def __init__(self, limit: int):
        self._limit = int(limit)

    def max_concurrency(self) -> int:
        return self._limit


class AutoLimiter(ConcurrencyLimiter):
    """Adaptive limit ≈ auto_concurrency_limiter.h: sampling windows of
    (qps, latency); min-latency EMA as the no-load estimate; limit =
    peak_qps × min_latency × (1 + alpha) with shrink on latency blow-up."""

    kind = "auto"

    def __init__(self,
                 min_limit: int = 8,
                 max_limit: int = 4096,
                 sample_window_s: float = 0.1,
                 min_sample_count: int = 50,
                 alpha_factor: float = 0.3):
        self._lock = threading.Lock()
        self._limit = min_limit * 4
        self._min_limit = min_limit
        self._max_limit = max_limit
        self._window_s = sample_window_s
        self._min_samples = min_sample_count
        self._alpha = alpha_factor
        self._win_start = time.monotonic()
        self._win_count = 0
        self._win_err = 0
        self._win_lat_sum = 0.0
        self._nolat_ema: Optional[float] = None   # no-load latency (us)
        self._peak_qps = 0.0

    def max_concurrency(self) -> int:
        return self._limit

    def on_responded(self, error_code: int, latency_us: float) -> None:
        with self._lock:
            self._win_count += 1
            if error_code != 0:
                self._win_err += 1
            else:
                self._win_lat_sum += latency_us
            now = time.monotonic()
            dt = now - self._win_start
            if dt < self._window_s or self._win_count < self._min_samples:
                return
            ok = self._win_count - self._win_err
            if ok > 0:
                avg_lat = self._win_lat_sum / ok
                qps = ok / dt
                self._peak_qps = max(self._peak_qps * 0.98, qps)
                if self._nolat_ema is None or avg_lat < self._nolat_ema:
                    self._nolat_ema = avg_lat
                elif avg_lat <= self._nolat_ema * (1.0 + self._alpha):
                    # quiet window: drift up slowly so the estimate can
                    # track a genuinely shifted baseline.  An OVERLOADED
                    # window must NOT meaningfully feed the no-load
                    # estimate — that drift would launder queueing delay
                    # into "normal" and the limit would never shrink
                    # under sustained overload (the reference
                    # re-measures min latency in non-overloaded windows
                    # for the same reason)
                    self._nolat_ema += (avg_lat - self._nolat_ema) * 0.02
                else:
                    # overloaded window: a 20x-slower RE-MEASUREMENT
                    # path so the estimate is not frozen forever when
                    # the baseline genuinely shifted past (1+alpha)x
                    # (slower dependency, not queueing) — a real shift
                    # re-learns over ~hundreds of windows, while
                    # transient overload moves the estimate by well
                    # under a percent before the shrink drains it
                    self._nolat_ema += (avg_lat - self._nolat_ema) * 0.001
                base = self._peak_qps * (self._nolat_ema / 1e6)
                if avg_lat > self._nolat_ema * (1.0 + self._alpha):
                    # overload: shrink — with peak_qps decaying 2% per
                    # window, sustained overload keeps ratcheting the
                    # limit down until latency returns to baseline
                    new_limit = base * (1.0 - self._alpha / 2)
                else:
                    new_limit = base * (1.0 + self._alpha)
                self._limit = int(min(self._max_limit,
                                      max(self._min_limit,
                                          math.ceil(new_limit))))
            self._win_start = now
            self._win_count = 0
            self._win_err = 0
            self._win_lat_sum = 0.0


class TimeoutLimiter(ConcurrencyLimiter):
    """Timeout-driven limit
    (≈ /root/reference/src/brpc/policy/timeout_concurrency_limiter.h):
    admit only as many requests as can still finish inside the timeout
    budget — max_concurrency = timeout / avg_latency.  A latency EMA
    (failures counted at the full timeout) drives the bound, so a slow
    backend sheds load it could never answer in time instead of queueing
    doomed requests."""

    kind = "timeout"

    def __init__(self, timeout_ms: float = 500.0,
                 min_limit: int = 2, max_limit: int = 4096,
                 alpha: float = 0.2):
        self._timeout_us = max(1.0, timeout_ms * 1000.0)
        self._min = min_limit
        self._max = max_limit
        self._alpha = alpha
        self._lock = threading.Lock()
        self._lat_ema: Optional[float] = None
        self._limit = max_limit

    def max_concurrency(self) -> int:
        return self._limit

    def on_responded(self, error_code: int, latency_us: float) -> None:
        with self._lock:
            sample = latency_us if error_code == 0 else self._timeout_us
            if self._lat_ema is None:
                self._lat_ema = float(sample)
            else:
                self._lat_ema += (sample - self._lat_ema) * self._alpha
            self._limit = int(min(self._max, max(
                self._min, self._timeout_us / max(1.0, self._lat_ema))))


def make_limiter(spec) -> Optional[ConcurrencyLimiter]:
    """Parse an AdaptiveMaxConcurrency-style spec
    (≈ src/brpc/adaptive_max_concurrency.h): int / "constant:N" /
    "auto" / "timeout[:ms]" / "unlimited"."""
    if spec is None:
        return None
    if isinstance(spec, int):
        return ConstantLimiter(spec) if spec > 0 else None
    s = str(spec).strip().lower()
    if s in ("", "unlimited", "0"):
        return None
    if s == "auto":
        return AutoLimiter()
    if s == "timeout":
        return TimeoutLimiter()
    if s.startswith("timeout:"):
        return TimeoutLimiter(float(s.split(":", 1)[1]))
    if s.startswith("constant:"):
        return ConstantLimiter(int(s.split(":", 1)[1]))
    if s.isdigit():
        return ConstantLimiter(int(s))
    raise ValueError(f"unknown concurrency limiter spec: {spec!r}")
