"""Pluggable policies — concurrency limiters, load balancers, naming
services, retry/backup policies (≈ /root/reference/src/brpc/policy/).
Each sub-module registers its implementations in the relevant extension
registry on import."""
