"""Watch-based remote naming service — membership from a fleet
controller endpoint over HTTP long-poll.

≈ /root/reference/src/brpc/policy/consul_naming_service.cpp: the
reference watches consul with blocking queries (``?index=N&wait=60s``),
resumes from the ``X-Consul-Index`` response header, and degrades to a
file-based snapshot when the registry is unreachable.  A TPU fleet's
membership comes from a controller service the same way — this NS
speaks that shape natively:

    ``watch://host:port/path``

- GET ``path?index=N&wait=Ws``: the controller blocks until its
  membership index advances past N (or the wait expires), then answers
  the full list — one ``host:port [tag]`` per line (the file-NS line
  format) — with the new index in the ``X-Fleet-Index`` header.
- No ``X-Fleet-Index`` header ⇒ the endpoint is a plain snapshot;
  the NS falls back to periodic polling at ``refresh_interval_s``.
- Every successful fetch is mirrored to
  ``<remote_ns_backup_dir>/<sanitized-url>``; when the controller is
  unreachable before the first fetch, the backup seeds the server list
  (the reference's degrade-to-file behavior), so a restarting client
  rides out a controller outage.

The long-poll runs on a dedicated daemon thread (not the shared
periodic timer): a blocking watch must never stall other naming
services' refreshes.
"""

from __future__ import annotations

import os
import re
import threading
import urllib.error
import urllib.request
from typing import List, Optional

from ..butil.flags import define_flag, get_flag
from ..butil.logging_util import LOG
from ..client.naming_service import (NamingService, ServerNode,
                                     naming_registry, parse_server_line)

define_flag("remote_ns_wait_s", 30,
            "long-poll wait the watch:// naming service asks of the "
            "controller", lambda v: int(v) > 0)
define_flag("remote_ns_backup_dir", "",
            "mirror watch:// membership to files here and seed from "
            "them when the controller is down at startup ('' = off)",
            lambda v: True)

INDEX_HEADER = "X-Fleet-Index"


def _backup_path(url: str) -> Optional[str]:
    d = str(get_flag("remote_ns_backup_dir", "") or "")
    if not d:
        return None
    return os.path.join(d, re.sub(r"[^A-Za-z0-9_.-]", "_", url))


def parse_membership(text: str) -> List[ServerNode]:
    nodes = []
    for line in text.splitlines():
        node = parse_server_line(line)
        if node is not None:
            nodes.append(node)
    return nodes


class WatchNamingService(NamingService):
    """Blocking long-poll against a membership endpoint, with index
    resumption and degrade-to-file."""

    def __init__(self):
        super().__init__()
        self.refresh_interval_s = 0        # we own our cadence
        self._url = ""
        self._index = 0
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()

    # -- NamingService ----------------------------------------------------

    def start(self, url_path: str) -> int:
        # url_path is everything after "watch://"
        if not url_path or "/" not in url_path and ":" not in url_path:
            return -1
        if "/" not in url_path.split("?", 1)[0]:
            # bare host:port — without a path the long-poll selector
            # would be "?index=..." (no leading '/'), a malformed
            # origin-form that strict servers reject; poll the root.
            # The slash goes BEFORE any query string.
            if "?" in url_path:
                host, q = url_path.split("?", 1)
                url_path = host + "/?" + q
            else:
                url_path += "/"
        self._url = "http://" + url_path
        self._thread = threading.Thread(
            target=self._watch_loop, name=f"ns-watch {url_path}",
            daemon=True)
        self._thread.start()
        return 0

    def stop(self) -> None:
        super().stop()
        self._wake.set()

    def fetch_servers(self):
        return self.current

    # -- watch loop -------------------------------------------------------

    def _fetch(self, wait_s: int) -> Optional[List[ServerNode]]:
        """One blocking query; returns the list or None on failure.
        Advances the resumption index from the response header."""
        sep = "&" if "?" in self._url else "?"
        url = f"{self._url}{sep}index={self._index}&wait={wait_s}s"
        req = urllib.request.Request(url, headers={
            "Accept": "text/plain"})
        # the controller may hold the request for the full wait; pad the
        # socket timeout so a healthy long-poll never trips it
        with urllib.request.urlopen(req, timeout=wait_s + 10) as resp:
            body = resp.read().decode("utf-8", "replace")
            idx = resp.headers.get(INDEX_HEADER)
            if idx is not None:
                try:
                    self._index = max(self._index, int(idx))
                except ValueError:
                    pass
            else:
                self._index = -1          # snapshot endpoint: poll mode
            return parse_membership(body)

    def _watch_loop(self) -> None:
        import time as _time
        backoff = 1.0
        seeded = False
        while not self._stopped:
            wait_s = int(get_flag("remote_ns_wait_s", 30))
            prev_index = self._index
            t0 = _time.monotonic()
            try:
                nodes = self._fetch(wait_s)
            except (urllib.error.URLError, OSError, ValueError) as e:
                if not seeded and self._last is None:
                    self._seed_from_backup()
                    seeded = True
                LOG.warning("watch NS %s unreachable (%s); retry in %.0fs",
                            self._url, e, backoff)
                self._wake.wait(backoff)
                backoff = min(backoff * 2, 30.0)
                continue
            backoff = 1.0
            if nodes is not None:
                self.push(nodes)
                self._mirror_to_backup(nodes)
            if self._index < 0:
                # plain snapshot endpoint — no server-side blocking, so
                # pace the polling ourselves
                self._wake.wait(
                    float(get_flag("remote_ns_snapshot_poll_s", 5.0)))
            elif self._index == prev_index \
                    and _time.monotonic() - t0 < 1.0:
                # a controller that claims indexed semantics but answers
                # instantly without advancing would otherwise be
                # hammered at one request per RTT — floor the cadence
                self._wake.wait(1.0)

    # -- degrade-to-file --------------------------------------------------

    def _mirror_to_backup(self, nodes: List[ServerNode]) -> None:
        path = _backup_path(self._url)
        if path is None:
            return
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write("\n".join(str(n) for n in nodes) + "\n")
            os.replace(tmp, path)
        except OSError as e:
            LOG.warning("watch NS backup write failed: %s", e)

    def _seed_from_backup(self) -> None:
        path = _backup_path(self._url)
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                nodes = parse_membership(f.read())
        except OSError:
            return
        if nodes:
            LOG.warning("watch NS %s: seeding %d servers from backup %s",
                        self._url, len(nodes), path)
            self.push(nodes)


define_flag("remote_ns_snapshot_poll_s", 5.0,
            "poll period for watch:// endpoints that answer without an "
            "index header", lambda v: float(v) > 0)

naming_registry().register("watch", WatchNamingService)
