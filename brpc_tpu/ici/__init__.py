"""ICI data plane — device-resident RPC payloads.

The TPU-native equivalent of the reference's RDMA stack
(/root/reference/src/brpc/rdma/): tensors stay in HBM end to end; the
TCP connection carries only *descriptors* (and acks), the way an RDMA
wire message carries rkeys instead of payload bytes.

Layers (mirroring rdma_endpoint.h / block_pool.cpp roles):

- :mod:`fabric`    — how posted tensors move between peers
  (in-process registry → ``jax.device_put`` over ICI; optional
  ``jax.experimental.transfer`` pull server for cross-host).
- :mod:`block_pool`— bounded, recycled HBM landing buffers for the
  host-staged fallback path (registered-memory analogue).
- :mod:`endpoint`  — per-connection window+ack flow control, descriptor
  lifecycle, the "TICI" ack frame protocol.
- :mod:`attachment`— the user-facing DeviceAttachment object.
"""

from .attachment import DeviceAttachment
from .block_pool import DeviceBlockPool, default_device_pool
from .endpoint import IciEndpoint, ici_enabled
from .fabric import local_domain_id

__all__ = [
    "DeviceAttachment", "DeviceBlockPool", "default_device_pool",
    "IciEndpoint", "ici_enabled", "local_domain_id",
]
