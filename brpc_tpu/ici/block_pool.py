"""Device block pool — bounded, recycled HBM landing buffers.

Role parity with /root/reference/src/brpc/rdma/block_pool.cpp: RDMA
needs payload memory drawn from a *registered*, bounded region so the
NIC can DMA into it without per-transfer registration.  The JAX
equivalent of "registered memory" is a live device buffer the runtime
already owns; the equivalent of block recycling is **buffer donation** —
a donated input's HBM is reused for the output, so landing a host
payload into a pooled block writes the *same* HBM pages every time
instead of churning the allocator.

Lifecycle is EXPLICIT, like RDMA registered buffers: the consumer calls
:meth:`DeviceBlockPool.recycle` when a landed buffer's contents are no
longer referenced — applications with repeated same-shape transfers
(parameter servers pushing fixed-shape shards) get page-stable reuse
this way.  The RPC fallback path itself uses plain ``device_put`` (no
recycling opportunity: the receiver owns the tensor indefinitely); the
pure ICI path never lands bytes at all (descriptors are redeemed
device-side, endpoint.py).

Why byte-granular HBM slicing is *not* re-expressed here: XLA owns HBM
through its BFC allocator and device arrays are immutable; what the
block pool can honestly guarantee on TPU is (a) a bounded data-plane
footprint and (b) page-stable recycling via donation — both are what
rdma/block_pool exists for.  The chain/ref mechanics stay in IOBuf.
"""

from __future__ import annotations

import functools
import threading
from collections import defaultdict, deque
from typing import Any, Deque, Dict, Optional, Tuple

from ..butil.iobuf import Block, BlockPool
from ..butil.logging_util import LOG

DEFAULT_POOL_BYTES = 256 * 1024 * 1024      # data-plane HBM cap


class DeviceBlock(Block):
    """A Block whose storage is a device (HBM) array of raw bytes.

    ``array`` is a flat uint8 jax.Array of ``capacity`` bytes.  IOBuf
    can chain refs to it like any block; byte access (``view``) stages
    to the host explicitly and lazily — the data plane never calls it.
    """

    __slots__ = ("array",)

    def __init__(self, array: Any, nbytes: int,
                 pool: Optional["DeviceBlockPool"] = None):
        # Block.data must be len()-able; the host mirror is created only
        # if someone byte-reads the block (portal/debug paths).
        self.array = array
        super().__init__(_LazyHostMirror(self, nbytes), nbytes, pool)

    def view(self, offset: int, length: int):
        return memoryview(self.data.materialize())[offset:offset + length]


class _LazyHostMirror:
    """len()-able placeholder that stages device bytes to host on first
    real access (explicit D2H, never implicit)."""

    __slots__ = ("_block", "_host", "_nbytes")

    def __init__(self, block: DeviceBlock, nbytes: int):
        self._block = block
        self._host = None
        self._nbytes = nbytes

    def __len__(self) -> int:
        return self._nbytes

    def materialize(self) -> bytes:
        if self._host is None:
            import numpy as np
            self._host = np.asarray(self._block.array).tobytes()
        return self._host


@functools.lru_cache(maxsize=64)
def _land_fn(nbytes: int):
    """jit'd landing kernel: donated dst ⇒ XLA writes src's bytes into
    dst's existing HBM pages (input-output aliasing)."""
    import jax

    def land(dst, src):
        return jax.lax.dynamic_update_slice(dst, src, (0,))

    return jax.jit(land, donate_argnums=(0,))


class DeviceBlockPool(BlockPool):
    """Free-listed HBM byte-buffer pool with donation-based recycling.

    ``land(host_view)`` → uint8 device array of exactly ``len(view)``
    bytes, drawn from (and returned to) per-size free lists.  Repeated
    same-size landings reuse the same HBM pages — assert-able via
    ``unsafe_buffer_pointer()`` stability, the test's proof of
    recycling.
    """

    def __init__(self, max_bytes: int = DEFAULT_POOL_BYTES,
                 device: Any = None):
        self.max_bytes = max_bytes
        self.device = device
        self._lock = threading.Lock()
        self._free: Dict[int, Deque[Any]] = defaultdict(deque)
        self.pooled_bytes = 0          # held in free lists
        self.landed = 0                # stats
        self.recycled = 0

    # -- BlockPool interface ----------------------------------------------

    def allocate(self, capacity: int = 0) -> DeviceBlock:
        """Fresh zeroed device block (IOBuf interface compliance; the
        data plane uses :meth:`land` / :meth:`adopt`)."""
        import jax.numpy as jnp
        capacity = capacity or 8192
        arr = self._take(capacity)
        if arr is None:
            arr = jnp.zeros((capacity,), jnp.uint8)
            if self.device is not None:
                import jax
                arr = jax.device_put(arr, self.device)
        return DeviceBlock(arr, capacity, self)

    # -- data plane --------------------------------------------------------

    def land(self, host_view) -> Any:
        """One H2D DMA of ``host_view`` into a pooled (donated) buffer;
        returns a flat uint8 device array owning recycled HBM."""
        import jax
        import numpy as np

        src = np.frombuffer(host_view, dtype=np.uint8)
        n = src.nbytes
        self.landed += 1
        dst = self._take(n)
        if dst is None:
            import jax.numpy as jnp
            dst = jnp.zeros((n,), jnp.uint8)
            if self.device is not None:
                dst = jax.device_put(dst, self.device)
        else:
            self.recycled += 1
        return _land_fn(n)(dst, src)

    def recycle(self, array: Any) -> None:
        """Return a landed uint8 buffer for reuse (caller guarantees no
        live views; donation on next land makes aliasing impossible to
        observe anyway — the old array object is consumed)."""
        n = int(array.size)
        with self._lock:
            if self.pooled_bytes + n > self.max_bytes:
                return                    # over cap: let XLA free it
            self._free[n].append(array)
            self.pooled_bytes += n

    def _take(self, nbytes: int) -> Optional[Any]:
        with self._lock:
            lst = self._free.get(nbytes)
            if lst:
                self.pooled_bytes -= nbytes
                return lst.popleft()
        return None


_default_lock = threading.Lock()
_default_pool: Optional[DeviceBlockPool] = None


def default_device_pool() -> DeviceBlockPool:
    global _default_pool
    with _default_lock:
        if _default_pool is None:
            _default_pool = DeviceBlockPool()
        return _default_pool
