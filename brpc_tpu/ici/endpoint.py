"""IciEndpoint — per-connection device data plane with window+ack flow
control.

Role parity with /root/reference/src/brpc/rdma/rdma_endpoint.h:55-180:

- ``RdmaEndpoint`` rides an established TCP Socket and moves payloads
  out-of-band (verbs) while the socket carries control frames; the
  IciEndpoint rides a Socket and moves tensors out-of-band (fabric:
  in-process registry / jax transfer server) while the socket carries
  descriptors and acks.
- sliding window + explicit ack (``rdma_endpoint.cpp`` window/ack
  machinery): posting counts against ``ici_window_bytes``; the
  receiver's redemption sends a "TICI" ack frame; the ack returns
  credit and releases the posted tensor.
- completion notification through the event dispatcher
  (``rdma_endpoint.h:145-159`` comp_channel→epoll): acks arrive as
  normal epoll-driven frames on the connection.

Send-path decision (mirrors ``Socket::_rdma_state``): if the peer's
domain (learned from RpcMeta on the first exchange) is reachable by a
fabric ⇒ descriptor send, zero host copies; else ⇒ host-staged bytes in
the regular attachment (the ``use_rdma=false`` TCP fallback).
"""

from __future__ import annotations

import struct
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ..butil.flags import define_flag, get_flag
from ..butil.iobuf import IOBuf
from ..butil.logging_util import LOG
from ..butil.status import Errno
from ..protocol.base import (ParseResult, Protocol, ProtocolType,
                             register_protocol)
from .attachment import (KIND_INLINE, KIND_INPROC, KIND_TRANSFER,
                         DeviceAttachment, decode_descriptor,
                         encode_descriptor)
from .fabric import (in_process_fabric, local_domain_id,
                     peer_transfer_addr, transfer_fabric, transfer_ready)

define_flag("ici_enabled", True,
            "exchange ICI domains and send device attachments "
            "device-resident when peers share a fabric",
            validator=lambda v: True)       # reloadable on/off switch
define_flag("ici_window_bytes", 256 * 1024 * 1024,
            "max posted-but-unacked device payload bytes per connection",
            validator=lambda v: int(v) > 0)
define_flag("ici_desc_ttl_s", 120,
            "reclaim posted descriptors never redeemed after this many "
            "seconds", validator=lambda v: int(v) > 0)
define_flag("ici_transfer_enabled", False,
            "advertise a jax.experimental.transfer server so peers in "
            "OTHER processes pull device attachments directly (needs a "
            "runtime with the PJRT transfer hooks)",
            validator=lambda v: True)


def ici_enabled() -> bool:
    return bool(get_flag("ici_enabled", True))


class IciEndpoint:
    """Sender-side window accounting for one connection.

    One per Socket, created lazily on the first device-attachment send
    (≈ RdmaEndpoint construction on handshake)."""

    __slots__ = ("socket_id", "_lock", "_cond", "outstanding_bytes",
                 "posted_count", "acked_count", "__weakref__")

    def __init__(self, socket_id: int):
        self.socket_id = socket_id
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.outstanding_bytes = 0
        self.posted_count = 0
        self.acked_count = 0

    def post(self, array: Any, nbytes: int, timeout_s: float = 30.0,
             conn_key=None, fabric=None) -> Optional[int]:
        """Reserve window credit and post to the fabric (default: the
        in-process registry). Returns the descriptor id, or None if the
        window stayed full (the EOVERCROWDED analogue of a stuffed RDMA
        send queue)."""
        window = int(get_flag("ici_window_bytes", 256 * 1024 * 1024))
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self.outstanding_bytes + nbytes <= window
                or self.outstanding_bytes == 0,    # oversized payload:
                timeout=timeout_s)                 # admit alone
            if not ok:
                return None
            self.outstanding_bytes += nbytes
            self.posted_count += 1
        if fabric is None:
            fabric = in_process_fabric()
        return fabric.post(array, nbytes, self._on_release,
                           socket_id=self.socket_id, conn_key=conn_key)

    def _on_release(self, nbytes: int) -> None:
        with self._cond:
            self.outstanding_bytes -= nbytes
            self.acked_count += 1
            self._cond.notify_all()


_endpoints: "weakref.WeakSet[IciEndpoint]" = weakref.WeakSet()


def endpoint_of(sock) -> IciEndpoint:
    ep = sock.ici_endpoint
    if ep is None:
        ep = sock.ici_endpoint = IciEndpoint(sock.id)
        _endpoints.add(ep)
    return ep


def live_endpoints() -> List[IciEndpoint]:
    """All endpoints that ever posted (introspection: /vars, tests)."""
    return list(_endpoints)


# -- send path -------------------------------------------------------------

def _tensor_meta(array) -> Tuple[int, str, Tuple[int, ...]]:
    dtype = str(array.dtype)
    shape = tuple(int(s) for s in array.shape)
    nbytes = int(array.size) * array.dtype.itemsize
    return nbytes, dtype, shape


_LOOPBACK_HOSTS = ("127.0.0.1", "::1", "localhost")


def _is_local_peer(sock) -> bool:
    """In-process reach additionally requires a loopback peer address —
    a remote peer replaying our own domain token must not be able to
    steer us onto descriptors it can never redeem."""
    ep = sock.remote_side
    return ep is not None and str(getattr(ep, "host", "")) \
        in _LOOPBACK_HOSTS


_nonce_init_lock = threading.Lock()


def conn_nonce_of(sock) -> bytes:
    """The initiator's connection nonce: generated lazily on the client
    socket, carried in every ici-enabled request meta, pinned by the
    receiver from the first frame (first write wins — a later frame
    cannot re-bind an established connection's identity).  The lazy
    init is locked: two threads racing the first RPC on one shared
    'single' connection must agree on ONE nonce, or the server's pinned
    value desyncs from the client's for the connection's lifetime."""
    tok = sock.ici_conn_token
    if tok is None:
        import os as _os
        with _nonce_init_lock:
            tok = sock.ici_conn_token
            if tok is None:
                tok = sock.ici_conn_token = _os.urandom(8)
    return tok


def conn_key_of(sock):
    """Connection identity both ends compute identically.

    Preferred: the in-band connection nonce (``conn_nonce_of``) — it
    survives proxies and NAT, where the two TCP legs see different
    address pairs.  Fallback (nonce not yet exchanged): the unordered
    (local, remote) address pair.  Either way a descriptor binds to the
    exact connection it was posted for — a peer on another connection
    forging ids cannot redeem them (fabric.redeem enforces equality; an
    on-path observer who could replay the nonce could also spoof the
    address pair, so the threat model is unchanged).

    Version note: both ends of this framework send/pin the nonce, so
    descriptor exchange always keys on it; peers predating the nonce
    TLV are not supported for device attachments (byte attachments and
    all other traffic are unaffected)."""
    tok = sock.ici_conn_token
    if tok is not None:
        return tok
    local = sock.pin_local_side()
    remote = sock.remote_side
    if local is None or remote is None:
        return None

    def norm(h: str) -> str:
        # wildcard-bound listeners report 0.0.0.0/::; the in-process
        # path is loopback-gated, so both ends agree on 127.0.0.1
        return "127.0.0.1" if h in ("0.0.0.0", "::", "localhost") else h

    a = (norm(str(local.host)), int(local.port))
    b = (norm(str(remote.host)), int(remote.port))
    return (a, b) if a <= b else (b, a)


def prepare_send(sock, meta, array,
                 timeout_s: float = 30.0) -> Optional[IOBuf]:
    """Route a device attachment for sending: descriptor (device stays
    put) or host-staged bytes (fallback — also taken when ici is
    disabled by flag).  Returns the byte tail to append to the frame
    attachment (None for the descriptor path); sets ``meta.ici_desc``.
    Raises RuntimeError if the window is full past ``timeout_s``."""
    import jax

    if not isinstance(array, jax.Array):
        array = jax.numpy.asarray(array)
    nbytes, dtype, shape = _tensor_meta(array)
    if nbytes >= 1 << 32:
        # the descriptor codec carries nbytes as u32; refuse before any
        # window credit or D2H staging is spent
        raise RuntimeError(
            f"device attachment of {nbytes} bytes exceeds the 4GiB "
            "frame limit — shard it or use streaming")
    peer = sock.ici_peer_domain
    conn_key = conn_key_of(sock)
    if ici_enabled() and peer is not None \
            and in_process_fabric().can_reach(peer) \
            and _is_local_peer(sock) and conn_key is not None:
        desc_id = endpoint_of(sock).post(array, nbytes,
                                         timeout_s=timeout_s,
                                         conn_key=conn_key)
        if desc_id is None:
            raise RuntimeError(
                "ICI window full: posted device payloads awaiting ack "
                f"exceed ici_window_bytes on socket {sock.id}")
        meta.ici_desc = encode_descriptor(KIND_INPROC, desc_id, nbytes,
                                          dtype, shape)
        return None
    # cross-process: the peer advertises a transfer-server address and
    # this process has one too — the payload moves HBM→HBM via the PJRT
    # transfer engine, descriptors+acks ride the connection as usual
    peer_addr = peer_transfer_addr(peer) if ici_enabled() else None
    local_addr = transfer_ready() if peer_addr is not None else None
    if peer_addr is not None and local_addr is not None:
        desc_id = endpoint_of(sock).post(array, nbytes,
                                         timeout_s=timeout_s,
                                         conn_key=None,
                                         fabric=transfer_fabric())
        if desc_id is None:
            raise RuntimeError(
                "ICI window full: posted device payloads awaiting ack "
                f"exceed ici_window_bytes on socket {sock.id}")
        meta.ici_desc = encode_descriptor(KIND_TRANSFER, desc_id, nbytes,
                                          dtype, shape, extra=local_addr)
        return None
    # fallback: one explicit D2H, bytes ride the regular attachment
    from ..ops.device_ops import tensor_bytes
    data, dtype, shape = tensor_bytes(array)
    meta.ici_desc = encode_descriptor(KIND_INLINE, 0, nbytes, dtype, shape)
    tail = IOBuf()
    tail.append_user_data(data)
    return tail


def split_device_attachment(meta, attachment: IOBuf, socket_id: int
                            ) -> Tuple[IOBuf, Optional[DeviceAttachment]]:
    """Receiver side: if the frame carries a device attachment, split
    its byte tail (inline fallback) off ``attachment``.  Returns
    ``(user_attachment, device_attachment_or_None)`` — the user byte
    attachment keeps its own semantics."""
    if not meta.ici_desc:
        return attachment, None
    try:
        kind, desc_id, nbytes, dtype, shape, extra = \
            decode_descriptor(meta.ici_desc)
    except (struct.error, IndexError):
        return attachment, None          # malformed wire field: drop
    if kind not in (KIND_INLINE, KIND_INPROC, KIND_TRANSFER):
        return attachment, None          # unknown/unsupported kind: drop
    host_bytes: Optional[bytes] = None
    if kind == KIND_INLINE:
        if nbytes > len(attachment):
            return attachment, None      # malformed; drop the handle
        keep = len(attachment) - nbytes
        user_part = attachment.cutn(keep)    # device tail stays behind
        # zero-copy landing: a single-block tail (the native ingest
        # shape) passes a view straight through to np.frombuffer —
        # the only copy left on the inline path is the device put
        host_bytes = attachment.as_contiguous()[0]
        attachment = user_part
    return attachment, DeviceAttachment(
        kind, desc_id, nbytes, dtype, shape, socket_id=socket_id,
        host_bytes=host_bytes, extra=extra)


# -- redeem path -----------------------------------------------------------

def redeem_attachment(att: DeviceAttachment, device: Any = None):
    """Land the attachment as a device tensor; acks the poster for
    descriptor kinds (credit return rides the connection, arriving at
    the poster through the normal dispatcher — the comp_channel→epoll
    shape)."""
    import jax.numpy as jnp

    if att.kind == KIND_INPROC:
        from ..transport.socket import Socket
        sock = Socket.address(att._socket_id)
        key = conn_key_of(sock) if sock is not None else None
        arr = in_process_fabric().redeem(att.desc_id, device, conn_key=key)
        if arr is None:
            raise RuntimeError(
                f"ICI descriptor {att.desc_id} expired, already redeemed, "
                "or bound to a different connection")
        _send_ack(att._socket_id, (att.desc_id,))
        return arr
    if att.kind == KIND_TRANSFER:
        import jax
        fab = transfer_fabric()
        if fab is None:
            raise RuntimeError(
                "peer sent a transfer descriptor but this process has no "
                "transfer fabric (enable ici_transfer_enabled)")
        import numpy as _np
        spec = jax.ShapeDtypeStruct(att.shape, _np.dtype(att.dtype))
        out = fab.redeem(att._extra, att.desc_id, [spec])
        _send_ack(att._socket_id, (att.desc_id,))
        arr = out[0]
        if device is not None:
            arr = jax.device_put(arr, device)
        return arr
    # inline fallback: host bytes → device (one H2D)
    from ..ops.device_ops import bytes_to_tensor
    arr = bytes_to_tensor(att._host_bytes, att.dtype, att.shape,
                          device=device)
    return arr if device is not None else jnp.asarray(arr)


# -- "TICI" ack frames -----------------------------------------------------
#
#    [ "TICI" ][ u32 count ][ count × u64 desc_id ]

_ACK_MAGIC = b"TICI"
_ACK_HEADER = 8


def _send_ack(socket_id: int, desc_ids) -> None:
    """Queue the credit-return ids on the connection; they piggyback in
    front of the next outgoing frame (request/response traffic makes one
    imminent) or go out on the socket's ack-flush timer — one write and
    one poster-side epoll wake saved per redeem."""
    from ..transport.socket import Socket
    sock = Socket.address(socket_id)
    if sock is None or sock.failed:
        return                      # poster's TTL sweep will reclaim
    sock.queue_ack(desc_ids)


def _parse_ack(source: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    avail = len(source)
    if avail < _ACK_HEADER:
        got = source.fetch(min(4, avail))
        if _ACK_MAGIC.startswith(got):
            return ParseResult.not_enough_data()
        return ParseResult.try_others()
    head = source.fetch(_ACK_HEADER)
    if head[:4] != _ACK_MAGIC:
        return ParseResult.try_others()
    (count,) = struct.unpack_from("<I", head, 4)
    if count > 1 << 20:
        return ParseResult.absolutely_wrong()
    total = _ACK_HEADER + 8 * count
    if avail < total:
        return ParseResult.not_enough_data()
    source.pop_front(_ACK_HEADER)
    payload = source.fetch(8 * count)
    source.pop_front(8 * count)
    ids = struct.unpack(f"<{count}Q", payload)
    return ParseResult.make_message(ids)


def ack_unused(meta, socket_id: int) -> None:
    """Return window credit for a descriptor the receiver is DISCARDING
    without redeeming (stale retry response, admission reject, dropped
    late response) — otherwise the credit stays pinned until the TTL
    sweep."""
    if not meta.ici_desc:
        return
    try:
        kind, desc_id = decode_descriptor(meta.ici_desc)[:2]
    except (struct.error, IndexError):
        return
    if kind == KIND_INPROC:
        _send_ack(socket_id, (desc_id,))


def _process_ack(msg, sock, server=None) -> None:
    fabric = in_process_fabric()
    xfab = transfer_fabric()
    sid = getattr(sock, "id", None)
    for desc_id in msg:
        # bound to the posting connection: forged acks naming another
        # connection's descriptors are dropped
        if not fabric.release(desc_id, only_socket=sid) \
                and xfab is not None:
            xfab.release(desc_id, only_socket=sid)


ICI_ACK = Protocol(
    ProtocolType.ICI_ACK, "ici_ack", _parse_ack,
    process_request=lambda m, s, srv: _process_ack(m, s, srv),
    process_response=lambda m, s: _process_ack(m, s),
    process_inline=True,           # a few dict ops; never blocks
)
register_protocol(ICI_ACK)

from ..transport.input_messenger import client_messenger  # noqa: E402

client_messenger().add_handler(ICI_ACK)


# -- descriptor TTL sweep --------------------------------------------------

_sweep_started = False
_sweep_lock = threading.Lock()


def _ensure_sweeper() -> None:
    global _sweep_started
    with _sweep_lock:
        if _sweep_started:
            return
        _sweep_started = True
    from ..fiber.timer_thread import global_timer_thread

    def sweep():
        ttl = float(get_flag("ici_desc_ttl_s", 120))
        n = in_process_fabric().sweep_expired(ttl)
        if n:
            LOG.warning("ICI ttl sweep reclaimed %d descriptors", n)
        global_timer_thread().schedule(sweep, max(ttl / 4, 5.0))

    global_timer_thread().schedule(sweep, 30.0)


_ensure_sweeper()
