"""Transfer fabrics — how a posted device tensor reaches its redeemer.

Role parity with the RDMA verbs layer the reference wraps in
RdmaEndpoint (/root/reference/src/brpc/rdma/rdma_endpoint.h:55-180): the
fabric owns the actual payload movement; the endpoint (endpoint.py) owns
per-connection descriptors and flow control, exactly as RdmaEndpoint
owns QP state while ibverbs moves bytes.

Two fabrics:

- :class:`InProcessFabric` — peers share one JAX runtime (every chip of
  a single-controller slice).  ``post`` parks the array in a registry;
  ``redeem`` lands it on the target device with ``jax.device_put`` —
  on hardware that is an HBM→HBM DMA over ICI, never touching the host.
- :class:`JaxTransferFabric` — peers in different processes with a
  runtime that implements the PJRT cross-host transfer API
  (``jax.experimental.transfer``): ``post`` schedules an await_pull,
  ``redeem`` pulls from the peer's transfer server over ICI/DCN.
  Probed at import; unsupported runtimes fall back to host-staged
  attachments (the ``FLAGS_use_rdma=false`` analogue).

A *domain id* names the reach of a fabric: peers exchange domain ids in
RpcMeta and go device-resident only when an installed fabric can bridge
the two domains.

Trust model: the domain exchange is cooperative, like the reference's
plaintext RDMA handshake (rdma_endpoint.cpp TCP bring-up) — it guards
against *misconfiguration* (random 16-byte tokens can't collide by
accident), not against a malicious peer.  The damage a forged domain or
descriptor can do is bounded: redemption requires the redeemer to sit
on the SAME connection the descriptor was posted for (the mirrored
address-pair key checked in :meth:`InProcessFabric.redeem`), acks from
other connections are rejected, all of a connection's descriptors are
reclaimed when it dies, the in-process path additionally requires a
loopback peer address, and the TTL sweep is the backstop.  Authenticate
peers with the regular auth layer if the network is hostile.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..butil.flags import get_flag
from ..butil.logging_util import LOG

# 16-byte process-unique token: same token on both ends of a connection
# ⇒ both ends share this process's JAX runtime (loopback / same host
# single-controller), so the in-process fabric can bridge them.
_LOCAL_DOMAIN = os.urandom(16)


_domain_cache: Optional[bytes] = None
_domain_cache_addr: Optional[bytes] = None


def local_domain_id() -> bytes:
    """Domain advertised in every RpcMeta: the process token, plus this
    process's transfer-server address when the cross-process fabric is
    up (``token@address``) — peers in OTHER processes use the address to
    pull device payloads directly (≈ the GID/QPN the reference sends in
    its RDMA handshake).  Cached: this runs on every RPC, so the common
    flag-off case is one dict lookup."""
    global _domain_cache, _domain_cache_addr
    if not get_flag("ici_transfer_enabled", False) and _xfer is None:
        addr = None
    else:
        # probing transfer_ready() here also lazily starts the transfer
        # server on the first RPC after the flag flips on
        addr = transfer_ready()
    if _domain_cache is None or addr != _domain_cache_addr:
        _domain_cache_addr = addr
        _domain_cache = _LOCAL_DOMAIN + b"@" + addr if addr \
            else _LOCAL_DOMAIN
    return _domain_cache


def domain_token(domain: bytes) -> bytes:
    return domain.split(b"@", 1)[0]


def peer_transfer_addr(domain: Optional[bytes]) -> Optional[bytes]:
    """The transfer-server address inside a peer's domain id (None when
    the peer has no cross-process fabric)."""
    if not domain or b"@" not in domain:
        return None
    return domain.split(b"@", 1)[1] or None


class PostedEntry:
    __slots__ = ("array", "nbytes", "posted_at", "on_release", "socket_id",
                 "conn_key")

    def __init__(self, array: Any, nbytes: int, on_release=None,
                 socket_id: int = 0, conn_key=None):
        self.array = array
        self.nbytes = nbytes
        self.posted_at = time.monotonic()
        self.on_release = on_release
        self.socket_id = socket_id      # poster-local: binds acks
        self.conn_key = conn_key        # connection pair: binds redemption


class InProcessFabric:
    """Descriptor registry for peers sharing this JAX runtime.

    post/redeem/release mirror the send-side MR lifecycle of
    rdma/block_pool.cpp: a posted tensor is 'registered' (kept alive,
    counted against the window) until the peer acks redemption or the
    TTL sweep reclaims it.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._posted: Dict[int, PostedEntry] = {}
        self._next_id = int.from_bytes(os.urandom(4), "little") | 1
        self.posted_bytes = 0          # live accounting (all connections)

    def can_reach(self, peer_domain: bytes) -> bool:
        return domain_token(peer_domain) == _LOCAL_DOMAIN

    def post(self, array: Any, nbytes: int, on_release=None,
             socket_id: int = 0, conn_key=None) -> int:
        with self._lock:
            desc_id = self._next_id
            self._next_id += 1
            self._posted[desc_id] = PostedEntry(array, nbytes, on_release,
                                                socket_id, conn_key)
            self.posted_bytes += nbytes
        return desc_id

    def redeem(self, desc_id: int, device: Any = None,
               conn_key=None) -> Optional[Any]:
        """Fetch a posted tensor, landing it on ``device`` (None = leave
        where posted).  Same-device redemption is zero-copy (device_put
        is an alias); cross-device rides ICI on hardware.

        When the entry was posted with a connection key, the redeemer
        must present the SAME key (both ends of one TCP connection see
        the mirrored address pair) — a peer forging descriptor ids from
        another connection gets None, never another client's tensor."""
        with self._lock:
            entry = self._posted.get(desc_id)
        if entry is None:
            return None
        if entry.conn_key is not None and conn_key != entry.conn_key:
            LOG.warning("ICI redeem rejected: descriptor %d bound to a "
                        "different connection", desc_id)
            return None
        arr = entry.array
        if device is not None:
            import jax
            arr = jax.device_put(arr, device)
        return arr

    def take(self, desc_id: int, conn_key=None) -> Optional[Any]:
        """Redeem AND consume in one step — the one-shot import the KV
        transfer plane rides: the caller owns the array from here on
        and the registration is gone, so a second take of the same
        descriptor (double import, or an import racing the exporter's
        release) returns None instead of silently aliasing memory two
        owners now believe they hold exclusively.  Same-device, so the
        hand-over is an alias: zero data motion."""
        with self._lock:
            entry = self._posted.get(desc_id)
            if entry is None:
                return None
            if entry.conn_key is not None and conn_key != entry.conn_key:
                LOG.warning("ICI take rejected: descriptor %d bound to "
                            "a different connection", desc_id)
                return None
            del self._posted[desc_id]
            self.posted_bytes -= entry.nbytes
        if entry.on_release is not None:
            try:
                entry.on_release(entry.nbytes)
            except Exception:
                LOG.exception("ici on_release callback raised")
        return entry.array

    def release(self, desc_id: int,
                only_socket: Optional[int] = None) -> bool:
        """Drop the posted ref (descriptor acked or expired).
        ``only_socket`` binds the release to the connection the
        descriptor was posted on — forged acks naming another
        connection's descriptors are rejected (the same spoof class the
        stream layer guards against)."""
        with self._lock:
            entry = self._posted.get(desc_id)
            if entry is None:
                return False
            if only_socket is not None and entry.socket_id != only_socket:
                return False
            del self._posted[desc_id]
            self.posted_bytes -= entry.nbytes
        if entry.on_release is not None:
            try:
                entry.on_release(entry.nbytes)
            except Exception:
                LOG.exception("ici on_release callback raised")
        return True

    def release_socket(self, socket_id: int) -> int:
        """Reclaim every descriptor posted on a dead connection (≈ QP
        teardown reclaiming posted WRs on disconnect)."""
        with self._lock:
            stale = [i for i, e in self._posted.items()
                     if e.socket_id == socket_id]
        n = 0
        for desc_id in stale:
            if self.release(desc_id):
                n += 1
        return n

    def sweep_expired(self, ttl_s: float) -> int:
        """Reclaim descriptors never redeemed (peer died before acking)
        — the reference's QP teardown reclaiming posted WRs."""
        now = time.monotonic()
        with self._lock:
            stale = [i for i, e in self._posted.items()
                     if now - e.posted_at > ttl_s]
        for desc_id in stale:
            self.release(desc_id)
        return len(stale)

    @property
    def live_descriptors(self) -> int:
        with self._lock:
            return len(self._posted)


class JaxTransferFabric:
    """Cross-host pull fabric over ``jax.experimental.transfer``.

    The PJRT transfer server is the runtime's RDMA engine: the sender
    schedules ``await_pull(uuid, arrays)`` and the receiver's
    ``TransferConnection.pull`` moves HBM→HBM over ICI/DCN.  Domain id =
    token + server address; redeem connects to the address inside the
    peer's descriptor.  Post/release mirror the in-process registry so
    window accounting and TICI acks work identically."""

    def __init__(self):
        self._server = None
        self._addr = b""
        self._conns: Dict[bytes, Any] = {}
        self._lock = threading.Lock()
        self._posted: Dict[int, PostedEntry] = {}
        self._next_id = int.from_bytes(os.urandom(4), "little") | 1

    @staticmethod
    def supported() -> bool:
        """One cached loopback probe — several installed runtimes ship
        the Python API but not the PJRT hooks underneath."""
        global _TRANSFER_SUPPORTED
        if _TRANSFER_SUPPORTED is None:
            _TRANSFER_SUPPORTED = _probe_transfer_runtime()
        return _TRANSFER_SUPPORTED

    def start(self) -> bool:
        if self._server is not None:
            return True
        try:
            import jax
            from jax.experimental import transfer
            self._server = transfer.start_transfer_server(
                jax.devices()[0].client)
            self._addr = self._server.address().encode()
            return True
        except Exception as e:
            LOG.warning("transfer server unavailable: %s", e)
            return False

    @property
    def address(self) -> bytes:
        return self._addr

    def post(self, array: Any, nbytes: int, on_release=None,
             socket_id: int = 0, conn_key=None) -> int:
        """Schedule an await_pull; returns the descriptor uuid the peer
        pulls with (same contract as InProcessFabric.post)."""
        with self._lock:
            uuid = self._next_id
            self._next_id += 1
            self._posted[uuid] = PostedEntry(array, nbytes, on_release,
                                             socket_id, conn_key)
        self._server.await_pull(uuid, [array])
        return uuid

    def redeem(self, peer_addr: bytes, uuid: int, specs):
        with self._lock:
            conn = self._conns.get(peer_addr)
            if conn is None:
                conn = self._server.connect(peer_addr.decode())
                self._conns[peer_addr] = conn
        return conn.pull(uuid, specs)

    def release(self, uuid: int, only_socket: Optional[int] = None) -> bool:
        """Ack arrived: drop the local ref + return window credit."""
        with self._lock:
            entry = self._posted.get(uuid)
            if entry is None:
                return False
            if only_socket is not None and entry.socket_id != only_socket:
                return False
            del self._posted[uuid]
        if entry.on_release is not None:
            try:
                entry.on_release(entry.nbytes)
            except Exception:
                LOG.exception("ici on_release callback raised")
        return True

    @property
    def live_descriptors(self) -> int:
        with self._lock:
            return len(self._posted)


_TRANSFER_SUPPORTED: Optional[bool] = None


def _probe_transfer_runtime() -> bool:
    try:
        import jax
        import jax.numpy as jnp
        from jax.experimental import transfer
        srv = transfer.start_transfer_server(jax.devices()[0].client)
        x = jnp.zeros((8,), jnp.float32)
        srv.await_pull(1, [x])
        conn = srv.connect(srv.address())
        out = conn.pull(1, [jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                 sharding=x.sharding)])
        return bool(out[0].shape == x.shape)
    except Exception:
        return False


_fabric_lock = threading.Lock()
_in_process: Optional[InProcessFabric] = None
_xfer: Optional[JaxTransferFabric] = None
_xfer_tried = False


def in_process_fabric() -> InProcessFabric:
    global _in_process
    with _fabric_lock:
        if _in_process is None:
            _in_process = InProcessFabric()
        return _in_process


def transfer_fabric() -> Optional[JaxTransferFabric]:
    """The process's cross-process fabric, started on first use; None
    when the runtime can't support it or the flag is off.  Tests may
    install a stand-in via set_transfer_fabric()."""
    global _xfer, _xfer_tried
    if not get_flag("ici_transfer_enabled", False):
        return _xfer            # explicit installs (tests) still count
    with _fabric_lock:
        if _xfer is not None or _xfer_tried:
            return _xfer
        _xfer_tried = True
    if not JaxTransferFabric.supported():
        LOG.warning("ici_transfer_enabled but the runtime lacks the "
                    "PJRT transfer hooks; device attachments fall back "
                    "to host staging across processes")
        return None
    f = JaxTransferFabric()
    if not f.start():
        return None
    with _fabric_lock:
        _xfer = f
    return _xfer


def set_transfer_fabric(f) -> None:
    """Install a transfer fabric explicitly (tests / custom runtimes)."""
    global _xfer, _xfer_tried
    with _fabric_lock:
        _xfer = f
        _xfer_tried = True


def transfer_ready() -> Optional[bytes]:
    """This process's transfer address, when the fabric is live."""
    f = transfer_fabric()
    return f.address if f is not None and f.address else None
