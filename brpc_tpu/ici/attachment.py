"""DeviceAttachment — a tensor riding an RPC without leaving the device.

The user-facing object on both ends:

- sender: ``cntl.request_device_attachment = jax_array`` (client) or
  ``cntl.response_device_attachment = jax_array`` (server);
- receiver: ``cntl.request_device_attachment.tensor(device=...)``.

On the wire it is either a *descriptor* (peer reachable through a
fabric — payload stays in HBM, ≈ the RDMA rkey the reference sends in
rdma_endpoint.cpp) or raw bytes in the regular attachment (fallback,
≈ ``use_rdma=false``).  The descriptor codec lives here; the transfer +
flow control live in endpoint.py.
"""

from __future__ import annotations

import struct
from typing import Any, Optional, Tuple

# descriptor kinds
KIND_INLINE = 0          # payload rides the byte attachment (fallback)
KIND_INPROC = 1          # redeem from this process's registry
KIND_TRANSFER = 2        # pull from peer's jax transfer server


def encode_descriptor(kind: int, desc_id: int, nbytes: int, dtype: str,
                      shape: Tuple[int, ...], extra: bytes = b"") -> bytes:
    d = dtype.encode()
    out = struct.pack("<BQI", kind, desc_id, nbytes)
    out += bytes([len(d)]) + d
    out += bytes([len(shape)]) + b"".join(
        struct.pack("<Q", s) for s in shape)
    out += struct.pack("<H", len(extra)) + extra
    return out


def decode_descriptor(data: bytes):
    kind, desc_id, nbytes = struct.unpack_from("<BQI", data)
    off = 13
    dlen = data[off]; off += 1
    dtype = data[off:off + dlen].decode(); off += dlen
    ndim = data[off]; off += 1
    shape = tuple(struct.unpack_from("<Q", data, off + 8 * i)[0]
                  for i in range(ndim))
    off += 8 * ndim
    (elen,) = struct.unpack_from("<H", data, off); off += 2
    extra = data[off:off + elen]
    return kind, desc_id, nbytes, dtype, shape, extra


class DeviceAttachment:
    """Received tensor handle: redeems lazily, at most once, and acks
    the sender on redemption (the ack returns window credit,
    endpoint.py)."""

    __slots__ = ("kind", "desc_id", "nbytes", "dtype", "shape",
                 "_array", "_host_bytes", "_socket_id", "_redeemed",
                 "_extra")

    def __init__(self, kind: int, desc_id: int, nbytes: int, dtype: str,
                 shape: Tuple[int, ...], socket_id: int = 0,
                 host_bytes: Optional[bytes] = None, extra: bytes = b""):
        self.kind = kind
        self.desc_id = desc_id
        self.nbytes = nbytes
        self.dtype = dtype
        self.shape = shape
        self._array = None
        self._host_bytes = host_bytes
        self._socket_id = socket_id
        self._redeemed = False
        self._extra = extra

    def __len__(self) -> int:
        return self.nbytes

    @property
    def device_resident(self) -> bool:
        return self.kind != KIND_INLINE

    def tensor(self, device: Any = None):
        """The attached tensor, landed on ``device`` (None: wherever the
        fabric left it / the default device for the fallback path)."""
        if self._array is not None:
            if device is not None:
                import jax
                return jax.device_put(self._array, device)
            return self._array
        from .endpoint import redeem_attachment
        self._array = redeem_attachment(self, device)
        self._redeemed = True
        return self._array

    def numpy(self):
        """Host copy (explicit D2H — debugging / host consumers)."""
        import numpy as np
        return np.asarray(self.tensor())

    def settle(self) -> None:
        """Ack the poster NOW if the attachment was never redeemed.
        The server calls this right before writing the response so the
        credit-return frame always PRECEDES the response on the wire —
        the invariant the client's sync fast lane relies on.  Handlers
        must redeem (``tensor()``) before finishing the RPC; a handle
        kept past the response is settled here and redeems no more."""
        if self.kind in (KIND_INPROC, KIND_TRANSFER) and not self._redeemed:
            self._redeemed = True
            from .endpoint import _send_ack
            _send_ack(self._socket_id, (self.desc_id,))

    def __del__(self):
        # dropped without redemption (user ignored the attachment):
        # return the poster's window credit instead of pinning it until
        # the TTL sweep
        if self.kind in (KIND_INPROC, KIND_TRANSFER) and not self._redeemed:
            try:
                from .endpoint import _send_ack
                _send_ack(self._socket_id, (self.desc_id,))
            except Exception:
                pass                     # interpreter teardown etc.
