"""brpc_tpu — a TPU-native RPC and service framework.

A from-scratch re-design of the capabilities of Apache brpc (reference:
/root/reference) for TPU pods:

- ``butil``   : base library — zero-copy chained buffers (IOBuf) over
                pluggable block pools (host bytearray slabs or HBM-resident
                device slabs), versioned-id resource pools, read-mostly
                double-buffered data, endpoints that address both ip:port
                and mesh device coordinates.
- ``bvar``    : thread-local-aggregated metrics (write O(1), read merges),
                windows, percentiles, latency recorders, Prometheus export.
- ``fiber``   : the task runtime (M:N-shaped scheduler API; Python engine on
                worker threads, native C++ engine for the hot paths),
                versioned correlation ids, execution queues, timer thread.
- ``transport``: Socket abstraction with wait-free write queue + keep-write
                draining, event dispatcher, in-process loopback, TCP, and the
                ICI device transport (device-resident payload path).
- ``protocol``: pluggable struct-of-callbacks protocol registry; framed
                pb-RPC (tpu_std), HTTP/1.1 + JSON bridge, streaming.
- ``server`` / ``client``: Server, Channel/Controller with timeout/retry/
                backup-request/cancel, naming services, load balancers,
                circuit breakers, Parallel/Partition/Selective channels.
- ``parallel``: mesh collectives layer (shard_map/ppermute rings) the combo
                channels and streaming map onto when peers form an ICI mesh.
- ``ops``     : pallas TPU kernels (checksum, chunked copy, ring transfer).
- ``models``  : flagship workloads (sharded embedding parameter-server).
- ``kv``      : KV-cache transfer subsystem — cache pages as first-class
                transferable objects (export/describe/import/release),
                lane-picking KvTransport, disaggregated prefill/decode
                serving tiers.

Nothing here is a port: architecture follows SURVEY.md, not the reference's
source. Reference citations in docstrings are for capability parity only.
"""

__version__ = "0.1.0"
