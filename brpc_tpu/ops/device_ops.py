"""Device-side ops for the payload path.

- :func:`checksum_u32` — pallas TPU kernel (VPU wrapping-sum fold)
  computing a 32-bit checksum of a device-resident payload without
  staging it to the host; the device analogue of butil's crc32c on the
  wire path (/root/reference/src/butil/crc32c.cc — capability, not
  algorithm).
- :func:`embedding_bag` — fused lookup+mean for the parameter-server
  model family.
- :func:`tensor_bytes` / :func:`bytes_to_tensor` — tensor ↔ wire bytes
  for carrying device payloads in RPC attachments.

Kernels run natively on TPU and in interpret mode elsewhere (tests run on
the CPU backend).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

_LANES = 128
_SUBLANES = 8


def _on_tpu() -> bool:
    import jax
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def _checksum_fn(padded_rows: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block_rows = padded_rows
    for cand in (512, 256, 64, _SUBLANES):
        if padded_rows % cand == 0:
            block_rows = cand
            break
    grid = (padded_rows // block_rows,)

    def kernel(x_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            out_ref[0, 0] = jnp.int32(0)

        # wrapping i32 sum on the VPU (mosaic has no unsigned
        # reductions; two's-complement wrap gives the same 32 bits);
        # grid steps are sequential on TPU so accumulating into the
        # SMEM scalar is well-defined
        out_ref[0, 0] = out_ref[0, 0] + jnp.sum(x_ref[...],
                                                dtype=jnp.int32)

    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        grid=grid,
        in_specs=[pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))],
        # scalar accumulator lives in SMEM: VMEM cannot take scalar stores
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        interpret=interpret,
    )
    return jax.jit(call)


def checksum_u32(x) -> int:
    """32-bit xor-fold checksum of an arbitrary device array (its raw
    bytes, zero-padded to a lane multiple)."""
    import jax
    import jax.numpy as jnp

    arr = jnp.atleast_1d(jnp.asarray(x))
    if arr.dtype.itemsize != 4:
        # non-32-bit payloads are checksummed via their f32 widening —
        # integrity of the values, not of a particular bit layout
        arr = arr.astype(jnp.float32)
    raw = jnp.ravel(jax.lax.bitcast_convert_type(arr, jnp.int32))
    n = raw.size
    rows = max(_SUBLANES, -(-n // _LANES))
    rows = -(-rows // _SUBLANES) * _SUBLANES
    padded = jnp.zeros((rows * _LANES,), jnp.int32).at[:n].set(raw)
    padded = padded.reshape(rows, _LANES)
    fn = _checksum_fn(rows, interpret=not _on_tpu())
    return int(np.uint32(fn(padded)[0, 0]))


@functools.lru_cache(maxsize=None)
def _embedding_bag_fn():
    import jax
    import jax.numpy as jnp

    def bag(table, ids):
        # (batch, slots) ids → mean of rows; XLA fuses gather+reduce and
        # inserts the collective when `table` is vocab-sharded
        emb = jnp.take(table, ids, axis=0)        # (b, s, d)
        return emb.mean(axis=1)

    return jax.jit(bag)


def embedding_bag(table, ids):
    """Fused multi-slot embedding lookup + mean pool (the parameter-server
    hot op). Works on replicated or vocab-sharded tables."""
    return _embedding_bag_fn()(table, ids)


def tensor_bytes(x) -> Tuple[memoryview, str, Tuple[int, ...]]:
    """Device/host array → (raw buffer, dtype str, shape) for shipping
    as an RPC attachment (zero serializer in the path).  The buffer is
    a read-only view over the host array's storage — no tobytes copy;
    the view keeps the array alive.  CONTRACT: when ``x`` is already a
    host numpy array, the view ALIASES it (readonly blocks writes
    through the view, not through the array) — the caller must not
    mutate ``x`` until the RPC's write completes; device arrays are
    immune (``np.asarray`` lands them in a fresh host copy)."""
    host = np.ascontiguousarray(np.asarray(x))
    return memoryview(host).cast("B").toreadonly(), \
        str(host.dtype), tuple(host.shape)


def bytes_to_tensor(data, dtype: str, shape: Tuple[int, ...],
                    device=None):
    """Wire buffer (bytes or any contiguous view) → host/device tensor.
    np.frombuffer aliases the storage — the landing copy is the device
    put (or nothing, for host consumers)."""
    arr = np.frombuffer(data, dtype=np.dtype(dtype)).reshape(shape)
    if device is None:
        return arr
    import jax
    return jax.device_put(arr, device)
