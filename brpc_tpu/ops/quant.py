"""Weight-only int8 quantization for the serving path.

Single-token decode is HBM-bandwidth-bound: every step streams every
weight matrix through the MXU once, so halving the bytes ≈ halves the
step time.  Symmetric per-output-channel int8 (scale = amax/127 over
the contraction axis) keeps matmul outputs within ~0.5% of bf16 for
transformer-scale weights; the int8→bf16 convert fuses into the
matmul's RHS load under XLA, so no dequantized copy ever materializes.

TPU-first notes: int8 values are exactly representable in bf16, so the
compute path stays on the MXU's bf16 pipeline (no XLA int8-matmul
special-casing needed); scales apply per OUTPUT channel, a cheap fused
multiply on the (..., n) result.

Reference scope note: the reference (an RPC framework) has no model
serving layer; this module serves the framework's own LM family
(models/transformer_lm.py), the capability its PS/LM examples build on.
"""

from __future__ import annotations

from typing import Any, NamedTuple


class QuantTensor(NamedTuple):
    """int8 weights + per-output-channel scales (a pytree node)."""
    q: Any          # int8, same shape as the original weight
    s: Any          # float32, shape = (out_channels,)

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self) -> int:
        return int(self.q.size) + int(self.s.size) * 4


def quantize_int8(w, contract_axis: int = 0) -> QuantTensor:
    """Symmetric per-channel quantization of a 2D weight.

    ``contract_axis`` is the axis the matmul reduces over (0 for the
    ``x @ w`` layout used throughout the LM); scales are computed per
    channel of the OTHER axis so each output feature keeps its own
    dynamic range.  Idempotent: an already-quantized tensor passes
    through unchanged."""
    import jax.numpy as jnp

    if isinstance(w, QuantTensor):
        return w
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=contract_axis, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return QuantTensor(q=q, s=scale.squeeze(contract_axis))


def qmatmul(x, w):
    """``x @ w`` where ``w`` is a QuantTensor (or a plain array, for
    call sites that handle both).  x is taken to bf16 (the MXU input
    dtype); the result is f32 with scales applied per output channel."""
    import jax.numpy as jnp

    if not isinstance(w, QuantTensor):
        return (x.astype(jnp.bfloat16)
                @ jnp.asarray(w).astype(jnp.bfloat16)).astype(jnp.float32)
    y = (x.astype(jnp.bfloat16)
         @ w.q.astype(jnp.bfloat16)).astype(jnp.float32)
    return y * w.s


def dequantize(w):
    """Materialize the f32 weight (tests / fallback paths)."""
    import jax.numpy as jnp
    if not isinstance(w, QuantTensor):
        return w
    return w.q.astype(jnp.float32) * w.s


_LM_QUANT_KEYS = ("wqkv", "wo", "w1", "w2")


def quantize_lm_params(params: dict) -> dict:
    """Quantize a TransformerLM parameter tree for serving: the block
    matmul weights and the unembedding go int8; embeddings (gather, not
    matmul), layernorm gains, and MoE trees stay as-is.  Returns a new
    tree; the original is untouched.

    Both layer layouts are served: unrolled ``blk{i}`` trees and
    stacked ``scan_layers`` trees (weights (depth, in, out) quantize
    with the contraction on axis 1, giving per-(layer, out-channel)
    scales — ``lax.scan`` then slices each layer's QuantTensor off the
    leading axis)."""
    out: dict = {}
    for key, val in params.items():
        if key == "unembed":
            out[key] = quantize_int8(val)
        elif key == "blocks" and isinstance(val, dict):
            out[key] = {
                bk: (quantize_int8(bv, contract_axis=1)
                     if bk in _LM_QUANT_KEYS else bv)
                for bk, bv in val.items()}
        elif key.startswith("blk") and isinstance(val, dict):
            blk = {}
            for bk, bv in val.items():
                blk[bk] = quantize_int8(bv) if bk in _LM_QUANT_KEYS \
                    else bv
            out[key] = blk
        else:
            out[key] = val
    return out


def quantized_nbytes(params: dict) -> int:
    """Total parameter bytes (QuantTensor-aware) — the serving-memory
    story a /status page or capacity planner reads."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, QuantTensor)):
        if isinstance(leaf, QuantTensor):
            total += leaf.nbytes
        else:
            total += int(leaf.size) * leaf.dtype.itemsize
    return total
