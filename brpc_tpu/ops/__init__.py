"""TPU kernels (pallas) + device-side utility ops.

The hot ops of the transport/data path, written as pallas TPU kernels
with jnp fallbacks (interpret mode on CPU): payload checksums for
integrity of device-resident frames, fused embedding-bag lookup, and the
block-copy primitive behind the HBM payload pool.
"""

from .device_ops import checksum_u32, embedding_bag, tensor_bytes

__all__ = ["checksum_u32", "embedding_bag", "tensor_bytes"]
