"""Pallas flash attention — the hot-op kernel for the dense models.

No reference counterpart (the reference is an RPC framework; its hot
path is framing/IO).  This is the TPU-first answer to SURVEY §5.7's
"blockwise attention" prescription, written against the Pallas TPU
playbook (/opt/skills/guides/pallas_guide.md):

- forward: grid (b, h, q_blocks, k_blocks), innermost dimension
  "arbitrary" — VMEM scratch (running max / denominator / accumulator)
  persists across the k-block sweep, the classic online-softmax flash
  schedule with O(seq) memory per q block; also emits the per-row
  logsumexp for the backward pass;
- backward: FUSED flash kernels too — a dq kernel sweeping k blocks and
  a dk/dv kernel sweeping q blocks, both recomputing p = exp(s - lse)
  blockwise from the saved logsumexp (the standard flash backward), so
  training memory is O(seq) as well, never O(seq²);
- q·kᵀ / p·v / ds·k / dsᵀ·q on the MXU via dot_general with
  ``preferred_element_type=float32``; masking from ``broadcasted_iota``
  (TPU-safe, pitfall #4); causal blocks above the diagonal predicated
  off with ``pl.when``;
- head dim padded to the 128 lane, sequence padded to lcm(bq, bk); pad
  keys are masked in-kernel; pad q rows are gradient-safe because their
  cotangents and dd are zero (they do attend real keys forward, but the
  rows are sliced off and contribute nothing backward).  The lse/dd
  blocks use a 1-wide lane (legal: equal to the array's last dim —
  verified compiling and running on real TPU hardware);
- ``interpret=True`` automatically off-TPU, so the same code paths are
  unit-tested on the CPU mesh.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# -- forward ----------------------------------------------------------------

def _fwd_kernel(*refs, scale: float, causal: bool, bq: int, bk: int,
                seq_len: int):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if causal:
        # triangular causal grid: prefetched arrays carry the
        # linearized (iq, ik<=iq) pair per step
        (iq_ref, ik_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
        t = pl.program_id(2)
        iq, ik = iq_ref[t], ik_ref[t]
        is_last = ik == iq
    else:
        (q_ref, k_ref, v_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
        iq, ik = pl.program_id(2), pl.program_id(3)
        is_last = ik == pl.num_programs(3) - 1

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q0 = iq * bq
    k0 = ik * bk
    # the causal grid is triangular — blocks above the diagonal are
    # statically absent; only the padded k tail needs skipping (and on
    # the triangular grid k0 <= q0 < seq_len always holds)
    live = k0 < seq_len

    @pl.when(live)
    def _step():
        # matmuls keep the INPUT dtype (bf16 stays bf16 — upcasting to
        # f32 first starves the MXU; measured ~1.7x on the whole
        # kernel) and accumulate in f32
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale        # (bq, bk)
        kpos = k0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = kpos < seq_len
        if causal:
            qpos = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            mask = jnp.logical_and(mask, qpos >= kpos)
        s = jnp.where(mask, s, -1e30)
        m_prev = m_scr[:]                                      # (bq, 1)
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[:] = l_scr[:] * corr + p.sum(axis=-1, keepdims=True)
        m_scr[:] = m_new
        # p rides the MXU in the value dtype (the flash-standard bf16
        # cast; exact when v is f32)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # (bq, d)

    @pl.when(is_last)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)
        # logsumexp per row.  The dead-row guard only matters if a
        # future mask can fully mask a LIVE row (today even pad q rows
        # attend k block 0): exp(s - 1e30) underflows to zero then.
        lse = m_scr[:] + jnp.log(l)                            # (bq, 1)
        dead = l_scr[:] <= 0.0
        lse_ref[0, 0] = jnp.where(dead, 1e30, lse)


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    import jax
    return jax.default_backend() != "tpu" if interpret is None \
        else interpret


def _make_prep(s_pad: int, d_pad: int, s: int, d: int):
    """(b, s, h, d) -> (b, h, s_pad, d_pad), zero-padded."""
    import jax.numpy as jnp

    def prep(x):
        x = jnp.moveaxis(x, 2, 1)
        return jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - s),
                           (0, d_pad - d)))

    return prep


# index maps shared by every kernel: block row iq / ik is the third
# grid axis for forward+dq, swapped for dkdv
_IXQ = lambda ib, ih, iq, ik: (ib, ih, iq, 0)       # noqa: E731
_IXK = lambda ib, ih, iq, ik: (ib, ih, ik, 0)       # noqa: E731
_IXQ2 = lambda ib, ih, ik, iq: (ib, ih, iq, 0)      # noqa: E731
_IXK2 = lambda ib, ih, ik, iq: (ib, ih, ik, 0)      # noqa: E731


# -- causal triangular grid -------------------------------------------------
#
# A rectangular (iq, ik) grid wastes HALF the machine on causal
# attention: blocks strictly above the diagonal are masked to nothing,
# but the grid still streams their K/V blocks and burns their MXU
# issue slots (measured: causal was SLOWER than non-causal at 16k).
# Instead the causal kernels linearize only the valid lower-triangle
# pairs into one grid axis; the (iq, ik) pair per step rides in as
# SCALAR-PREFETCHED index arrays so the pipeline can still compute the
# next step's DMAs ahead of time (computing them with arithmetic inside
# the index maps measured 2.2x slower per step — the prefetcher
# couldn't run ahead).  q-major order keeps each q block's k sweep
# contiguous, so the VMEM scratch carries across it exactly as in the
# rectangular schedule.

def _tri_arrays(nq: int):
    """q-major lower-triangle enumeration: (iq_arr, ik_arr), len T."""
    import numpy as np
    idx = np.arange(nq)
    iq = np.repeat(idx, idx + 1)
    ik = np.concatenate([np.arange(i + 1) for i in idx]) if nq else idx
    return iq.astype(np.int32), ik.astype(np.int32)


def _tri_arrays_rev(nq: int):
    """k-major enumeration for the dk/dv sweep: for each ik the valid
    iq >= ik ascend contiguously."""
    import numpy as np
    idx = np.arange(nq)
    ik = np.repeat(idx, nq - idx)
    iq = np.concatenate([np.arange(i, nq) for i in idx]) if nq else idx
    return iq.astype(np.int32), ik.astype(np.int32)


# index maps for the prefetched triangular grid: block row from the
# prefetched arrays, everything else straight through
_TRIQ = lambda ib, ih, t, iqr, ikr: (ib, ih, iqr[t], 0)     # noqa: E731
_TRIK = lambda ib, ih, t, iqr, ikr: (ib, ih, ikr[t], 0)     # noqa: E731


def _block_geometry(s: int, d: int, block_q, block_k,
                    causal: bool = False):
    d_pad = _ceil_to(max(d, 1), 128)
    if block_q is None or block_k is None:
        # measured on v5e (post bf16-MXU-input rework): 1024/1024 is
        # fastest everywhere the kernel is actually dispatched (the
        # auto impl uses dense below 2k) — bigger blocks amortize the
        # per-block scratch round trips, and fp32 scores stay within
        # the 16MB VMEM at 1024^2
        auto = 1024 if s >= 2048 else 256
        block_q = auto if block_q is None else block_q
        block_k = auto if block_k is None else block_k
    bq = min(block_q, _ceil_to(s, 8))
    bk = min(block_k, _ceil_to(s, 8))
    if causal:
        # the triangular grid linearizes (iq, ik<=iq) pairs — that
        # needs a SQUARE block lattice (forward and backward recompute
        # this geometry independently; keep it a pure function)
        bq = bk = min(bq, bk)
    # pad to a common multiple: padding only to max(bq, bk) would
    # floor-truncate the other grid dimension and silently drop keys
    s_pad = _ceil_to(s, math.lcm(bq, bk))
    return d_pad, bq, bk, s_pad


def _pallas_forward(q, k, v, causal: bool, block_q: Optional[int],
                    block_k: Optional[int],
                    interpret: Optional[bool]) -> Tuple:
    """Returns (out (b,s,h,d), lse (b,h,s_pad,1) fp32 — padded layout,
    consumed only by _pallas_backward which recomputes the identical
    block geometry)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    interpret = _resolve_interpret(interpret)
    d_pad, bq, bk, s_pad = _block_geometry(s, d, block_q, block_k,
                                           causal)
    nq, nk = s_pad // bq, s_pad // bk
    kernel = functools.partial(
        _fwd_kernel, scale=1.0 / (d ** 0.5), causal=causal,
        bq=bq, bk=bk, seq_len=s)
    prep = _make_prep(s_pad, d_pad, s, d)
    qp, kp, vp = prep(q), prep(k), prep(v)
    out_shape = [
        jax.ShapeDtypeStruct((b, h, s_pad, d_pad), q.dtype),
        jax.ShapeDtypeStruct((b, h, s_pad, 1), jnp.float32),
    ]
    scratch = [
        pltpu.VMEM((bq, 1), jnp.float32),       # running max
        pltpu.VMEM((bq, 1), jnp.float32),       # running denom
        pltpu.VMEM((bq, d_pad), jnp.float32),   # accumulator
    ]
    if causal:
        iq_arr, ik_arr = _tri_arrays(nq)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, h, int(iq_arr.size)),
            in_specs=[
                pl.BlockSpec((1, 1, bq, d_pad), _TRIQ),
                pl.BlockSpec((1, 1, bk, d_pad), _TRIK),
                pl.BlockSpec((1, 1, bk, d_pad), _TRIK),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, bq, d_pad), _TRIQ),
                pl.BlockSpec((1, 1, bq, 1), _TRIQ),
            ],
            scratch_shapes=scratch,
        )
        out, lse = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=out_shape,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel",
                                     "arbitrary")),
            interpret=interpret,
        )(jnp.asarray(iq_arr), jnp.asarray(ik_arr), qp, kp, vp)
        return jnp.moveaxis(out[:, :, :s, :d], 1, 2), lse
    qblk, kblk, rowblk = _IXQ, _IXK, _IXQ
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d_pad), qblk,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d_pad), kblk,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d_pad), kblk,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d_pad), qblk,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, 1), rowblk,
                         memory_space=pltpu.VMEM),
        ],
        out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    return jnp.moveaxis(out[:, :, :s, :d], 1, 2), lse


# -- backward ---------------------------------------------------------------

def _masked_p(q, k, lse, scale, causal, q0, k0, bq, bk, seq_len):
    """Recompute p = exp(s - lse) for one block (shared by dq/dkdv)."""
    import jax
    import jax.numpy as jnp

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    kpos = k0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = kpos < seq_len
    if causal:
        qpos = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        mask = jnp.logical_and(mask, qpos >= kpos)
    s = jnp.where(mask, s, -1e30)
    return jnp.exp(s - lse)                       # (bq, bk)


def _dq_kernel(*refs, scale: float, causal: bool, bq: int, bk: int,
               seq_len: int):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if causal:
        (iq_ref, ik_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
         dq_ref, acc_scr) = refs
        t = pl.program_id(2)
        iq, ik = iq_ref[t], ik_ref[t]
        is_last = ik == iq
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
         dq_ref, acc_scr) = refs
        iq, ik = pl.program_id(2), pl.program_id(3)
        is_last = ik == pl.num_programs(3) - 1

    @pl.when(ik == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q0 = iq * bq
    k0 = ik * bk
    live = k0 < seq_len          # triangular grid when causal

    @pl.when(live)
    def _step():
        # native-dtype MXU inputs, f32 accumulation (see _fwd_kernel)
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                       # (bq, 1)
        dd = dd_ref[0, 0]                         # D = rowsum(do * o)
        p = _masked_p(q, k, lse, scale, causal, q0, k0, bq, bk, seq_len)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dd)                        # (bq, bk) f32
        acc_scr[:] = acc_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(is_last)
    def _finalize():
        dq_ref[0, 0] = acc_scr[:].astype(dq_ref.dtype)


def _dkdv_kernel(*refs, scale: float, causal: bool,
                 bq: int, bk: int, seq_len: int, tri_nq: int = 0):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if causal:
        # k-major triangle: for each ik, sweep the valid iq >= ik
        (iq_ref, ik_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        t = pl.program_id(2)
        iq, ikb = iq_ref[t], ik_ref[t]
        is_first = iq == ikb
        is_last = iq == tri_nq - 1
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        ikb = pl.program_id(2)
        iq = pl.program_id(3)              # q innermost: sweep per k blk
        is_first = iq == 0
        is_last = iq == pl.num_programs(3) - 1

    @pl.when(is_first)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    k0 = ikb * bk
    q0 = iq * bq
    live = k0 < seq_len          # triangular grid when causal

    @pl.when(live)
    def _step():
        # native-dtype MXU inputs, f32 accumulation (see _fwd_kernel)
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                       # (bq, 1)
        dd = dd_ref[0, 0]
        p = _masked_p(q, k, lse, scale, causal, q0, k0, bq, bk, seq_len)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)   # (bk, d)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dd)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(is_last)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _pallas_backward(q, k, v, o, lse, g, causal: bool,
                     block_q: Optional[int], block_k: Optional[int],
                     interpret: Optional[bool]):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    interpret = _resolve_interpret(interpret)
    d_pad, bq, bk, s_pad = _block_geometry(s, d, block_q, block_k,
                                           causal)
    nq, nk = s_pad // bq, s_pad // bk
    tri_T = nq * (nq + 1) // 2 if causal else 0
    scale = 1.0 / (d ** 0.5)
    prep = _make_prep(s_pad, d_pad, s, d)
    qp, kp, vp, op, dop = prep(q), prep(k), prep(v), prep(o), prep(g)
    # lse arrives already in the padded layout: _block_geometry is a
    # pure function of (s, d, block_q, block_k), so forward and
    # backward always agree on s_pad
    assert lse.shape == (b, h, s_pad, 1), (lse.shape, s_pad)
    lsep = lse
    dd = jnp.sum(dop.astype(jnp.float32) * op.astype(jnp.float32),
                 axis=-1, keepdims=True)           # (b, h, s_pad, 1)

    dq_kernel = functools.partial(_dq_kernel, scale=scale, causal=causal,
                                  bq=bq, bk=bk, seq_len=s)
    dq_shape = jax.ShapeDtypeStruct((b, h, s_pad, d_pad), q.dtype)
    dq_scratch = [pltpu.VMEM((bq, d_pad), jnp.float32)]
    if causal:
        iq_arr, ik_arr = _tri_arrays(nq)
        # dq: sweep k blocks per q block over the lower triangle
        dq = pl.pallas_call(
            dq_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(b, h, int(iq_arr.size)),
                in_specs=[
                    pl.BlockSpec((1, 1, bq, d_pad), _TRIQ),
                    pl.BlockSpec((1, 1, bk, d_pad), _TRIK),
                    pl.BlockSpec((1, 1, bk, d_pad), _TRIK),
                    pl.BlockSpec((1, 1, bq, d_pad), _TRIQ),
                    pl.BlockSpec((1, 1, bq, 1), _TRIQ),
                    pl.BlockSpec((1, 1, bq, 1), _TRIQ),
                ],
                out_specs=pl.BlockSpec((1, 1, bq, d_pad), _TRIQ),
                scratch_shapes=dq_scratch,
            ),
            out_shape=dq_shape,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel",
                                     "arbitrary")),
            interpret=interpret,
        )(jnp.asarray(iq_arr), jnp.asarray(ik_arr),
          qp, kp, vp, dop, lsep, dd)
    else:
        qblk, kblk, qrow = _IXQ, _IXK, _IXQ
        dq = pl.pallas_call(
            dq_kernel,
            grid=(b, h, nq, nk),
            in_specs=[
                pl.BlockSpec((1, 1, bq, d_pad), qblk,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, bk, d_pad), kblk,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, bk, d_pad), kblk,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, bq, d_pad), qblk,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, bq, 1), qrow,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, bq, 1), qrow,
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, 1, bq, d_pad), qblk,
                                   memory_space=pltpu.VMEM),
            out_shape=dq_shape,
            scratch_shapes=dq_scratch,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel",
                                     "arbitrary")),
            interpret=interpret,
        )(qp, kp, vp, dop, lsep, dd)

    # dk/dv: sweep q blocks per k block (q is the innermost dimension)
    kv_kernel = functools.partial(_dkdv_kernel, scale=scale,
                                  causal=causal, bq=bq, bk=bk, seq_len=s,
                                  tri_nq=nq)
    kv_shape = [
        jax.ShapeDtypeStruct((b, h, s_pad, d_pad), k.dtype),
        jax.ShapeDtypeStruct((b, h, s_pad, d_pad), v.dtype),
    ]
    kv_scratch = [pltpu.VMEM((bk, d_pad), jnp.float32),
                  pltpu.VMEM((bk, d_pad), jnp.float32)]
    if causal:
        iq_arr2, ik_arr2 = _tri_arrays_rev(nq)
        dk, dv = pl.pallas_call(
            kv_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(b, h, int(iq_arr2.size)),
                in_specs=[
                    pl.BlockSpec((1, 1, bq, d_pad), _TRIQ),
                    pl.BlockSpec((1, 1, bk, d_pad), _TRIK),
                    pl.BlockSpec((1, 1, bk, d_pad), _TRIK),
                    pl.BlockSpec((1, 1, bq, d_pad), _TRIQ),
                    pl.BlockSpec((1, 1, bq, 1), _TRIQ),
                    pl.BlockSpec((1, 1, bq, 1), _TRIQ),
                ],
                out_specs=[
                    pl.BlockSpec((1, 1, bk, d_pad), _TRIK),
                    pl.BlockSpec((1, 1, bk, d_pad), _TRIK),
                ],
                scratch_shapes=kv_scratch,
            ),
            out_shape=kv_shape,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel",
                                     "arbitrary")),
            interpret=interpret,
        )(jnp.asarray(iq_arr2), jnp.asarray(ik_arr2),
          qp, kp, vp, dop, lsep, dd)
    else:
        kblk2, qblk2, qrow2 = _IXK2, _IXQ2, _IXQ2
        dk, dv = pl.pallas_call(
            kv_kernel,
            grid=(b, h, nk, nq),
            in_specs=[
                pl.BlockSpec((1, 1, bq, d_pad), qblk2,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, bk, d_pad), kblk2,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, bk, d_pad), kblk2,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, bq, d_pad), qblk2,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, bq, 1), qrow2,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, bq, 1), qrow2,
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, bk, d_pad), kblk2,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, bk, d_pad), kblk2,
                             memory_space=pltpu.VMEM),
            ],
            out_shape=kv_shape,
            scratch_shapes=kv_scratch,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel",
                                     "arbitrary")),
            interpret=interpret,
        )(qp, kp, vp, dop, lsep, dd)

    unprep = lambda x: jnp.moveaxis(x[:, :, :s, :d], 1, 2)  # noqa: E731
    return unprep(dq), unprep(dk), unprep(dv)


# -- public api -------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Flash attention: (b, s, h, d) q/k/v -> (b, s, h, d).

    Forward AND backward run fused Pallas kernels (interpret mode
    off-TPU) — O(seq) memory in both directions.  ``block_q``/
    ``block_k`` default to None = auto (256 for short context, 512 from
    4k tokens — measured on v5e); pass explicit sizes to override."""
    out, _ = _pallas_forward(q, k, v, causal, block_q, block_k, interpret)
    return out


# Measured crossover on the v5e-class chip (bench.py device-compute
# section): at 2k tokens one XLA-fused einsum→softmax→einsum chain is
# on par with or ahead of the kernel's block pipeline (0.8-1.3x), while
# from ~4k the O(s) memory + streaming K/V blocks win decisively (2-2.6x
# at 16k).  Dense also costs O(s^2) activation memory, so the crossover
# stays low enough that the scores tensor is cheap.
DENSE_FLASH_CROSSOVER = 2048


def dense_attention(q, k, v, causal: bool = False):
    """XLA-fused dense attention — materializes the (s, s) scores and
    lets the compiler tile the matmul chain onto the MXU.  The fastest
    impl below the crossover; the correctness oracle everywhere."""
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (d ** 0.5)
    if causal:
        n = q.shape[1]
        pos = jnp.arange(n)
        mask = (pos[:, None] >= pos[None, :])[None, None]
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v).astype(q.dtype)


def attention(q, k, v, causal: bool = False, impl: str = "auto",
              block_q: Optional[int] = None,
              block_k: Optional[int] = None,
              interpret: Optional[bool] = None):
    """Sequence-adaptive attention dispatch.

    ``impl="auto"`` picks dense (XLA-fused, O(s²) memory) below
    :data:`DENSE_FLASH_CROSSOVER` tokens and the Pallas flash kernel
    (O(s) memory) at or above it — each impl where it measures faster.
    Off-TPU, auto always picks dense: the kernel would run in Pallas
    interpret mode there, which is never the faster choice.
    ``impl="dense"``/``"flash"`` force.  Shapes are static under jit,
    so the choice is made at trace time: no runtime branching."""
    if impl == "auto":
        import jax
        impl = "flash" if (q.shape[1] >= DENSE_FLASH_CROSSOVER
                           and jax.default_backend() == "tpu") else "dense"
    if impl == "dense":
        return dense_attention(q, k, v, causal)
    if impl == "flash":
        return flash_attention(q, k, v, causal, block_q=block_q,
                               block_k=block_k, interpret=interpret)
    raise ValueError(f"unknown attention impl {impl!r}")


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _pallas_forward(q, k, v, causal, block_q, block_k,
                               interpret)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    return _pallas_backward(q, k, v, o, lse, g, causal, block_q, block_k,
                            interpret)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
