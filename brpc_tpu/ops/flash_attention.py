"""Pallas flash attention — the hot-op kernel for the dense models.

No reference counterpart (the reference is an RPC framework; its hot
path is framing/IO).  This is the TPU-first answer to SURVEY §5.7's
"blockwise attention" prescription, written against the Pallas TPU
playbook (/opt/skills/guides/pallas_guide.md):

- grid (b, h, q_blocks, k_blocks), innermost dimension "arbitrary":
  VMEM scratch (running max / denominator / accumulator) persists
  across the k-block sweep — the classic online-softmax flash schedule,
  O(seq) memory per q block instead of O(seq²);
- q·kᵀ and p·v on the MXU via dot_general with
  ``preferred_element_type=float32``; masking built from
  ``broadcasted_iota`` (TPU-safe, pitfall #4);
- causal blocks entirely above the diagonal are skipped with
  ``pl.when`` (predication, no dynamic shapes);
- head dim and sequence are padded to lane/block multiples in the
  wrapper; pad keys are masked out in-kernel, pad rows sliced off;
- **custom VJP**: the backward pass recomputes attention with the
  dense XLA formulation — gradients are exact, forward is flash.
  (A fused backward kernel is a further optimization, not a semantic
  change.)
- ``interpret=True`` automatically off-TPU, so the same code path is
  unit-testable on the CPU mesh.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                scale: float, causal: bool, bq: int, bk: int,
                seq_len: int):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    iq = pl.program_id(2)
    q0 = iq * bq
    k0 = ik * bk
    # causal: skip k blocks strictly above the diagonal; always skip
    # blocks entirely in the padded tail
    live = k0 < seq_len
    if causal:
        live = jnp.logical_and(live, k0 <= q0 + bq - 1)

    @pl.when(live)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale        # (bq, bk)
        kpos = k0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = kpos < seq_len
        if causal:
            qpos = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            mask = jnp.logical_and(mask, qpos >= kpos)
        s = jnp.where(mask, s, -1e30)
        m_prev = m_scr[:]                                      # (bq, 1)
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_scr[:] = l_scr[:] * corr + p.sum(axis=-1, keepdims=True)
        m_scr[:] = m_new
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # (bq, d)

    @pl.when(ik == nk - 1)
    def _finalize():
        o_ref[0, 0] = (acc_scr[:]
                       / jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)


def _pallas_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                    interpret: Optional[bool]):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    d_pad = _ceil_to(max(d, 1), 128)
    bq = min(block_q, _ceil_to(s, 8))
    bk = min(block_k, _ceil_to(s, 8))
    # pad to a common multiple: padding only to max(bq, bk) would
    # floor-truncate the other grid dimension and silently drop keys
    s_pad = _ceil_to(s, math.lcm(bq, bk))
    nq, nk = s_pad // bq, s_pad // bk

    def prep(x):
        # (b, s, h, d) -> (b, h, s_pad, d_pad)
        x = jnp.moveaxis(x, 2, 1)
        return jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - s),
                           (0, d_pad - d)))

    qp, kp, vp = prep(q), prep(k), prep(v)
    kernel = functools.partial(
        _fwd_kernel, scale=1.0 / (d ** 0.5), causal=causal,
        bq=bq, bk=bk, seq_len=s)
    blk = lambda ib, ih, iq, ik: (ib, ih, iq, 0)        # noqa: E731
    kblk = lambda ib, ih, iq, ik: (ib, ih, ik, 0)       # noqa: E731
    out = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d_pad), blk,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d_pad), kblk,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d_pad), kblk,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d_pad), blk,
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, h, s_pad, d_pad), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),       # running max
            pltpu.VMEM((bq, 1), jnp.float32),       # running denom
            pltpu.VMEM((bq, d_pad), jnp.float32),   # accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    return jnp.moveaxis(out[:, :, :s, :d], 1, 2)       # (b, s, h, d)


def _dense(q, k, v, causal: bool):
    from ..parallel.ring_attention import reference_attention
    return reference_attention(q, k, v, causal=causal)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 256,
                    block_k: int = 256, interpret: Optional[bool] = None):
    """Flash attention: (b, s, h, d) q/k/v -> (b, s, h, d).

    Forward runs the Pallas kernel (interpret mode off-TPU); backward
    recomputes with the dense XLA formulation, so it is differentiable
    everywhere the dense oracle is."""
    return _pallas_forward(q, k, v, causal, block_q, block_k, interpret)


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret):
    return (_pallas_forward(q, k, v, causal, block_q, block_k, interpret),
            (q, k, v))


def _fa_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _dense(q, k, v, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
