"""fleet_dump — fetch a fleet registry's /fleet page and render the
member table + merged event timeline (the trace_dump sibling for the
fleet observability plane).

Point it at the registry host (any server that called
``fleet.host_registry``); plain members answer too, with their own
load report instead of a member table.  The operator one-liners:

    python -m brpc_tpu.tools.fleet_dump host:port
    python -m brpc_tpu.tools.fleet_dump host:port --timeline 50
    python -m brpc_tpu.tools.fleet_dump host:port --json
    python -m brpc_tpu.tools.fleet_dump host:port --self
"""

from __future__ import annotations

import http.client
import json
import sys
from typing import List, Optional


def fetch_fleet(server: str, self_only: bool = False,
                timeout: float = 10.0) -> dict:
    """Parsed /fleet?format=json body (raises on non-200)."""
    host, _, port = server.rpartition(":")
    path = "/fleet?format=json" + ("&self=1" if self_only else "")
    conn = http.client.HTTPConnection(host or "127.0.0.1", int(port),
                                      timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            raise RuntimeError(f"HTTP {resp.status}: {body[:200]!r}")
        return json.loads(body.decode("utf-8", "replace"))
    finally:
        conn.close()


def _fmt_member(row: dict) -> str:
    rep = row.get("report") or {}
    slots = rep.get("slots") or {}
    busy = rep.get("busy_ratio")
    age = row.get("age_s")
    return (f"{row.get('instance', '?'):<22} "
            f"{row.get('state', '?'):<9} "
            f"{('%.1fs' % age) if age is not None else '-':>8} "
            f"{rep.get('drain', '-'):>9} "
            f"{str(slots.get('live', '-')) + '/' + str(slots.get('total', '-')):>9} "
            f"{rep.get('inflight', '-'):>8} "
            f"{('%.2f' % busy) if busy is not None else '-':>5}")


def render(doc: dict, timeline: int = 20) -> str:
    """Human view of one /fleet JSON document."""
    out: List[str] = []
    if not doc.get("registry"):
        rep = doc.get("self", doc)      # --self answers the bare report
        out.append(f"member {rep.get('instance') or '(unnamed)'} "
                   f"drain={rep.get('drain')} seq={rep.get('seq')}")
        out.append(json.dumps(rep, indent=1, default=str))
        return "\n".join(out)
    members = doc.get("members", [])
    out.append(f"fleet: {len(members)} member(s), "
               f"ttl {doc.get('ttl_s')}s")
    out.append(f"{'instance':<22} {'state':<9} {'age':>8} "
               f"{'drain':>9} {'slots':>9} {'inflight':>8} {'busy':>5}")
    for row in members:
        out.append(_fmt_member(row))
    roll = doc.get("rollups") or {}
    if roll.get("top_busy"):
        out.append("top busy: " + ", ".join(
            f"{r['instance']}={r['busy_ratio']}"
            for r in roll["top_busy"]))
    if roll.get("top_slo_miss"):
        out.append("top slo-miss: " + ", ".join(
            f"{r['instance']}={r['miss_ratio']}"
            for r in roll["top_slo_miss"]))
    rows = (doc.get("timeline") or [])[-timeline:]
    if rows:
        out.append(f"timeline (last {len(rows)}):")
        for ev in rows:
            out.append(f"  {ev.get('wall_s', 0):>14.3f} "
                       f"{ev.get('instance', '?'):<22} "
                       f"{ev.get('event', '?'):<26} "
                       f"{ev.get('detail', '')}")
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="dump a fleet registry's member table + merged "
                    "event timeline")
    ap.add_argument("server", help="host:port of the registry host "
                                   "(any member answers with its own "
                                   "report)")
    ap.add_argument("--json", action="store_true",
                    help="raw /fleet JSON instead of the table")
    ap.add_argument("--self", dest="self_only", action="store_true",
                    help="this node's own load report only")
    ap.add_argument("--timeline", type=int, default=20,
                    help="timeline rows to show (default 20)")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    try:
        doc = fetch_fleet(args.server, self_only=args.self_only,
                          timeout=args.timeout)
    except Exception as e:
        print(f"fetch failed: {e}", file=sys.stderr)
        return 1
    if args.json:
        sys.stdout.write(json.dumps(doc, indent=1, default=str) + "\n")
        return 0
    sys.stdout.write(render(doc, timeline=args.timeline) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
