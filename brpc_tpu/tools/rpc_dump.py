"""rpc_dump — sampled capture of server traffic, replayable bytes.

≈ /root/reference/src/brpc/rpc_dump.h:50-69 (SampledRequest + the
rpc_dump_* gflags): when enabled, the server appends a budgeted sample
of incoming requests to a dump file as RAW tpu_std frames — the dump IS
wire format, so the replayer just sends it back out.

Flags (live-settable via /flags):
  rpc_dump                      master switch (default off)
  rpc_dump_dir                  directory for dump files
  rpc_dump_max_requests_per_second   sampling budget
  rpc_dump_max_file_mb          rotate/stop cap per file
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import Iterator, Optional, Tuple

from ..butil.flags import define_flag, get_flag
from ..butil.logging_util import LOG
from ..protocol.meta import RpcMeta

define_flag("rpc_dump", False, "capture sampled requests to disk",
            lambda v: True)
define_flag("rpc_dump_dir", "./rpc_dump", "dump file directory",
            lambda v: bool(str(v)))
define_flag("rpc_dump_max_requests_per_second", 1000,
            "dump sampling budget", lambda v: int(v) >= 0)
define_flag("rpc_dump_max_file_mb", 256, "per-file size cap",
            lambda v: int(v) > 0)

_lock = threading.Lock()
_file = None
_file_bytes = 0
_window = [0.0, 0]      # window start, taken


def dump_enabled() -> bool:
    return bool(get_flag("rpc_dump", False))


def _open_file():
    global _file, _file_bytes
    d = str(get_flag("rpc_dump_dir", "./rpc_dump"))
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"requests.{os.getpid()}.{int(time.time())}.dump")
    _file = open(path, "ab")
    _file_bytes = 0
    LOG.info("rpc_dump capturing to %s", path)


def maybe_dump_request(meta: RpcMeta, payload_bytes: bytes) -> None:
    """Called per request when the switch is on: budgeted sampling, then
    append the frame (re-encoded meta + payload+attachment bytes)."""
    global _file_bytes
    now = time.monotonic()
    with _lock:
        if now - _window[0] >= 1.0:
            _window[0] = now
            _window[1] = 0
        if _window[1] >= int(get_flag("rpc_dump_max_requests_per_second",
                                      1000)):
            return
        _window[1] += 1
        if _file is None:
            try:
                _open_file()
            except OSError as e:
                LOG.warning("rpc_dump cannot open file: %s", e)
                return
        cap = int(get_flag("rpc_dump_max_file_mb", 256)) << 20
        if _file_bytes >= cap:
            return
        mb = meta.encode()
        frame = (b"TRPC" + struct.pack("<II", len(mb) + len(payload_bytes),
                                       len(mb)) + mb + payload_bytes)
        try:
            _file.write(frame)
            _file.flush()
            _file_bytes += len(frame)
        except OSError as e:
            LOG.warning("rpc_dump write failed: %s", e)


def close_dump() -> Optional[str]:
    """Close the current dump file (tests / rotation); returns its path."""
    global _file
    with _lock:
        if _file is None:
            return None
        path = _file.name
        _file.close()
        _file = None
        return path


class DumpReader:
    """Iterate (meta, payload_bytes) frames out of a dump file."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self) -> Iterator[Tuple[RpcMeta, bytes]]:
        with open(self.path, "rb") as f:
            data = f.read()
        off = 0
        while off + 12 <= len(data):
            if data[off:off + 4] != b"TRPC":
                raise ValueError(f"bad magic at offset {off}")
            body, msize = struct.unpack_from("<II", data, off + 4)
            frame_end = off + 12 + body
            if frame_end > len(data):
                break                     # truncated tail (partial write)
            meta = RpcMeta.decode(data[off + 12:off + 12 + msize])
            if meta is None:
                raise ValueError(f"bad meta at offset {off}")
            yield meta, data[off + 12 + msize:frame_end]
            off = frame_end

    def frames(self):
        return list(self)
