"""rpc_replay — re-send dumped traffic at a target server.

≈ /root/reference/tools/rpc_replay/rpc_replay.cpp: read rpc_dump files,
replay each captured request against a server (original service/method
preserved, fresh correlation ids), optionally rate-limited and looped;
report latency/error stats.
"""

from __future__ import annotations

import time
from typing import List, Optional

from ..bvar.latency_recorder import LatencyRecorder
from ..client import Channel, ChannelOptions, Controller
from .rpc_dump import DumpReader


class ReplayOptions:
    def __init__(self):
        self.server = ""
        self.dump_files: List[str] = []
        self.qps = 0                  # 0 = max
        self.loop = 1                 # times through the dump
        self.timeout_ms = 1000
        self.connection_type = "pooled"


class Replayer:
    def __init__(self, options: ReplayOptions):
        self.options = options
        self.latency = LatencyRecorder("rpc_replay")
        self.sent = 0
        self.errors = 0

    def run(self) -> dict:
        opts = self.options
        copts = ChannelOptions()
        copts.connection_type = opts.connection_type
        copts.timeout_ms = opts.timeout_ms
        ch = Channel(copts)
        if ch.init(opts.server) != 0:
            raise RuntimeError(f"cannot init channel to {opts.server}")
        frames = []
        for path in opts.dump_files:
            frames.extend(DumpReader(path))
        interval = 1.0 / opts.qps if opts.qps > 0 else 0.0
        next_at = time.monotonic()
        begin = time.monotonic()
        for _ in range(max(1, opts.loop)):
            for meta, payload in frames:
                if interval:
                    now = time.monotonic()
                    if now < next_at:
                        time.sleep(next_at - now)
                    next_at += interval
                cntl = Controller()
                cntl.timeout_ms = opts.timeout_ms
                body = payload
                if meta.attachment_size and \
                        0 < meta.attachment_size <= len(payload):
                    body = payload[:len(payload) - meta.attachment_size]
                    cntl.request_attachment.append(
                        payload[len(payload) - meta.attachment_size:])
                t0 = time.monotonic()
                ch.call_method(f"{meta.service_name}.{meta.method_name}",
                               body, cntl=cntl)
                us = int((time.monotonic() - t0) * 1e6)
                self.sent += 1
                if cntl.failed:
                    self.errors += 1
                else:
                    self.latency << us
        elapsed = max(1e-9, time.monotonic() - begin)
        return {
            "frames": len(frames),
            "sent": self.sent,
            "errors": self.errors,
            "elapsed_s": round(elapsed, 3),
            "qps": round(self.sent / elapsed, 1),
            "latency_us_p50": round(self.latency.p50(), 1),
            "latency_us_p99": round(self.latency.p99(), 1),
        }


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json
    ap = argparse.ArgumentParser(description="replay rpc_dump files")
    ap.add_argument("--server", required=True)
    ap.add_argument("--qps", type=int, default=0)
    ap.add_argument("--loop", type=int, default=1)
    ap.add_argument("--timeout-ms", type=int, default=1000)
    ap.add_argument("dumps", nargs="+")
    args = ap.parse_args(argv)
    opts = ReplayOptions()
    opts.server = args.server
    opts.qps = args.qps
    opts.loop = args.loop
    opts.timeout_ms = args.timeout_ms
    opts.dump_files = args.dumps
    summary = Replayer(opts).run()
    print(json.dumps(summary, indent=1))
    return 0 if summary["errors"] == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
