"""Operational tools — load generation, traffic capture/replay, portal
viewing.

≈ /root/reference/tools/ (rpc_press, rpc_replay, rpc_view) and
src/brpc/rpc_dump.h — re-designed for this framework: the press drives
the client fast lane, dumps are raw tpu_std frames (replayable bytes,
no intermediate format), and the viewer reads the builtin portal.

Submodules import lazily (PEP 562): the server's dump hook must not pull
the whole client stack at dispatch time.
"""

_EXPORTS = {
    "Press": "rpc_press", "PressOptions": "rpc_press",
    "DumpReader": "rpc_dump", "dump_enabled": "rpc_dump",
    "maybe_dump_request": "rpc_dump", "close_dump": "rpc_dump",
    "Replayer": "rpc_replay", "ReplayOptions": "rpc_replay",
    "fetch": "rpc_view",
}


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib
    return getattr(importlib.import_module(f".{mod}", __name__), name)
