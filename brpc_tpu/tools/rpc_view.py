"""rpc_view — read a remote server's builtin portal from the terminal.

≈ /root/reference/tools/rpc_view/rpc_view.cpp: fetch any builtin page
(status, vars, flags, connections, rpcz, hotspots, ...) over HTTP and
print it.  `python -m brpc_tpu.tools.rpc_view host:port [page]`.
"""

from __future__ import annotations

import http.client
from typing import List, Optional


def fetch(server: str, page: str = "status", timeout: float = 10.0) -> str:
    host, _, port = server.partition(":")
    conn = http.client.HTTPConnection(host, int(port or 80), timeout=timeout)
    try:
        conn.request("GET", "/" + page.lstrip("/"))
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            raise RuntimeError(f"HTTP {resp.status}: {body[:200]!r}")
        return body.decode("utf-8", "replace")
    finally:
        conn.close()


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description="view a tpu-rpc server portal")
    ap.add_argument("server", help="host:port")
    ap.add_argument("page", nargs="?", default="status")
    args = ap.parse_args(argv)
    print(fetch(args.server, args.page), end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
