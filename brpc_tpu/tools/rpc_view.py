"""rpc_view — browse a remote server's builtin portal.

≈ /root/reference/tools/rpc_view/rpc_view.cpp: not just a fetcher — a
local HTTP proxy that serves any remote rank's portal to a browser,
rewriting the page's absolute links so navigation (vars trends, rpcz
drill-downs, hotspots, flags) keeps flowing through the proxy.  The
operator debugging rank 1234 of a TPU fleet points a browser at
``localhost:<proxy>/10.0.0.5:8080/status`` and walks the whole portal.

    python -m brpc_tpu.tools.rpc_view host:port [page]     # one page
    python -m brpc_tpu.tools.rpc_view --proxy 8888         # browse mode
"""

from __future__ import annotations

import http.client
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple


def fetch_raw(server: str, page: str = "status",
              timeout: float = 10.0) -> Tuple[int, str, bytes, str]:
    """(status, content_type, body, location) from a remote portal page
    (location is "" unless the upstream answered a redirect)."""
    host, _, port = server.partition(":")
    conn = http.client.HTTPConnection(host, int(port or 80),
                                      timeout=timeout)
    try:
        conn.request("GET", "/" + page.lstrip("/"))
        resp = conn.getresponse()
        body = resp.read()
        ctype = resp.headers.get("Content-Type", "text/plain")
        return resp.status, ctype, body, resp.headers.get("Location", "")
    finally:
        conn.close()


def fetch(server: str, page: str = "status", timeout: float = 10.0) -> str:
    status, _, body, _loc = fetch_raw(server, page, timeout)
    if status != 200:
        raise RuntimeError(f"HTTP {status}: {body[:200]!r}")
    return body.decode("utf-8", "replace")


# absolute-path link attributes and redirects get re-rooted under the
# proxy's /<target>/ prefix so the browser stays inside the proxy
_LINK_RE = re.compile(
    rb"""((?:href|src|action)\s*=\s*["'])/(?!/)""", re.IGNORECASE)
_TARGET_RE = re.compile(r"^/([^/]+:\d+)(/.*)?$")


def rewrite_links(body: bytes, target: str) -> bytes:
    """Re-root absolute links: href="/vars" → href="/<target>/vars"."""
    return _LINK_RE.sub(
        lambda m: m.group(1) + b"/" + target.encode() + b"/", body)


class ViewProxy:
    """The browsing proxy.  URL shape: ``/<host:port>/<portal path>``;
    ``/`` lists usage.  Serves on a daemon thread."""

    def __init__(self, port: int = 0, timeout: float = 10.0):
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                m = _TARGET_RE.match(self.path)
                if m is None:
                    body = (b"rpc_view proxy: browse a remote portal at "
                            b"/<host:port>/<page>\n")
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                target, rest = m.group(1), (m.group(2) or "/status")
                try:
                    status, ctype, body, location = fetch_raw(
                        target, rest, timeout=proxy.timeout)
                except (OSError, http.client.HTTPException) as e:
                    body = f"upstream {target} unreachable: {e}\n".encode()
                    self.send_response(502)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if ctype.startswith("text/html"):
                    body = rewrite_links(body, target)
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                if location:
                    # re-root absolute redirects so the browser stays
                    # inside the proxy's /<target>/ namespace
                    if location.startswith("/") \
                            and not location.startswith("//"):
                        location = f"/{target}{location}"
                    self.send_header("Location", location)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.timeout = timeout
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.httpd.server_address[1]
        self._thr: Optional[threading.Thread] = None

    def start(self) -> int:
        self._thr = threading.Thread(target=self.httpd.serve_forever,
                                     daemon=True, name="rpc_view-proxy")
        self._thr.start()
        return self.port

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description="view a tpu-rpc server portal")
    ap.add_argument("server", nargs="?", help="host:port")
    ap.add_argument("page", nargs="?", default="status")
    ap.add_argument("--proxy", type=int, metavar="PORT",
                    help="serve a browsing proxy instead of fetching once")
    args = ap.parse_args(argv)
    if args.proxy is not None:
        proxy = ViewProxy(port=args.proxy)
        port = proxy.start()
        print(f"rpc_view proxy on http://127.0.0.1:{port}/ — open "
              f"http://127.0.0.1:{port}/<host:port>/status")
        try:
            proxy._thr.join()
        except KeyboardInterrupt:
            proxy.stop()
        return 0
    if not args.server:
        ap.error("server required unless --proxy is given")
    print(fetch(args.server, args.page), end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
