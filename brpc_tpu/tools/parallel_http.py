"""parallel_http — fetch one URL path from many servers concurrently.

≈ /root/reference/tools/parallel_http/parallel_http.cpp: the fleet
operator's mass probe — pull ``/vars/process_uptime`` (or any portal
page) from every rank at once and see who is slow, stuck, or divergent.

    python -m brpc_tpu.tools.parallel_http /status host1:p1 host2:p2 ...
    python -m brpc_tpu.tools.parallel_http /vars -f ranks.txt -c 64
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from .rpc_view import fetch_raw


@dataclass
class FetchResult:
    server: str
    status: int = 0                 # 0 = transport failure
    body: bytes = b""
    latency_s: float = 0.0
    error: str = ""

    @property
    def ok(self) -> bool:
        return self.status == 200


def parallel_fetch(servers: Sequence[str], path: str = "/status",
                   concurrency: int = 32,
                   timeout: float = 10.0) -> Dict[str, FetchResult]:
    """Fetch ``path`` from every server with a bounded thread pool.
    Never raises — per-server failures land in the result's ``error``."""

    def one(server: str) -> FetchResult:
        import http.client as _hc
        t0 = time.monotonic()
        try:
            status, _, body, _loc = fetch_raw(server, path,
                                              timeout=timeout)
            return FetchResult(server, status, body,
                               time.monotonic() - t0)
        except (OSError, _hc.HTTPException, RuntimeError, ValueError) as e:
            # one garbled rank (non-HTTP port, truncated reply) must
            # never abort the fleet scan
            return FetchResult(server, 0, b"", time.monotonic() - t0,
                               f"{type(e).__name__}: {e}")

    results: Dict[str, FetchResult] = {}
    with ThreadPoolExecutor(max_workers=max(1, concurrency)) as pool:
        for r in pool.map(one, servers):
            results[r.server] = r
    return results


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="fetch a portal page from many servers at once")
    ap.add_argument("path", help="page path, e.g. /status")
    ap.add_argument("servers", nargs="*", help="host:port ...")
    ap.add_argument("-f", "--file", help="file with one host:port per line")
    ap.add_argument("-c", "--concurrency", type=int, default=32)
    ap.add_argument("-t", "--timeout", type=float, default=10.0)
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="summary only (no bodies)")
    args = ap.parse_args(argv)
    servers = list(args.servers)
    if args.file:
        with open(args.file) as f:
            stripped = (ln.strip() for ln in f)
            servers += [s for s in stripped
                        if s and not s.startswith("#")]
    if not servers:
        ap.error("no servers given")
    results = parallel_fetch(servers, args.path,
                             concurrency=args.concurrency,
                             timeout=args.timeout)
    ok = 0
    for server in servers:
        r = results[server]
        if r.ok:
            ok += 1
            print(f"== {server} ({r.latency_s * 1e3:.1f}ms)")
            if not args.quiet:
                print(r.body.decode("utf-8", "replace").rstrip())
        else:
            print(f"== {server} FAILED "
                  f"({r.error or f'HTTP {r.status}'})")
    print(f"-- {ok}/{len(servers)} ok")
    return 0 if ok == len(servers) else 1


if __name__ == "__main__":
    raise SystemExit(main())
