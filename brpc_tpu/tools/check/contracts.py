"""Analyzer 1 — C++ ↔ Python contract checker.

The native lanes rest on hand-mirrored contracts: engine.cpp's closed
fallback enums vs the Python reason-name tables, the TLV tag registry
vs the engine's meta scans and the pre-encoded ``TLV_*`` prefixes, and
the shim call arities (which "grew one arg" in two separate rounds).
This analyzer reads BOTH sides as source text and cross-checks every
one of them, so a drift fails tier-1 instead of waiting for the exact
runtime shape that exercises it.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional

from .base import Finding, Tree, public_arity
from . import cppscan

ENGINE = "brpc_tpu/native/src/engine.cpp"
META = "brpc_tpu/protocol/meta.py"
BRIDGE = "brpc_tpu/transport/native_bridge.py"
CLIENT_LANE = "brpc_tpu/transport/client_lane.py"
SLIM = "brpc_tpu/server/slim_dispatch.py"
HTTP_SLIM = "brpc_tpu/server/http_slim.py"
STREAM_SLIM = "brpc_tpu/server/stream_slim.py"

# struct format char -> byte width (the meta codec's fixed-size fields)
_WIDTHS = {"Q": 8, "q": 8, "I": 4, "i": 4, "H": 2, "h": 2, "B": 1}


def _fail(findings, path, line, msg):
    findings.append(Finding("contracts", path, line, msg))


# -- python-side extraction --------------------------------------------------

def _module_tuple(tree: Tree, rel: str, name: str) -> Optional[List[str]]:
    """A module-level tuple/list of string constants, by variable name."""
    try:
        mod = ast.parse(tree.text(rel))
    except SyntaxError:
        return None
    for node in mod.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name \
                        and isinstance(node.value, (ast.Tuple, ast.List)):
                    out = []
                    for e in node.value.elts:
                        if isinstance(e, ast.Constant) \
                                and isinstance(e.value, str):
                            out.append(e.value)
                        else:
                            return None
                    return out
    return None


def meta_registry(tree: Tree) -> Dict[str, Dict]:
    """The TLV registry out of protocol/meta.py source:

    - ``tags``: _T_NAME -> int tag
    - ``widths``: tag -> fixed byte width (None = variable length),
      derived from the codec (``struct.unpack("<Q", ...)`` in decode)
    - ``prefixes``: TLV_NAME -> bytes literal
    """
    mod = ast.parse(tree.text(META))
    tags: Dict[str, int] = {}
    prefixes: Dict[str, bytes] = {}
    for node in ast.walk(mod):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if name.startswith("_T_") and isinstance(node.value,
                                                     ast.Constant) \
                    and isinstance(node.value.value, int):
                tags[name] = node.value.value
            if name.startswith("TLV_") and isinstance(node.value,
                                                      ast.Constant) \
                    and isinstance(node.value.value, bytes):
                prefixes[name] = node.value.value
    # widths from the decode() unpacks: `struct.unpack("<Q", field)`
    # guarded by `tag == _T_X` — walk the if/elif chain
    widths: Dict[int, Optional[int]] = {t: None for t in tags.values()}
    src = tree.text(META)
    for m in re.finditer(
            r"tag\s*==\s*(_T_\w+)\s*:\s*\n(.*?)(?=\n\s*elif|\n\s*#|\Z)",
            src, re.S):
        tname, body = m.group(1), m.group(2)
        if tname not in tags:
            continue
        wm = re.search(r'struct\.unpack\("<(\w)"', body)
        if wm and wm.group(1) in _WIDTHS:
            widths[tags[tname]] = _WIDTHS[wm.group(1)]
        elif "field[0]" in body:
            widths[tags[tname]] = 1
    return {"tags": tags, "widths": widths, "prefixes": prefixes}


def _public_def_arity(tree: Tree, rel: str, qualpath: List[str]
                      ) -> Optional[int]:
    """Public arity of a (possibly nested) function.  ``qualpath`` is
    e.g. ["make_slim_handler", "slim"] or ["ClientLane", "_on_burst"]."""
    try:
        mod = ast.parse(tree.text(rel))
    except SyntaxError:
        return None
    scope = mod.body
    node = None
    for name in qualpath:
        node = None
        for n in scope:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)) and n.name == name:
                node = n
                break
        if node is None:
            return None
        scope = node.body
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    return public_arity(node)


# -- the checks --------------------------------------------------------------

def _check_reason_tables(tree, eng, findings) -> None:
    # FbReason members vs kFbNames (count), vs the bridge mirror (order)
    fb = cppscan.parse_enum(eng, "FbReason")
    fb_names = cppscan.parse_string_array(eng, "kFbNames")
    if fb is None or fb_names is None:
        _fail(findings, ENGINE, 1,
              "FbReason enum or kFbNames table not found")
        return
    fb_members = [m for m in fb if m != "FB_REASONS"]
    if len(fb_members) != len(fb_names):
        _fail(findings, ENGINE, 1,
              f"FbReason has {len(fb_members)} members but kFbNames "
              f"has {len(fb_names)} strings — the reason-name table "
              "drifted from the enum")
    mirror = _module_tuple(tree, BRIDGE, "FB_REASON_NAMES")
    if mirror is None:
        _fail(findings, BRIDGE, 1,
              "FB_REASON_NAMES mirror missing from the bridge (the "
              "fallback family pre-seed must cover every engine reason)")
    elif list(mirror) != list(fb_names):
        _fail(findings, BRIDGE, 1,
              f"bridge FB_REASON_NAMES != engine kFbNames: "
              f"{sorted(set(mirror) ^ set(fb_names)) or 'order differs'}")

    # RouteFb per-route names must each be one of the global reasons
    rfb = cppscan.parse_enum(eng, "RouteFb")
    rfb_names = cppscan.parse_string_array(eng, "kRouteFbNames")
    if rfb is not None and rfb_names is not None:
        rfb_members = [m for m in rfb if m != "kRouteFb"]
        if len(rfb_members) != len(rfb_names):
            _fail(findings, ENGINE, 1,
                  f"RouteFb has {len(rfb_members)} members but "
                  f"kRouteFbNames has {len(rfb_names)}")
        for n in rfb_names:
            if n not in fb_names:
                _fail(findings, ENGINE, 1,
                      f"kRouteFbNames entry '{n}' is not a kFbNames "
                      "reason — per-route attribution would invent a "
                      "name the global family never exports")

    # kind-5 streaming lane: StreamFb vs kStreamFbNames vs the
    # stream_slim mirror
    sfb = cppscan.parse_enum(eng, "StreamFb")
    sfb_names = cppscan.parse_string_array(eng, "kStreamFbNames")
    if sfb is None or sfb_names is None:
        _fail(findings, ENGINE, 1,
              "StreamFb enum or kStreamFbNames table not found")
    else:
        sfb_members = [m for m in sfb if m != "SFB_REASONS"]
        if len(sfb_members) != len(sfb_names):
            _fail(findings, ENGINE, 1,
                  f"StreamFb has {len(sfb_members)} members but "
                  f"kStreamFbNames has {len(sfb_names)} strings — the "
                  "kind-5 reason-name table drifted from the enum")
        smirror = _module_tuple(tree, STREAM_SLIM, "STREAM_FB_NAMES")
        if smirror is None:
            _fail(findings, STREAM_SLIM, 1,
                  "STREAM_FB_NAMES mirror missing from stream_slim "
                  "(the kind-5 fallback pre-seed must cover every "
                  "engine reason)")
        elif list(smirror) != list(sfb_names):
            _fail(findings, STREAM_SLIM, 1,
                  f"stream_slim STREAM_FB_NAMES != engine "
                  f"kStreamFbNames: "
                  f"{sorted(set(smirror) ^ set(sfb_names)) or 'order differs'}")

    # client lane: CliFb vs kCliFbNames vs the Python REASONS tuple
    cli = cppscan.parse_enum(eng, "CliFb")
    cli_names = cppscan.parse_string_array(eng, "kCliFbNames")
    if cli is None or cli_names is None:
        _fail(findings, ENGINE, 1,
              "CliFb enum or kCliFbNames table not found")
        return
    cli_members = [m for m in cli if m != "CFB_REASONS"]
    if len(cli_members) != len(cli_names):
        _fail(findings, ENGINE, 1,
              f"CliFb has {len(cli_members)} members but kCliFbNames "
              f"has {len(cli_names)} strings")
    reasons = _module_tuple(tree, CLIENT_LANE, "REASONS")
    if reasons is None:
        _fail(findings, CLIENT_LANE, 1, "REASONS tuple not found")
    elif list(reasons) != list(cli_names):
        _fail(findings, CLIENT_LANE, 1,
              f"client_lane.REASONS != engine kCliFbNames: "
              f"{sorted(set(reasons) ^ set(cli_names)) or 'order differs'}")


def _check_tlv_registry(tree, eng, findings) -> None:
    reg = meta_registry(tree)
    tags, widths, prefixes = reg["tags"], reg["widths"], reg["prefixes"]
    if not tags:
        _fail(findings, META, 1, "no _T_* tag registry found")
        return
    by_value: Dict[int, str] = {}
    for name, val in tags.items():
        if val in by_value:
            _fail(findings, META, 1,
                  f"duplicate TLV tag {val}: {by_value[val]} and {name}")
        by_value[val] = name

    # the engine's request meta scan: every case label must be a
    # registered tag, and fixed-length guards must match the codec width
    cases = cppscan.scan_case_tags(eng, "scan_request_meta")
    if not cases:
        _fail(findings, ENGINE, 1, "scan_request_meta case labels not "
                                   "found")
    for tag, need in cases.items():
        if tag not in by_value:
            _fail(findings, ENGINE, 1,
                  f"engine scan_request_meta handles TLV tag {tag} "
                  "which is not in protocol/meta.py's registry "
                  "(renumbered or removed?)")
            continue
        want = widths.get(tag)
        if need is not None and want is not None and need != want:
            _fail(findings, ENGINE, 1,
                  f"engine requires length {need} for TLV tag {tag} "
                  f"({by_value[tag]}) but the Python codec reads "
                  f"{want} bytes")
    # ad-hoc `tag == N` walks (client demux classification, plain-resp
    # scans): every literal tag referenced anywhere must be registered
    for tag in cppscan.literal_tag_checks(eng):
        if tag != 0 and tag not in by_value:
            _fail(findings, ENGINE, 1,
                  f"engine compares against TLV tag {tag} which is not "
                  "in protocol/meta.py's registry")

    # pre-encoded TLV_* prefixes: tag byte + <I length must agree with
    # the registry tag and the codec's fixed width
    alias = {"TLV_CORRELATION": "_T_CORRELATION",
             "TLV_ATTACHMENT": "_T_ATTACHMENT",
             "TLV_TIMEOUT": "_T_TIMEOUT_MS",
             "TLV_TRACE": "_T_TRACE_ID",
             "TLV_SPAN": "_T_SPAN_ID"}
    for pname, blob in prefixes.items():
        tname = alias.get(pname, "_T_" + pname[4:])
        if tname not in tags:
            _fail(findings, META, 1,
                  f"{pname} has no matching registry tag ({tname})")
            continue
        if len(blob) != 5:
            _fail(findings, META, 1,
                  f"{pname} must be 5 bytes (tag + u32 length), got "
                  f"{len(blob)}")
            continue
        if blob[0] != tags[tname]:
            _fail(findings, META, 1,
                  f"{pname} tag byte is {blob[0]} but {tname} is "
                  f"{tags[tname]} — pre-encoded prefix drifted from "
                  "the registry")
        ln = int.from_bytes(blob[1:5], "little")
        want = widths.get(tags[tname])
        if want is not None and ln != want:
            _fail(findings, META, 1,
                  f"{pname} length field says {ln} bytes but the codec "
                  f"reads {want} for {tname}")


def _check_shim_arities(tree, eng, findings) -> None:
    # kind-3 (slim tpu_std) and kind-2 (raw) shim call sites — both go
    # through it.m->handler; the kind-3 site sits inside the
    # `if (it.m->kind == 3)` branch, which precedes the kind-2 else arm
    clean = cppscan.strip_comments(eng)
    sites = cppscan.call_sites(eng, "PyObject_CallFunctionObjArgs",
                               "it.m->handler")
    # sites are in source order: the first sits inside the
    # `if (it.m->kind == 3)` branch (slim), the second in the else arm
    # (kind-2 raw) — raw_slim_item's layout, sanity-checked below
    kind3_off = clean.find("it.m->kind == 3")
    kind3 = sites[0][1] if sites and kind3_off != -1 \
        and sites[0][0] > kind3_off else None
    kind2 = sites[1][1] if len(sites) >= 2 else None
    if kind3 is None:
        _fail(findings, ENGINE, 1, "kind-3 slim shim call site not found")
    else:
        want = _public_def_arity(tree, SLIM, ["make_slim_handler", "slim"])
        if want is None:
            _fail(findings, SLIM, 1,
                  "make_slim_handler's inner slim() def not found")
        elif len(kind3) != want:
            _fail(findings, ENGINE, 1,
                  f"engine calls the kind-3 slim shim with "
                  f"{len(kind3)} args but slim_dispatch's shim "
                  f"takes {want} — the contract grew/shrank on one "
                  "side only")
    if kind2 is not None:
        if len(kind2) != 2:
            _fail(findings, ENGINE, 1,
                  f"engine calls the kind-2 raw handler with "
                  f"{len(kind2)} args; @raw_method's contract is "
                  "(payload, attachment)")

    # kind-5 (stream open) shim
    s5_sites = cppscan.call_sites(eng, "PyObject_CallFunctionObjArgs",
                                  "it.m->stream_handler")
    if not s5_sites:
        _fail(findings, ENGINE, 1,
              "kind-5 stream shim call site not found")
    else:
        want = _public_def_arity(tree, STREAM_SLIM,
                                 ["make_stream_handler", "slim"])
        if want is None:
            _fail(findings, STREAM_SLIM, 1,
                  "make_stream_handler's inner slim() def not found")
        elif len(s5_sites[0][1]) != want:
            _fail(findings, ENGINE, 1,
                  f"engine calls the kind-5 stream shim with "
                  f"{len(s5_sites[0][1])} args but stream_slim's shim "
                  f"takes {want} — the contract grew/shrank on one "
                  "side only")

    # batched stream-chunk delivery: one-list contract
    chunk_sites = cppscan.call_sites(eng, "PyObject_CallFunctionObjArgs",
                                     "lp->eng->stream_chunks")
    if not chunk_sites:
        _fail(findings, ENGINE, 1,
              "stream chunk delivery call site not found")
    else:
        want = _public_def_arity(tree, STREAM_SLIM, ["slim_chunks"])
        if want is not None and len(chunk_sites[0][1]) != want:
            _fail(findings, ENGINE, 1,
                  f"engine calls stream_chunks with "
                  f"{len(chunk_sites[0][1])} args but slim_chunks "
                  f"takes {want}")

    # kind-4 (slim HTTP) shim
    http_sites = cppscan.call_sites(eng, "PyObject_CallFunctionObjArgs",
                                    "it.hroute->handler")
    if not http_sites:
        _fail(findings, ENGINE, 1, "kind-4 http shim call site not found")
    else:
        want = _public_def_arity(tree, HTTP_SLIM,
                                 ["make_http_slim_handler", "slim"])
        if want is None:
            _fail(findings, HTTP_SLIM, 1,
                  "make_http_slim_handler's inner slim() def not found")
        elif len(http_sites[0][1]) != want:
            _fail(findings, ENGINE, 1,
                  f"engine calls the kind-4 http shim with "
                  f"{len(http_sites[0][1])} args but http_slim's shim "
                  f"takes {want}")

    # burst-end hook: CallNoArgs on the C side, zero-arg def on ours
    if "PyObject_CallNoArgs(lp->eng->burst_end)" not in clean:
        _fail(findings, ENGINE, 1,
              "burst_end hook is no longer a CallNoArgs site — "
              "flush_burst_accounting's zero-arg contract drifted")
    want = _public_def_arity(tree, SLIM, ["flush_burst_accounting"])
    if want != 0:
        _fail(findings, SLIM, 1,
              f"flush_burst_accounting takes {want} args; the engine "
              "invokes it with none")

    # format-string entries: the event dispatch callback and the client
    # demux burst callback
    disp_fmts = set(cppscan.callfunction_formats(eng, "eng->dispatch"))
    want = _public_def_arity(tree, BRIDGE, ["NativeBridge", "_dispatch"])
    for fmt in disp_fmts:
        if want is not None and len(fmt) != want:
            _fail(findings, ENGINE, 1,
                  f"engine dispatch call format '{fmt}' passes "
                  f"{len(fmt)} args but NativeBridge._dispatch takes "
                  f"{want}")
    demux_fmts = set(cppscan.callfunction_formats(eng, "d->callback"))
    want = _public_def_arity(tree, CLIENT_LANE, ["ClientLane", "_on_burst"])
    for fmt in demux_fmts:
        if want is not None and len(fmt) != want:
            _fail(findings, ENGINE, 1,
                  f"client demux callback format '{fmt}' passes "
                  f"{len(fmt)} args but ClientLane._on_burst takes "
                  f"{want}")


def check_contracts(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    eng = tree.text(ENGINE)
    _check_reason_tables(tree, eng, findings)
    _check_tlv_registry(tree, eng, findings)
    _check_shim_arities(tree, eng, findings)
    return findings
