"""CLI for the static-analysis suite: ``python -m brpc_tpu.tools.check``.

Exit codes: 0 = clean, 1 = findings, 2 = suite failure — suitable as a
pre-commit / CI gate (see tools/check/run_all.sh).
"""

from __future__ import annotations

import argparse
import sys

from . import ANALYZERS, run_all


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m brpc_tpu.tools.check",
        description="repo-specific static analysis: contract drift, "
                    "lane invariants, closed enums/flags, loop-thread "
                    "blocking calls")
    ap.add_argument("--analyzer", "-a", action="append", default=[],
                    choices=[n for n, _ in ANALYZERS],
                    help="run only this analyzer (repeatable)")
    ap.add_argument("--fail-fast", action="store_true",
                    help="stop after the first analyzer with findings")
    ap.add_argument("--root", default=None,
                    help="repo root to analyze (default: this checkout)")
    ap.add_argument("--quiet", "-q", action="store_true",
                    help="findings only, no summary line")
    args = ap.parse_args(argv)

    try:
        findings = run_all(root=args.root,
                           only=tuple(args.analyzer) or None,
                           fail_fast=args.fail_fast)
    except Exception as e:                      # suite bug ≠ clean tree
        print(f"check: suite error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    for f in findings:
        print(f"{f.path}:{f.line}: [{f.analyzer}] {f.message}")
    if not args.quiet:
        ran = tuple(args.analyzer) or tuple(n for n, _ in ANALYZERS)
        if findings:
            print(f"check: {len(findings)} finding(s) across "
                  f"{', '.join(ran)}", file=sys.stderr)
        else:
            print(f"check: clean ({', '.join(ran)})")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
