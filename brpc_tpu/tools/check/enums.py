"""Analyzer 3 — closed-enum / flag / bvar-cardinality lint.

The telemetry planes promise *closed* reason enums (no "unknown"
bucket) and every flag read promises a declared flag.  Those promises
hold only while three conventions do:

1. every ``FB_*``/``CFB_*``/``RFB_*``/``DP_*`` token referenced in
   engine.cpp (counter bumps, ``route_fb`` sites, module constants) is
   a declared member of its closed enum — and so is every such token
   the Python side references off the native module;
2. every reason NAME the process can export (engine fallback names,
   client-lane names, scatter screening literals, admission verdicts)
   is pinned by at least one test under ``tests/`` — a reason nobody
   asserts on is a reason free to drift;
3. every ``get_flag``/``set_flag``/``watch_flag`` string literal (in
   the package AND the tests — a test flipping a renamed flag silently
   no-ops) resolves to a ``define_flag`` declaration, and every
   ``PassiveDimension`` family declares its label names as literals,
   with tenant-labeled families living next to a cardinality bound.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .base import ALLOW_MARK, Finding, Tree, call_name
from . import cppscan

ENGINE = "brpc_tpu/native/src/engine.cpp"

_ENUM_PREFIX = {
    "FB_": "FbReason",
    "CFB_": "CliFb",
    "RFB_": "RouteFb",
    "DP_": "DpStage",
    "SFB_": "StreamFb",
}
# python-side identifiers sharing an enum prefix that are NOT engine
# constants (the bridge's name-table mirror)
_SENTINELS = {"FB_REASONS", "CFB_REASONS", "FB_REASON_NAMES"}

_FLAG_READERS = ("get_flag", "set_flag", "watch_flag")


def _fail(findings, path, line, msg):
    findings.append(Finding("enums", path, line, msg))


def _allowed(text_lines: List[str], line: int) -> bool:
    return 0 < line <= len(text_lines) \
        and ALLOW_MARK in text_lines[line - 1]


def _parse_all(tree: Tree, files) -> List[Tuple[str, str, ast.Module]]:
    out = []
    for rel, text in files:
        try:
            out.append((rel, text, ast.parse(text)))
        except SyntaxError:
            pass
    return out


def _str_const(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None




def check_enums(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    eng = tree.text(ENGINE)

    declared: Dict[str, List[str]] = {}
    for prefix, enum_name in _ENUM_PREFIX.items():
        declared[prefix] = cppscan.parse_enum(eng, enum_name) or []

    # 1a. engine-side closed-enum usage
    used = cppscan.used_enum_tokens(eng, tuple(_ENUM_PREFIX))
    for tok, line in sorted(used.items()):
        prefix = next(p for p in _ENUM_PREFIX if tok.startswith(p))
        if tok not in declared[prefix]:
            _fail(findings, ENGINE, line,
                  f"'{tok}' is used but not declared in enum "
                  f"{_ENUM_PREFIX[prefix]} — the closed enum is no "
                  "longer closed")

    pkg = _parse_all(tree, tree.package_files())
    tests = tree.test_files()
    tests_blob = "\n".join(t for _r, t in tests)

    # 1b. python-side references to the engine's enum constants
    tok_re = re.compile(r"\b(?:%s)[A-Z0-9_]+\b"
                        % "|".join(re.escape(p) for p in _ENUM_PREFIX))
    for rel, text, _mod in pkg:
        if "tools/check/" in rel.replace("\\", "/"):
            continue          # the analyzers name tokens in messages
        for i, line in enumerate(text.splitlines(), 1):
            if ALLOW_MARK in line:
                continue
            for m in tok_re.finditer(line):
                tok = m.group(0)
                prefix = next(p for p in _ENUM_PREFIX
                              if tok.startswith(p))
                if declared[prefix] and tok not in declared[prefix] \
                        and tok not in _SENTINELS:
                    _fail(findings, rel, i,
                          f"'{tok}' is not a declared {name_of(prefix)}"
                          " member — the native module will not export "
                          "it")

    # 2. every exportable reason name has a test pin
    reason_names: List[Tuple[str, str]] = []      # (name, origin)
    for arr in ("kFbNames", "kCliFbNames", "kStreamFbNames"):
        for n in cppscan.parse_string_array(eng, arr) or []:
            reason_names.append((n, f"{ENGINE} ({arr})"))
    for rel, _text, mod in pkg:
        for node in ast.walk(mod):
            if isinstance(node, ast.Call) \
                    and call_name(node) == "_scatter_fallback" \
                    and node.args:
                s = _str_const(node.args[0])
                if s:
                    reason_names.append((s, f"{rel} (scatter)"))
        if rel.endswith("server/admission.py"):
            for node in ast.walk(mod):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id in (
                            "ADMITTED", "SERVER_CAP", "METHOD_CAP",
                            "CODEL", "TENANT_QUOTA", "LAME_DUCK"):
                    s = _str_const(node.value)
                    if s:
                        reason_names.append((s, f"{rel} (verdict)"))
        if rel.endswith("kv/transport.py"):
            # the KV transfer plane's closed fallback/close enums: every
            # member needs a test pin, like the engine name tables
            for node in ast.walk(mod):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id in (
                            "KV_FALLBACK_REASONS", "KV_CLOSE_REASONS") \
                        and isinstance(node.value, ast.Tuple):
                    for e in node.value.elts:
                        s = _str_const(e)
                        if s:
                            reason_names.append((s, f"{rel} (kv)"))
        if rel.endswith("models/lm_service.py"):
            # the SLO scheduler's closed event enums (chunk-slice /
            # preemption events + spec-decode outcomes): count_sched/
            # count_spec assert membership at runtime, and every member
            # needs a test anchor here — an unpinned scheduler event is
            # free to drift out of the telemetry contract
            for node in ast.walk(mod):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id in (
                            "SLO_SCHED_EVENTS", "SPEC_DECODE_EVENTS") \
                        and isinstance(node.value, ast.Tuple):
                    for e in node.value.elts:
                        s = _str_const(e)
                        if s:
                            reason_names.append((s, f"{rel} (sched)"))
        if rel.endswith("models/lm_telemetry.py"):
            # the serving-observability plane's closed enums (step-loop
            # phase names + SLO attainment verdicts): record_phase
            # indexes the phase table and count_slo asserts verdict
            # membership at runtime; every member needs a test anchor
            # here — an unpinned phase or verdict is free to drift out
            # of the /lm + Prometheus surface
            for node in ast.walk(mod):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id in (
                            "LM_STEP_PHASES", "LM_SLO_VERDICTS") \
                        and isinstance(node.value, ast.Tuple):
                    for e in node.value.elts:
                        s = _str_const(e)
                        if s:
                            reason_names.append((s, f"{rel} (lm_obs)"))
        if rel.endswith("kv/pages.py"):
            # the paged-KV allocator's closed enums (eviction close
            # reasons + prefix-cache events): same pin discipline —
            # count_evict/count_prefix assert membership at runtime,
            # and every member needs a test anchor here
            for node in ast.walk(mod):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id in (
                            "KV_EVICT_REASONS", "PREFIX_CACHE_EVENTS") \
                        and isinstance(node.value, ast.Tuple):
                    for e in node.value.elts:
                        s = _str_const(e)
                        if s:
                            reason_names.append((s, f"{rel} (kv)"))
        if rel.endswith("brpc_tpu/fleet.py"):
            # the fleet flight recorder's closed event enum:
            # record_event asserts membership at runtime, and every
            # member needs a test anchor here — an unpinned event would
            # silently vanish from the /fleet postmortem timeline
            for node in ast.walk(mod):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id in ("FLEET_EVENTS",) \
                        and isinstance(node.value, ast.Tuple):
                    for e in node.value.elts:
                        s = _str_const(e)
                        if s:
                            reason_names.append((s, f"{rel} (fleet)"))
    seen: Set[str] = set()
    for name, origin in reason_names:
        if name in seen:
            continue
        seen.add(name)
        if name not in tests_blob:
            _fail(findings, origin.split(" ")[0], 1,
                  f"reason '{name}' ({origin}) has no test pin under "
                  "tests/ — an unasserted reason is free to drift")

    # 3a. flag references resolve to declarations
    declared_flags: Set[str] = set()
    for _rel, _text, mod in pkg:
        for node in ast.walk(mod):
            if isinstance(node, ast.Call) \
                    and call_name(node) == "define_flag" and node.args:
                s = _str_const(node.args[0])
                if s:
                    declared_flags.add(s)
    for rel, text, mod in pkg + _parse_all(tree, tests):
        lines = text.splitlines()
        for node in ast.walk(mod):
            if isinstance(node, ast.Call) \
                    and call_name(node) in _FLAG_READERS and node.args:
                s = _str_const(node.args[0])
                if s and s not in declared_flags \
                        and not _allowed(lines, node.lineno):
                    _fail(findings, rel, node.lineno,
                          f"flag '{s}' is read/set but never declared "
                          "with define_flag — typo or renamed flag")

    # 3b. PassiveDimension label discipline
    for rel, text, mod in pkg:
        if rel.endswith("bvar/multi_dimension.py"):
            continue      # the class definition itself
        lines = text.splitlines()
        for node in ast.walk(mod):
            if not (isinstance(node, ast.Call)
                    and call_name(node) in ("PassiveDimension",
                                             "_PassiveDim")):
                continue
            if _allowed(lines, node.lineno):
                continue
            if not node.args:
                continue
            labels = node.args[0]
            if not isinstance(labels, (ast.Tuple, ast.List)) or not all(
                    _str_const(e) for e in labels.elts):
                _fail(findings, rel, node.lineno,
                      "PassiveDimension labels must be a literal tuple "
                      "of names (dynamic label sets are unbounded)")
                continue
            names = [_str_const(e) for e in labels.elts]
            if len(names) > 4:
                _fail(findings, rel, node.lineno,
                      f"PassiveDimension declares {len(names)} labels "
                      "— cardinality explodes multiplicatively")
            if "tenant" in names and "_MAX_TENANTS" not in text \
                    and "TENANT_OVERFLOW" not in text:
                _fail(findings, rel, node.lineno,
                      "tenant-labeled family without a visible "
                      "cardinality bound (_MAX_TENANTS/TENANT_OVERFLOW) "
                      "in the module")
    return findings


def name_of(prefix: str) -> str:
    return _ENUM_PREFIX.get(prefix, prefix)
