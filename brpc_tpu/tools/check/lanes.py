"""Analyzer 2 — five-lane invariant linter.

Until the interceptor-pipeline refactor (ROADMAP item 5) lands, the
mandatory request stages are hand-replicated across all five server
dispatch paths.  This AST pass asserts, per lane:

1. the SHARED admission stage runs, and runs BEFORE user code;
2. deadline shedding (``maybe_shed``) runs before user code;
3. trace extraction happens (``start_server_span`` family /
   ``parse_traceparent``);
4. the MethodStatus settle (``on_responded``) is present in the lane
   (directly or in its completion closure);
5. rejection serialization goes through the SHARED helpers — both HTTP
   lanes through ``http_reject``, tpu_std lanes through the classic
   error builder with the rejection's code, the gRPC lane through
   grpc-status 8 (RESOURCE_EXHAUSTED) — so a new lane cannot invent a
   private (and drifting) rejection wire shape.

"User code" is the ``entry.fn`` / ``entry.raw_fn`` invocation (the
slim shims call it through their ``_fn`` closure binding).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from .base import Finding, Tree, call_name

# per-lane spec: module, path to the lane function, which names count
# as each stage, and how a rejection must serialize
LANES = (
    {
        # full tpu_std lane — the FIFTH (final) interceptor-chain
        # binding, completing ROADMAP item 1: admission → controller/
        # attachment/ici/shm staging → trace extract → deadline
        # arm+shed live in compile_rpc_chain; the lane body keeps only
        # the protocol concerns (find_method, auth, user interceptor,
        # decompress/parse, user code) and funnels every completion
        # through the chain settle inside its send closure
        "lane": "tpu_std",
        "path": "brpc_tpu/server/rpc_dispatch.py",
        "func": ["process_rpc_request"],
        "reject": {"kind": "call", "names": {"_send_error"}},
        "chain": {
            "path": "brpc_tpu/server/interceptors.py",
            "func": ["compile_rpc_chain", "enter"],
            "settle_func": ["compile_rpc_chain", "settle"],
            "entry_names": {"_enter", "enter"},
            "settle_names": {"_settle", "settle"},
        },
    },
    {
        # kind-3 slim lane — the SECOND interceptor-chain binding
        # (mechanical port of ROADMAP item 1): the cross-cutting
        # stages live in the compiled chain; the lane body calls
        # enter before user code and settle after.  Its precompiled
        # fast template (trivial shapes only — no admission layers,
        # no trace/tenant) is the documented exception and keeps its
        # own shed call, which this spec's chain half does not weaken:
        # the chain is still checked end to end.
        "lane": "slim",
        "path": "brpc_tpu/server/slim_dispatch.py",
        "func": ["make_slim_handler", "slim"],
        "reject": {"kind": "call", "names": {"_send_error"}},
        "chain": {
            "path": "brpc_tpu/server/interceptors.py",
            "func": ["compile_chain", "enter"],
            "settle_func": ["compile_chain", "settle"],
            "entry_names": {"_enter", "enter"},
            "settle_names": {"_settle", "settle"},
        },
    },
    {
        # classic HTTP bridge — the THIRD interceptor-chain binding:
        # admission/trace/deadline live in compile_http_chain; the lane
        # body builds its HTTP send closure, calls enter before user
        # code, and settles every response shape through the chain
        "lane": "http",
        "path": "brpc_tpu/server/http_dispatch.py",
        "func": ["_bridge_rpc"],
        "reject": {"kind": "call", "names": {"http_reject", "_reject"}},
        "chain": {
            "path": "brpc_tpu/server/interceptors.py",
            "func": ["compile_http_chain", "enter"],
            "settle_func": ["compile_http_chain", "settle"],
            "entry_names": {"_enter", "enter"},
            "settle_names": {"_settle", "settle"},
        },
    },
    {
        # kind-4 slim HTTP lane — the FOURTH interceptor-chain binding:
        # admission/trace/deadline-shed live in compile_http_slim_chain
        # (rejections and sheds come back as inline slim tuples); the
        # shim body keeps only the cell/deliver plumbing and settles
        # every response shape through the chain
        "lane": "http_slim",
        "path": "brpc_tpu/server/http_slim.py",
        "func": ["make_http_slim_handler", "slim"],
        "reject": {"kind": "call", "names": {"http_reject", "_reject"}},
        "chain": {
            "path": "brpc_tpu/server/interceptors.py",
            "func": ["compile_http_slim_chain", "enter"],
            "settle_func": ["compile_http_slim_chain", "settle"],
            "entry_names": {"_enter", "enter"},
            "settle_names": {"_settle", "settle"},
        },
    },
    {
        "lane": "grpc",
        "path": "brpc_tpu/protocol/h2_rpc.py",
        "func": ["_process_grpc"],
        "reject": {"kind": "grpc8"},
    },
    {
        # fully-buffered requests on @grpc_streaming methods ride this
        # fiber body instead of _process_grpc's unary path; no span
        # machinery there (streams are not traced), so trace/shed are
        # not required — admission + settle + grpc-status 8 are
        "lane": "grpc_streaming",
        "path": "brpc_tpu/protocol/h2_rpc.py",
        "func": ["_run_streaming_handler"],
        "reject": {"kind": "grpc8"},
        "optional": {"trace", "shed"},
    },
    {
        # kind-5 streaming lane — the FIRST interceptor-chain BINDING
        # (ROADMAP item 1): the cross-cutting stages live in the
        # compiled chain (server/interceptors.py), not the lane body.
        # The linter checks the CHAIN for admission→shed ordering,
        # trace extraction and the shared rejection serializer, and
        # the LANE BODY for chain-enter-before-user-code plus the
        # settle call — a binding lane cannot drop or reorder a stage
        # without one of the two halves failing here.
        "lane": "stream_slim",
        "path": "brpc_tpu/server/stream_slim.py",
        "func": ["make_stream_handler", "slim"],
        "reject": {"kind": "call", "names": {"_send_error"}},
        "chain": {
            "path": "brpc_tpu/server/interceptors.py",
            "func": ["compile_chain", "enter"],
            "settle_func": ["compile_chain", "settle"],
            "entry_names": {"_enter", "enter"},
            "settle_names": {"_settle", "settle"},
        },
    },
)

ADMIT_NAMES = {"admit", "_admit", "_admit_rpc", "_admit_stage",
               "_trivial", "trivial_shape"}
SHED_NAMES = {"maybe_shed", "_maybe_shed", "_shed"}
TRACE_NAMES = {"start_server_span", "passive_server_span",
               "parse_traceparent", "_sample", "_pspan"}
SETTLE_NAMES = {"on_responded"}
USER_FN_NAMES = {"fn", "_fn", "raw_fn"}


def _fail(findings, path, line, lane, msg):
    findings.append(Finding("lanes", path, line, f"[{lane}] {msg}"))


def _find_func(mod: ast.Module, qualpath: Sequence[str]
               ) -> Optional[ast.FunctionDef]:
    scope: Sequence[ast.stmt] = mod.body
    node = None
    for name in qualpath:
        node = None
        for n in scope:
            if isinstance(n, (ast.FunctionDef, ast.ClassDef)) \
                    and n.name == name:
                node = n
                break
        if node is None:
            return None
        scope = node.body
    return node if isinstance(node, ast.FunctionDef) else None




def _calls(func: ast.FunctionDef) -> List[ast.Call]:
    return [n for n in ast.walk(func) if isinstance(n, ast.Call)]


def _first_line(calls: List[ast.Call], names: Set[str]
                ) -> Optional[int]:
    lines = [c.lineno for c in calls if call_name(c) in names]
    return min(lines) if lines else None


def _rejection_blocks(func: ast.FunctionDef) -> List[ast.If]:
    """``if rej is not None:``-shaped guards (any If whose test reads a
    name ending in ``rej``)."""
    out = []
    for n in ast.walk(func):
        if isinstance(n, ast.If):
            for sub in ast.walk(n.test):
                if isinstance(sub, ast.Name) and sub.id.endswith("rej"):
                    out.append(n)
                    break
    return out


def _block_has_call(block: ast.If, names: Set[str]) -> bool:
    return any(isinstance(n, ast.Call) and call_name(n) in names
               for stmt in block.body for n in ast.walk(stmt))


def _block_has_grpc8(block: ast.If) -> bool:
    """A send_grpc_response(..., 8, ...) / _finish(8, ...) call —
    RESOURCE_EXHAUSTED is the one legal admission-rejection status."""
    for stmt in block.body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call) \
                    and call_name(n) in ("send_grpc_response",
                                          "_finish"):
                if any(isinstance(a, ast.Constant) and a.value == 8
                       for a in n.args):
                    return True
    return False


def _check_chain_lane(tree: Tree, spec, findings: List[Finding]) -> None:
    """An interceptor-chain BINDING lane: the mandatory stages live in
    the compiled chain, the lane body only calls enter/settle.  Two
    halves, both machine-checked:

    - CHAIN (interceptors.enter): admission present and BEFORE the
      deadline shed; trace extraction present; every ``if rej:`` block
      serializes through the shared helper;
    - LANE BODY: the chain-enter call runs BEFORE user code, and the
      settle half (chain ``settle`` with the MethodStatus
      ``on_responded``) is actually invoked.
    """
    lane, path = spec["lane"], spec["path"]
    chain = spec["chain"]
    cpath = chain["path"]
    try:
        cmod = ast.parse(tree.text(cpath))
    except SyntaxError as e:
        _fail(findings, cpath, e.lineno or 1, lane,
              f"chain syntax error: {e.msg}")
        return
    enter = _find_func(cmod, chain["func"])
    if enter is None:
        _fail(findings, cpath, 1, lane,
              f"chain function {'.'.join(chain['func'])} not found")
        return
    ccalls = _calls(enter)
    admit_at = _first_line(ccalls, ADMIT_NAMES)
    shed_at = _first_line(ccalls, SHED_NAMES)
    trace_at = _first_line(ccalls, TRACE_NAMES)
    if admit_at is None:
        _fail(findings, cpath, enter.lineno, lane,
              "chain enter is missing the mandatory admission stage "
              "(server/admission.admit)")
    if shed_at is None:
        _fail(findings, cpath, enter.lineno, lane,
              "chain enter is missing the deadline shed "
              "(deadline.maybe_shed)")
    if admit_at is not None and shed_at is not None \
            and admit_at > shed_at:
        _fail(findings, cpath, admit_at, lane,
              "chain admission must precede the deadline shed "
              "(rejections are cheaper than armed deadlines)")
    if trace_at is None:
        _fail(findings, cpath, enter.lineno, lane,
              "chain enter is missing trace extraction "
              "(start_server_span family)")
    blocks = _rejection_blocks(enter)
    if admit_at is not None and not blocks:
        _fail(findings, cpath, enter.lineno, lane,
              "no `if rej is not None` rejection guard found in the "
              "chain — admission verdicts are not being honored")
    for block in blocks:
        if not _block_has_call(block, spec["reject"]["names"]):
            _fail(findings, cpath, block.lineno, lane,
                  "chain rejection block does not serialize through "
                  "the shared helper "
                  f"({' / '.join(sorted(spec['reject']['names']))})")
    settle_fn = _find_func(cmod, chain["settle_func"])
    if settle_fn is None or _first_line(_calls(settle_fn),
                                        SETTLE_NAMES) is None:
        _fail(findings, cpath, enter.lineno, lane,
              "chain settle half is missing the MethodStatus settle "
              "(on_responded) — admission in-flight counts would leak")
    # -- the lane body: enter-before-user-code + settle invoked --------
    try:
        mod = ast.parse(tree.text(path))
    except SyntaxError as e:
        _fail(findings, path, e.lineno or 1, lane,
              f"syntax error: {e.msg}")
        return
    func = _find_func(mod, spec["func"])
    if func is None:
        _fail(findings, path, 1, lane,
              f"lane function {'.'.join(spec['func'])} not found")
        return
    calls = _calls(func)
    user_at = _first_line(calls, USER_FN_NAMES)
    enter_at = _first_line(calls, set(chain["entry_names"]))
    settle_at = _first_line(calls, set(chain["settle_names"]))
    if user_at is None:
        _fail(findings, path, func.lineno, lane,
              "no user-code invocation (entry.fn/raw_fn) found — "
              "lane shape changed, update the linter spec")
        return
    if enter_at is None:
        _fail(findings, path, func.lineno, lane,
              "lane body never calls the compiled interceptor chain "
              "(enter) — the binding is gone")
    elif enter_at > user_at:
        _fail(findings, path, enter_at, lane,
              f"chain enter runs at line {enter_at}, AFTER user code "
              f"at line {user_at} — the stages must run first")
    if settle_at is None:
        _fail(findings, path, func.lineno, lane,
              "lane body never calls the chain settle half — "
              "fast completions would skip MethodStatus/rpcz")


def check_lanes(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    for spec in LANES:
        lane, path = spec["lane"], spec["path"]
        optional = spec.get("optional", set())
        if "chain" in spec:
            _check_chain_lane(tree, spec, findings)
            continue
        try:
            mod = ast.parse(tree.text(path))
        except SyntaxError as e:
            _fail(findings, path, e.lineno or 1, lane,
                  f"syntax error: {e.msg}")
            continue
        func = _find_func(mod, spec["func"])
        if func is None:
            _fail(findings, path, 1, lane,
                  f"lane function {'.'.join(spec['func'])} not found")
            continue
        calls = _calls(func)
        admit_at = _first_line(calls, ADMIT_NAMES)
        shed_at = _first_line(calls, SHED_NAMES)
        trace_at = _first_line(calls, TRACE_NAMES)
        settle_at = _first_line(calls, SETTLE_NAMES)
        user_at = _first_line(calls, USER_FN_NAMES)

        if user_at is None:
            _fail(findings, path, func.lineno, lane,
                  "no user-code invocation (entry.fn/raw_fn) found — "
                  "lane shape changed, update the linter spec")
            continue
        if admit_at is None:
            _fail(findings, path, func.lineno, lane,
                  "mandatory admission stage (server/admission.admit) "
                  "is missing")
        elif admit_at > user_at:
            _fail(findings, path, admit_at, lane,
                  f"admission runs at line {admit_at}, AFTER user code "
                  f"at line {user_at} — admission must be first")
        if "shed" not in optional:
            if shed_at is None:
                _fail(findings, path, func.lineno, lane,
                      "deadline shed (deadline.maybe_shed) is missing — "
                      "queue-expired requests would reach user code")
            elif shed_at > user_at:
                _fail(findings, path, shed_at, lane,
                      f"deadline shed at line {shed_at} runs after "
                      f"user code at line {user_at}")
            if admit_at is not None and shed_at is not None \
                    and admit_at > shed_at:
                _fail(findings, path, admit_at, lane,
                      "admission must precede the deadline shed "
                      "(rejections are cheaper than armed deadlines)")
        if "trace" not in optional and trace_at is None:
            _fail(findings, path, func.lineno, lane,
                  "trace extraction (start_server_span family) is "
                  "missing — requests on this lane would drop their "
                  "trace context")
        if settle_at is None:
            _fail(findings, path, func.lineno, lane,
                  "MethodStatus settle (on_responded) is missing — "
                  "admission in-flight counts would leak")

        # rejection serialization through the shared helpers
        blocks = _rejection_blocks(func)
        if admit_at is not None and not blocks:
            _fail(findings, path, func.lineno, lane,
                  "no `if rej is not None` rejection guard found — "
                  "admission verdicts are not being honored")
        rj = spec["reject"]
        for block in blocks:
            if rj["kind"] == "grpc8":
                ok = _block_has_grpc8(block)
                want = "grpc-status 8 (RESOURCE_EXHAUSTED)"
            else:
                ok = _block_has_call(block, rj["names"])
                want = " / ".join(sorted(rj["names"]))
            if not ok:
                _fail(findings, path, block.lineno, lane,
                      f"rejection block does not serialize through the "
                      f"shared helper ({want}) — lanes must not invent "
                      "private rejection wire shapes")
    return findings
