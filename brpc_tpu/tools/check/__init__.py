"""brpc_tpu.tools.check — the repo's static-analysis suite.

One command::

    python -m brpc_tpu.tools.check            # all four analyzers
    python -m brpc_tpu.tools.check --fail-fast

and one pytest surface (``tests/test_static_checks.py``) run the same
four analyzers:

- **contracts** — C++↔Python contract checker (closed fallback enums vs
  the Python reason-name tables, the TLV tag registry vs the engine's
  meta scans and pre-encoded prefixes, shim/callback call arities);
- **lanes** — five-lane invariant linter (admission first, deadline
  shed before user code, trace extract, MethodStatus settle, shared
  rejection serialization on every dispatch path);
- **enums** — closed-enum / flag / bvar-cardinality lint (every reason
  declared AND test-pinned, every flag string declared, every labeled
  family bounded);
- **blocking** — blocking-call detector over the loop-thread surfaces
  (slim shims, client demux delivery, finalizers).

Exit status of the CLI: 0 = clean tree, 1 = findings, 2 = suite error.
Analyzers read *source text* (no imports of the code under test) and
accept per-path overrides, so drifts can be seeded into copies — the
linter itself is covered by negative tests.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .base import Finding, Tree
from .blocking import check_blocking
from .contracts import check_contracts
from .enums import check_enums
from .lanes import check_lanes

ANALYZERS = (
    ("contracts", check_contracts),
    ("lanes", check_lanes),
    ("enums", check_enums),
    ("blocking", check_blocking),
)


def run_all(overrides: Optional[Dict[str, str]] = None,
            root: Optional[str] = None,
            only: Optional[Tuple[str, ...]] = None,
            fail_fast: bool = False) -> List[Finding]:
    """Run the suite over the tree (with optional source overrides for
    seeded-drift tests).  Returns every finding; ``fail_fast`` stops
    after the first analyzer that reports any."""
    tree = Tree(root=root, overrides=overrides)
    findings: List[Finding] = []
    for name, fn in ANALYZERS:
        if only and name not in only:
            continue
        findings.extend(fn(tree))
        if fail_fast and findings:
            break
    return findings


__all__ = ["ANALYZERS", "Finding", "Tree", "run_all",
           "check_blocking", "check_contracts", "check_enums",
           "check_lanes"]
