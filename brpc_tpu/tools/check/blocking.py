"""Analyzer 4 — blocking-call detector for loop-thread code.

The engine's loop threads and the client demux loop run Python in
batched GIL entries: the kind-3/kind-4 slim shims, the burst-end hook,
``ClientLane``'s burst delivery and ``Controller._on_plain_response``
all execute ON an event loop.  One blocking primitive there stalls
every connection the loop owns — exactly the class of bug ADVICE r5 #1
("a blocking handler must never freeze a loop") was about, and the one
thing runtime tests are worst at catching (the stall needs load +
timing to show).

This pass walks the AST from each loop-thread entry point, follows
*direct* calls into functions defined in the same module (handoffs —
``fiber_runtime.spawn``, ``ExecutionQueue.execute``, timers — are
boundaries by design: the callee runs elsewhere), and flags blocking
primitives:

- ``time.sleep`` / bare ``sleep``
- ``.join()`` / ``.wait()`` / ``.wait_for(pred)`` without a timeout
- explicit ``.acquire()`` without a timeout (``with lock:`` around a
  short critical section is the sanctioned shape and is not flagged)
- versioned-id ``idp.lock()`` (parks the caller until the id frees;
  loop code must use ``try_lock`` and hop to a fiber)
- blocking socket ops (``.recv``/``.accept``/``.connect``/
  ``create_connection``), ``select.select`` without timeout
- ``subprocess.run``/``check_output``/``os.system``

A reviewed exception suppresses itself with a ``static-check: allow``
comment on the flagged line.  User code invoked by the shims
(``entry.fn``) is the documented ``usercode_inline`` contract and is
not followed.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .base import ALLOW_MARK, Finding, Tree

# (module, dotted function path) entry points that run on an engine /
# demux loop thread (or in a weakref finalizer, which may fire on one)
ENTRY_POINTS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("brpc_tpu/server/slim_dispatch.py", ("make_slim_handler", "slim")),
    ("brpc_tpu/server/slim_dispatch.py", ("flush_burst_accounting",)),
    ("brpc_tpu/server/http_slim.py",
     ("make_http_slim_handler", "slim")),
    ("brpc_tpu/transport/client_lane.py", ("ClientLane", "_on_burst")),
    # per-demux-loop burst entry — the cross-loop completion handoff
    # delivery callback (ISSUE 11): completions parsed on one demux
    # loop are handed to callers on any other thread/loop, so its
    # whole reachable body runs ON a loop
    ("brpc_tpu/transport/client_lane.py",
     ("ClientLane", "_on_loop_burst")),
    ("brpc_tpu/transport/client_lane.py",
     ("ClientLane", "_complete_burst")),
    ("brpc_tpu/transport/client_lane.py",
     ("ClientLane", "_enqueue_classic")),
    ("brpc_tpu/client/controller.py",
     ("Controller", "_on_plain_response")),
    # slot-settle finalizers: fire on whichever thread drops the last
    # reference to a response view — possibly a demux loop
    ("brpc_tpu/transport/shm_ring.py", ("client_complete",)),
    ("brpc_tpu/transport/shm_ring.py", ("wrap_view_iobuf",)),
    # per-loop shm sweep + response staging (ISSUE 11): EV_CLOSE lands
    # the dead-conn slot sweep on the owning engine loop, and the slim
    # shims stage response attachments into the sharded allocator from
    # their loop thread
    ("brpc_tpu/transport/shm_ring.py", ("on_socket_closed",)),
    ("brpc_tpu/transport/shm_ring.py", ("ShmRing", "free_owner")),
    ("brpc_tpu/transport/shm_ring.py", ("describe_response_att",)),
    # operability plane (ISSUE 12): the drain/hot-restart paths are
    # DEADLINE-BOUNDED by contract — every wait they reach must carry
    # a timeout (a drain that can hang forever defeats the grace), so
    # they live in the same un-timed-primitive lint as loop code.
    # Intentional bounded socket ops (settimeout'd handoff accept/
    # connect) carry reviewed allow-markers.
    ("brpc_tpu/server/server.py", ("Server", "drain")),
    ("brpc_tpu/server/server.py", ("Server", "join")),
    ("brpc_tpu/transport/shm_ring.py", ("drain_settle",)),
    ("brpc_tpu/transport/client_lane.py", ("drain_settle",)),
    ("brpc_tpu/server/hot_restart.py", ("handoff_listeners",)),
    ("brpc_tpu/server/hot_restart.py", ("import_listeners",)),
    # kind-5 streaming lane (ISSUE 13): the stream-open shim and the
    # batched chunk delivery run inside the engine's per-burst GIL
    # entry, ON a loop thread; the compiled interceptor chain they
    # bind is loop-thread code by the same contract
    ("brpc_tpu/server/stream_slim.py",
     ("make_stream_handler", "slim")),
    ("brpc_tpu/server/stream_slim.py", ("slim_chunks",)),
    ("brpc_tpu/server/interceptors.py", ("compile_chain", "enter")),
    ("brpc_tpu/server/interceptors.py", ("compile_chain", "settle")),
    # drain-path stream settle: deadline-bounded by contract, same
    # un-timed-primitive lint as Server.drain
    ("brpc_tpu/streaming.py", ("drain_server_streams",)),
    ("brpc_tpu/streaming.py", ("Stream", "drain_close")),
    # KV transfer plane (ISSUE 15): the page sweep fires from
    # Socket.release on the owning loop; the drain settle is
    # deadline-bounded by contract; the transport's lease settle runs
    # on the handoff completion path (possibly a demux loop)
    ("brpc_tpu/kv/pages.py", ("on_socket_closed",)),
    ("brpc_tpu/kv/pages.py", ("KvPageStore", "release_owner")),
    ("brpc_tpu/kv/pages.py", ("drain_settle",)),
    ("brpc_tpu/kv/transport.py", ("KvTransport", "_settle")),
    # SLO-tiered scheduler (ISSUE 17): the chunk-prefill round and the
    # speculative-decode round run inside the batcher's step loop —
    # every live session's next token waits on them, so a blocking
    # primitive there is an ITL stall for the whole slot pool (the
    # step loop itself, _run, carries its sanctioned idle sleep and is
    # not entry-listed; these rounds must stay primitive-free)
    ("brpc_tpu/models/lm_service.py",
     ("ContinuousBatcher", "_chunk_round")),
    ("brpc_tpu/models/lm_service.py",
     ("ContinuousBatcher", "_spec_round")),
    # the fourth chain binding (http_slim): enter/settle run inside
    # the kind-4 shim's per-burst GIL entry, on a loop thread
    ("brpc_tpu/server/interceptors.py",
     ("compile_http_slim_chain", "enter")),
    ("brpc_tpu/server/interceptors.py",
     ("compile_http_slim_chain", "settle")),
    # serving observability (ISSUE 18): every write-side telemetry hook
    # runs inside the batcher's step loop — a lock or sleep there is an
    # ITL stall for the whole slot pool, so the write paths are plain
    # GIL-atomic list/dict increments on the ONE batcher thread (the
    # reader side, LmTelemetryCache, holds its snapshot lock off-loop
    # and is deliberately NOT entry-listed)
    ("brpc_tpu/models/lm_telemetry.py", ("record_phase",)),
    ("brpc_tpu/models/lm_telemetry.py", ("on_emit",)),
    ("brpc_tpu/models/lm_telemetry.py", ("open_timeline",)),
    ("brpc_tpu/models/lm_telemetry.py", ("close_timeline",)),
    ("brpc_tpu/models/lm_telemetry.py", ("count_slo",)),
    # fleet observability: the flight-recorder write path runs inside
    # Server.drain and the KV evict/spill paths, and the report builder
    # runs inside the KV.Probe handler — neither may ever grow a sleep,
    # an untimed wait, or socket work (cadence + transport live in
    # FleetReporter, which is a plain daemon thread)
    ("brpc_tpu/fleet.py", ("record_event",)),
    ("brpc_tpu/fleet.py", ("build_load_report",)),
)

# names whose call is a handoff, not an execution: arguments/targets
# run on another thread, so they are not followed
_HANDOFF = {"spawn", "execute", "schedule", "unschedule", "start"}

# user-code closure bindings the shims invoke under the documented
# inline contract — not followed, not flagged
_USER_CODE = {"fn", "_fn", "raw_fn"}

_SUBPROC = {"run", "call", "check_call", "check_output", "system",
            "popen"}
_SOCK_OPS = {"recv", "recv_into", "accept", "connect",
             "create_connection", "getaddrinfo", "gethostbyname"}


def _fail(findings, path, line, chain, msg):
    via = " -> ".join(chain)
    findings.append(Finding("blocking", path, line, f"[{via}] {msg}"))


def _call_parts(call: ast.Call) -> Tuple[Optional[str], Optional[str]]:
    """(receiver, attr_or_name): ('time','sleep') for time.sleep(...),
    (None,'sleep') for sleep(...), ('self','_foo') for self._foo()."""
    f = call.func
    if isinstance(f, ast.Name):
        return None, f.id
    if isinstance(f, ast.Attribute):
        recv = None
        if isinstance(f.value, ast.Name):
            recv = f.value.id
        return recv, f.attr
    return None, None


def _has_timeout(call: ast.Call) -> bool:
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    return False


class _ModuleIndex:
    """Function lookup for one module: module-level defs, class
    methods, and nested defs addressed by their enclosing chain."""

    def __init__(self, rel: str, text: str):
        self.rel = rel
        self.lines = text.splitlines()
        self.mod = ast.parse(text)
        # flat name -> def node (last one wins is fine for this tree)
        self.funcs: Dict[str, ast.FunctionDef] = {}
        self.methods: Dict[Tuple[str, str], ast.FunctionDef] = {}
        for node in self.mod.body:
            if isinstance(node, ast.FunctionDef):
                self._index_nested(node)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        self.methods[(node.name, sub.name)] = sub
                        self.funcs.setdefault(sub.name, sub)
        self.time_sleep_names = self._sleep_imports()

    def _index_nested(self, node: ast.FunctionDef) -> None:
        self.funcs.setdefault(node.name, node)
        for sub in ast.walk(node):
            if isinstance(sub, ast.FunctionDef) and sub is not node:
                self.funcs.setdefault(sub.name, sub)

    def _sleep_imports(self) -> Set[str]:
        out = set()
        for node in ast.walk(self.mod):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name == "sleep":
                        out.add(a.asname or a.name)
        return out

    def resolve(self, path: Sequence[str]) -> Optional[ast.FunctionDef]:
        if len(path) == 1:
            return self.funcs.get(path[0])
        node = self.methods.get((path[0], path[1]))
        if node is not None and len(path) == 2:
            return node
        # nested chain (make_slim_handler -> slim)
        cur: Optional[ast.FunctionDef] = self.funcs.get(path[0])
        for name in path[1:]:
            if cur is None:
                return None
            nxt = None
            for sub in ast.walk(cur):
                if isinstance(sub, ast.FunctionDef) and sub.name == name:
                    nxt = sub
                    break
            cur = nxt
        return cur

    def allowed(self, line: int) -> bool:
        return 0 < line <= len(self.lines) \
            and ALLOW_MARK in self.lines[line - 1]


def _scan_function(idx: _ModuleIndex, func: ast.FunctionDef,
                   chain: List[str], visited: Set[str],
                   findings: List[Finding], depth: int) -> None:
    # nested defs inside this function run when *called*; the shims'
    # completion closures DO run inline, so nested bodies are scanned
    # as part of the parent (they share the loop thread unless handed
    # off, and handoff args are not followed at all)
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        recv, name = _call_parts(node)
        if name is None or idx.allowed(node.lineno):
            continue
        line = node.lineno

        if name == "sleep" and (recv == "time"
                                or (recv is None
                                    and "sleep" in idx.time_sleep_names)):
            _fail(findings, idx.rel, line, chain,
                  "time.sleep on a loop thread stalls every connection "
                  "the loop owns")
        elif name == "join" and not node.args and not node.keywords:
            _fail(findings, idx.rel, line, chain,
                  ".join() without a timeout blocks the loop thread")
        elif name == "wait" and not node.args and not _has_timeout(node):
            _fail(findings, idx.rel, line, chain,
                  ".wait() without a timeout blocks the loop thread")
        elif name == "wait_for" and len(node.args) < 2 \
                and not _has_timeout(node):
            _fail(findings, idx.rel, line, chain,
                  ".wait_for(pred) without a timeout blocks the loop "
                  "thread")
        elif name == "acquire" and not node.args \
                and not _has_timeout(node) \
                and not any(kw.arg == "blocking" for kw in node.keywords):
            _fail(findings, idx.rel, line, chain,
                  "un-timed .acquire() blocks the loop thread (use a "
                  "timeout, try-acquire, or a short `with lock:`)")
        elif name == "lock" and recv in ("idp", "pool", "id_pool"):
            _fail(findings, idx.rel, line, chain,
                  "versioned-id .lock() parks the caller until the id "
                  "frees — loop code must try_lock and hop to a fiber")
        elif name in _SOCK_OPS:
            _fail(findings, idx.rel, line, chain,
                  f"blocking socket op .{name}() on a loop thread")
        elif name == "select" and recv == "select" \
                and len(node.args) < 4:
            _fail(findings, idx.rel, line, chain,
                  "select.select without a timeout blocks the loop")
        elif name in _SUBPROC and recv in ("subprocess", "os"):
            _fail(findings, idx.rel, line, chain,
                  f"{recv}.{name} blocks the loop thread on a child "
                  "process")

        # follow same-module direct calls (not handoffs / user code)
        if depth <= 0 or name in _HANDOFF or name in _USER_CODE:
            continue
        target = None
        if recv in (None, "self", "_self"):
            target = idx.funcs.get(name)
        if target is not None and name not in visited \
                and target is not func:
            visited.add(name)
            _scan_function(idx, target, chain + [name], visited,
                          findings, depth - 1)


def check_blocking(tree: Tree) -> List[Finding]:
    findings: List[Finding] = []
    indexes: Dict[str, _ModuleIndex] = {}
    for rel, path in ENTRY_POINTS:
        if rel not in indexes:
            try:
                indexes[rel] = _ModuleIndex(rel, tree.text(rel))
            except (OSError, SyntaxError) as e:
                findings.append(Finding("blocking", rel, 1,
                                        f"cannot analyze: {e}"))
                continue
        idx = indexes[rel]
        func = idx.resolve(path)
        if func is None:
            findings.append(Finding(
                "blocking", rel, 1,
                f"entry point {'.'.join(path)} not found — loop-thread "
                "surface changed, update the detector spec"))
            continue
        _scan_function(idx, func, [".".join(path)], {path[-1]},
                      findings, depth=4)
    return findings
