#!/bin/sh
# Static-analysis gate, pre-commit / CI shape:
#
#     brpc_tpu/tools/check/run_all.sh            # whole suite
#     brpc_tpu/tools/check/run_all.sh --fail-fast
#
# Exit 0 = clean tree, 1 = findings, 2 = suite error — plain
# `python -m brpc_tpu.tools.check` semantics, from any cwd.
set -eu
cd "$(dirname "$0")/../../.."
exec "${PYTHON:-python3}" -m brpc_tpu.tools.check "$@"
