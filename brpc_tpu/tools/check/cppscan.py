"""Clang-free C++ scanning for ``native/src/engine.cpp``.

The engine is one hand-written translation unit with a deliberately
regular style (closed ``enum X : int { ... }`` bodies, ``static const
char* kNames[] = {...}`` mirrors, ``PyObject_CallFunction*`` shim
entries), so regex + balanced-paren extraction is enough to read the
contracts out of it — no clang, no compile step, runs in milliseconds
as a tier-1 test.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

_LINE_COMMENT = re.compile(r"//[^\n]*")
_BLOCK_COMMENT = re.compile(r"/\*.*?\*/", re.S)


def strip_comments(text: str) -> str:
    # newline-preserving: line numbers computed on the stripped text
    # still point into the original file
    text = _BLOCK_COMMENT.sub(lambda m: "\n" * m.group(0).count("\n"),
                              text)
    return _LINE_COMMENT.sub("", text)


def parse_enum(text: str, name: str) -> Optional[List[str]]:
    """Member identifiers of ``enum <name> : int { ... }`` in
    declaration order (values/sentinels included — callers drop the
    trailing ``*_REASONS``/``k*`` counter if present)."""
    m = re.search(r"enum\s+%s\s*:\s*int\s*\{" % re.escape(name), text)
    if m is None:
        return None
    body = text[m.end():]
    end = body.find("};")
    if end < 0:
        return None
    body = strip_comments(body[:end])
    members = []
    for item in body.split(","):
        item = item.strip()
        if not item:
            continue
        ident = item.split("=")[0].strip()
        if re.fullmatch(r"[A-Za-z_]\w*", ident):
            members.append(ident)
    return members


def parse_string_array(text: str, name: str) -> Optional[List[str]]:
    """String literals of ``const char* <name>[...] = { "...", ... };``."""
    m = re.search(r"%s\s*\[[^\]]*\]\s*=\s*\{" % re.escape(name), text)
    if m is None:
        return None
    body = text[m.end():]
    end = body.find("};")
    if end < 0:
        return None
    return re.findall(r'"([^"]*)"', strip_comments(body[:end]))


def _balanced(text: str, open_idx: int) -> str:
    """Text of the balanced paren group starting at ``open_idx`` ('(')."""
    depth = 0
    for i in range(open_idx, len(text)):
        c = text[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return text[open_idx + 1:i]
    return text[open_idx + 1:]


def _split_args(argtext: str) -> List[str]:
    """Top-level comma split of a C call's argument text."""
    out, depth, cur = [], 0, []
    for c in argtext:
        if c in "([{":
            depth += 1
        elif c in ")]}":
            depth -= 1
        if c == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(c)
    tail = "".join(cur).strip()
    if tail:
        out.append(tail)
    return out


def call_sites(text: str, fn: str, first_arg: str) -> List[Tuple[int, List[str]]]:
    """Every ``fn(first_arg, ...)`` call: (offset, arg list).  The arg
    list excludes ``first_arg`` itself and a trailing ``nullptr``
    varargs sentinel."""
    clean = strip_comments(text)
    out = []
    for m in re.finditer(re.escape(fn) + r"\s*\(", clean):
        args = _split_args(_balanced(clean, m.end() - 1))
        if not args or args[0].replace(" ", "") != first_arg.replace(" ", ""):
            continue
        rest = args[1:]
        if rest and rest[-1] == "nullptr":
            rest = rest[:-1]
        out.append((m.start(), rest))
    return out


def callfunction_formats(text: str, target: str) -> List[str]:
    """Format strings of every ``PyObject_CallFunction(<target>, "fmt",
    ...)`` site (the arity contract of the format-driven entries)."""
    clean = strip_comments(text)
    out = []
    for m in re.finditer(r"PyObject_CallFunction\s*\(", clean):
        args = _split_args(_balanced(clean, m.end() - 1))
        if len(args) < 2:
            continue
        if args[0].replace(" ", "") != target.replace(" ", ""):
            continue
        fm = re.fullmatch(r'"([^"]*)"', args[1])
        if fm:
            out.append(fm.group(1))
    return out


def scan_case_tags(text: str, func_name: str) -> Dict[int, Optional[int]]:
    """TLV ``case N:`` labels inside one function body, mapped to the
    fixed length the engine enforces there (``if (ln != K) return``) or
    None for variable-length fields."""
    m = re.search(r"\b%s\s*\(" % re.escape(func_name), text)
    if m is None:
        return {}
    # function body: first '{' after the signature, balanced to close
    start = text.find("{", m.end())
    depth = 0
    end = start
    for i in range(start, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                end = i
                break
    body = strip_comments(text[start:end])
    tags: Dict[int, Optional[int]] = {}
    # fallthrough case groups share one handler: collect runs
    for m2 in re.finditer(
            r"((?:case\s+\d+\s*:\s*)+)((?:(?!case\s+\d+\s*:|default\s*:).)*)",
            body, re.S):
        labels = [int(x) for x in re.findall(r"case\s+(\d+)\s*:",
                                             m2.group(1))]
        handler = m2.group(2)
        lm = re.search(r"ln\s*!=\s*(\d+)", handler)
        need = int(lm.group(1)) if lm else None
        for t in labels:
            tags[t] = need
    return tags


def literal_tag_checks(text: str) -> List[int]:
    """Every ``tag == N`` / ``tag != N`` literal comparison in the file
    — the ad-hoc TLV walks (client demux meta scan, plain-response
    classification) reference tags this way instead of via case labels."""
    clean = strip_comments(text)
    return sorted({int(n) for n in
                   re.findall(r"\btag\s*[!=]=\s*(\d+)", clean)})


def used_enum_tokens(text: str, prefixes: Tuple[str, ...]) -> Dict[str, int]:
    """Every ``FB_*``-style identifier used anywhere in the file →
    first line number.  Compared against the declared enum bodies to
    catch a counter bumped under a member that was never declared (or
    was deleted while call sites remained)."""
    out: Dict[str, int] = {}
    for i, line in enumerate(strip_comments(text).splitlines(), 1):
        for m in re.finditer(r"\b(%s)[A-Z0-9_]*\b"
                             % "|".join(re.escape(p) for p in prefixes),
                             line):
            tok = m.group(0)
            out.setdefault(tok, i)
    return out
