"""Shared plumbing for the repo's static-analysis suite.

Every analyzer is a function ``(tree: Tree) -> list[Finding]``.  A
:class:`Tree` hands out *source text* (never imports the code under
analysis), and accepts per-path overrides so the suite's own negative
tests can seed a drift into a copy of a file and assert the analyzer
catches it — the linter is itself testable by construction.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Tuple

# repo root: brpc_tpu/tools/check/base.py -> three levels up
_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

# suppression marker: a flagged line carrying this comment is skipped
# (the analyzers are heuristic; a reviewed exception states itself in
# the source instead of weakening the rule)
ALLOW_MARK = "static-check: allow"


class Finding:
    """One analyzer finding: where and what."""

    __slots__ = ("analyzer", "path", "line", "message")

    def __init__(self, analyzer: str, path: str, line: int,
                 message: str):
        self.analyzer = analyzer
        self.path = path
        self.line = line
        self.message = message

    def __repr__(self) -> str:
        return f"{self.path}:{self.line}: [{self.analyzer}] {self.message}"


class Tree:
    """Source access for the analyzers.  ``overrides`` maps repo-relative
    paths to replacement text (the seeded-drift test hook); everything
    else reads from disk under ``root``."""

    def __init__(self, root: Optional[str] = None,
                 overrides: Optional[Dict[str, str]] = None):
        self.root = root or _ROOT
        self.overrides = dict(overrides or {})

    def path(self, rel: str) -> str:
        return os.path.join(self.root, rel)

    def exists(self, rel: str) -> bool:
        return rel in self.overrides or os.path.exists(self.path(rel))

    def text(self, rel: str) -> str:
        if rel in self.overrides:
            return self.overrides[rel]
        with open(self.path(rel), "r", encoding="utf-8",
                  errors="replace") as f:
            return f.read()

    def _walk_py(self, base_rel: str) -> Iterable[str]:
        base = self.path(base_rel)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    full = os.path.join(dirpath, fn)
                    yield os.path.relpath(full, self.root)

    def package_files(self) -> List[Tuple[str, str]]:
        """(relpath, text) for every .py under brpc_tpu/ (overrides
        applied; override-only paths under the package are included)."""
        rels = set(self._walk_py("brpc_tpu"))
        rels.update(r for r in self.overrides
                    if r.startswith("brpc_tpu") and r.endswith(".py"))
        return [(r, self.text(r)) for r in sorted(rels)]

    def test_files(self) -> List[Tuple[str, str]]:
        rels = set(self._walk_py("tests"))
        rels.update(r for r in self.overrides
                    if r.startswith("tests") and r.endswith(".py"))
        return [(r, self.text(r)) for r in sorted(rels)]


def public_arity(func_def) -> int:
    """Count of a ``def``'s *public* parameters — the call-contract
    arity.  Excludes ``self``/``cls`` and the underscore-prefixed
    default-bound privates the fast paths use to pin globals
    (``_server=server`` closures are implementation, not interface)."""
    args = func_def.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return sum(1 for n in names if not n.startswith("_"))


def call_name(call: ast.Call) -> Optional[str]:
    """Bare/attr callee name of an ast.Call (``foo(...)`` and
    ``x.foo(...)`` both resolve to ``"foo"``) — the one call-site
    identity every analyzer matches on."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None
