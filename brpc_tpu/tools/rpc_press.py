"""rpc_press — generic load generator.

≈ /root/reference/tools/rpc_press/rpc_press_impl.h:106 (RpcPress):
drive any service/method at a target QPS (or flat out), print live
qps/latency percentiles, report a summary.  Programmatic API first
(the tests and bench drive it); `python -m brpc_tpu.tools.rpc_press`
for the command line.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Callable, List, Optional

from ..bvar.latency_recorder import LatencyRecorder
from ..client import Channel, ChannelOptions, Controller


class PressOptions:
    def __init__(self):
        self.server = ""                 # "ip:port" or naming url
        self.lb_name = ""                # for cluster targets
        self.method = ""                 # "Service.Method"
        self.qps = 0                     # 0 = as fast as possible
        self.duration_s = 0.0            # 0 = until stop()
        self.threads = 1
        self.connection_type = "pooled"
        self.timeout_ms = 1000
        self.input: Any = b""            # payload bytes, or list of payloads
        self.attachment: bytes = b""
        self.report_interval_s = 1.0
        self.report: Optional[Callable[[str], None]] = None  # default: stderr


class Press:
    def __init__(self, options: PressOptions):
        self.options = options
        self.latency = LatencyRecorder("rpc_press")
        self.sent = 0
        self.errors = 0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()

    # -- control -----------------------------------------------------------

    def run(self) -> dict:
        """Blocking: run for duration_s (or until stop()), return the
        summary dict."""
        self.start()
        try:
            if self.options.duration_s > 0:
                self._stop.wait(self.options.duration_s)
            else:
                while not self._stop.is_set():
                    self._stop.wait(0.5)
        finally:
            self.stop()
        return self.summary()

    def start(self) -> None:
        opts = self.options
        if not opts.server or not opts.method:
            raise ValueError("press needs server and method")
        self._begin = time.monotonic()
        for i in range(max(1, opts.threads)):
            t = threading.Thread(target=self._worker, name=f"press_{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        self._reporter = threading.Thread(target=self._report_loop,
                                          daemon=True)
        self._reporter.start()

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)

    def summary(self) -> dict:
        elapsed = max(1e-9, time.monotonic() - self._begin)
        return {
            "sent": self.sent,
            "errors": self.errors,
            "elapsed_s": round(elapsed, 3),
            "qps": round(self.sent / elapsed, 1),
            "latency_us_p50": round(self.latency.p50(), 1),
            "latency_us_p99": round(self.latency.p99(), 1),
            "latency_us_avg": round(self.latency.latency(), 1),
        }

    # -- internals ---------------------------------------------------------

    def _payloads(self):
        inp = self.options.input
        if isinstance(inp, (bytes, bytearray, memoryview)):
            return [bytes(inp)]
        return [bytes(p) for p in inp] or [b""]

    def _worker(self) -> None:
        opts = self.options
        copts = ChannelOptions()
        copts.connection_type = opts.connection_type
        copts.timeout_ms = opts.timeout_ms
        ch = Channel(copts)
        if ch.init(opts.server, opts.lb_name) != 0:
            raise RuntimeError(f"cannot init channel to {opts.server}")
        payloads = self._payloads()
        npay = len(payloads)
        # per-thread pacing slice of the target qps
        per_thread_qps = opts.qps / max(1, opts.threads) if opts.qps else 0
        interval = 1.0 / per_thread_qps if per_thread_qps > 0 else 0.0
        next_at = time.monotonic()
        k = 0
        while not self._stop.is_set():
            if interval:
                now = time.monotonic()
                if now < next_at:
                    time.sleep(min(interval, next_at - now))
                    continue
                next_at += interval
                if now - next_at > 1.0:
                    next_at = now       # fell behind a full second: reset
            cntl = Controller()
            cntl.timeout_ms = opts.timeout_ms
            if opts.attachment:
                cntl.request_attachment.append(opts.attachment)
            t0 = time.monotonic()
            ch.call_method(opts.method, payloads[k % npay], cntl=cntl)
            us = int((time.monotonic() - t0) * 1e6)
            k += 1
            with self._lock:
                self.sent += 1
                if cntl.failed:
                    self.errors += 1
                else:
                    self.latency << us

    def _report_loop(self) -> None:
        report = self.options.report
        if report is None:
            report = lambda s: print(s, file=sys.stderr)  # noqa: E731
        last_sent = 0
        while not self._stop.wait(self.options.report_interval_s):
            sent = self.sent
            report(f"[rpc_press] qps={(sent - last_sent) / self.options.report_interval_s:.0f} "
                   f"sent={sent} errors={self.errors} "
                   f"p50={self.latency.p50():.0f}us "
                   f"p99={self.latency.p99():.0f}us")
            last_sent = sent


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="drive a tpu-rpc service at a target QPS")
    ap.add_argument("--server", required=True)
    ap.add_argument("--method", required=True,
                    help='"Service.Method"')
    ap.add_argument("--qps", type=int, default=0, help="0 = max speed")
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--threads", type=int, default=1)
    ap.add_argument("--timeout-ms", type=int, default=1000)
    ap.add_argument("--connection-type", default="pooled")
    ap.add_argument("--input", default="",
                    help="payload file (raw bytes); default empty payload")
    ap.add_argument("--lb", default="", help="load balancer for naming urls")
    args = ap.parse_args(argv)
    opts = PressOptions()
    opts.server = args.server
    opts.method = args.method
    opts.qps = args.qps
    opts.duration_s = args.duration
    opts.threads = args.threads
    opts.timeout_ms = args.timeout_ms
    opts.connection_type = args.connection_type
    opts.lb_name = args.lb
    if args.input:
        with open(args.input, "rb") as f:
            opts.input = f.read()
    summary = Press(opts).run()
    import json
    print(json.dumps(summary, indent=1))
    return 0 if summary["errors"] == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
