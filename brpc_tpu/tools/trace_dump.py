"""trace_dump — fetch a distributed trace from a live server and emit
Perfetto-loadable Chrome trace JSON.

The server does the heavy lifting: ``/rpcz?trace_id=X&stitch=1``
follows the trace's client spans to every sub-process they point at
(rpcz_stitch.collect_trace) and ``format=chrome`` renders the merged
span set as Chrome trace events.  Point this tool at the process
holding the trace's ROOT (usually the original caller): stitching
walks client spans' ``remote_side`` downward, so a sub-server can only
show its own branch.  The operator one-liner:

    python -m brpc_tpu.tools.trace_dump host:port TRACE_ID_HEX
    python -m brpc_tpu.tools.trace_dump host:port dead0 -o trace.json
    python -m brpc_tpu.tools.trace_dump host:port dead0 --tree
    python -m brpc_tpu.tools.trace_dump host:port dead0 --no-stitch

Open the JSON at https://ui.perfetto.dev (or chrome://tracing): every
process the call crossed shows as its own track, client and server
spans nest by parent id, clock-skew-flagged spans carry the skew in
their args.
"""

from __future__ import annotations

import http.client
import json
import sys
from typing import List, Optional


def fetch_trace(server: str, trace_id: int, fmt: str = "chrome",
                stitch: bool = True, limit: int = 512,
                timeout: float = 10.0) -> bytes:
    """Raw /rpcz response body for one trace (raises on non-200)."""
    host, _, port = server.rpartition(":")
    path = f"/rpcz?trace_id={trace_id:x}&format={fmt}&limit={int(limit)}"
    if stitch:
        path += "&stitch=1"
    conn = http.client.HTTPConnection(host or "127.0.0.1", int(port),
                                      timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            raise RuntimeError(f"HTTP {resp.status}: {body[:200]!r}")
        return body
    finally:
        conn.close()


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="dump a distributed rpcz trace as Perfetto-loadable "
                    "Chrome trace JSON")
    ap.add_argument("server", help="host:port of the server holding the "
                                   "trace's root spans (stitching follows "
                                   "client spans downward from there)")
    ap.add_argument("trace_id", help="trace id (hex, as shown on /rpcz)")
    ap.add_argument("-o", "--output", default="-",
                    help="output file (default: stdout)")
    ap.add_argument("--tree", action="store_true",
                    help="print a text tree instead of Chrome JSON")
    ap.add_argument("--no-stitch", action="store_true",
                    help="this process's spans only (no remote fetches)")
    ap.add_argument("--limit", type=int, default=512,
                    help="max spans per process (default 512)")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    try:
        tid = int(args.trace_id, 16)
    except ValueError:
        print(f"bad trace id {args.trace_id!r} (hex expected)",
              file=sys.stderr)
        return 2
    fmt = "tree" if args.tree else "chrome"
    try:
        body = fetch_trace(args.server, tid, fmt=fmt,
                           stitch=not args.no_stitch, limit=args.limit,
                           timeout=args.timeout)
    except Exception as e:
        print(f"fetch failed: {e}", file=sys.stderr)
        return 1
    if fmt == "chrome":
        # validate + count before writing: an empty trace is a usage
        # error the operator should see, not a blank file
        doc = json.loads(body)
        n = sum(1 for ev in doc.get("traceEvents", ())
                if ev.get("ph") == "X")
        if n == 0:
            print(f"trace {tid:x} has no spans on {args.server} "
                  "(expired from the store, or wrong server?)",
                  file=sys.stderr)
            return 1
        print(f"{n} span(s)", file=sys.stderr)
    if args.output == "-":
        sys.stdout.write(body.decode("utf-8", "replace"))
    else:
        with open(args.output, "wb") as f:
            f.write(body)
        print(f"wrote {args.output}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
