"""perf_guard — mechanical bench-regression gate (ISSUE 8 CI/tooling).

Compares a bench result (the one-line JSON ``bench.py`` prints, or a
driver ``BENCH_*.json`` capture of it) against one or more recorded
baselines with a tolerance band, and exits non-zero on regression — so
`http_slim_vs_classic` / `goodput_under_overload`-style drift is caught
by the pipeline instead of a reviewer's eyeball.

Usage (the documented post-bench step)::

    python bench.py | tee /tmp/bench.out
    python -m brpc_tpu.tools.perf_guard /tmp/bench.out \
        --baseline BENCH_r05.json --tolerance 0.5 --check

``--check`` additionally runs the static-analysis suite
(``brpc_tpu.tools.check`` — contract drift, lane invariants, closed
enums/flags, loop-thread blocking calls), so the one documented
post-bench invocation gates both perf and contracts.

Direction is inferred from the key name (``*_qps``/``*_gbps``/... are
higher-is-better; ``*_us``/``*_ms`` are lower-is-better; ratio keys on
the WATCHED list are higher-is-better).  Keys with no inferable
direction are ignored unless explicitly ``--watch``\\ ed.  The default
tolerance is deliberately wide (50%): the session boxes swing ~2x
between scheduler phases, and the guard exists to catch collapses and
sign flips, not noise.  Keys absent from either side are reported but
never fail the gate (benches grow keys over time).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Dict, Optional, Tuple

# keys the guard always watches when present on both sides, including
# the ratio keys whose drift history motivated the tool (ratios are
# phase-immune, so their band can be meaningfully tighter than raw
# throughput keys — see --ratio-tolerance)
WATCHED_RATIOS = (
    "http_slim_vs_classic",
    "goodput_under_overload",
    "zero_copy_vs_copy_gbps",
    "grpc_vs_grpcio_oracle",
    "fanout_cntl_vs_raw_gap",
    "cntl_vs_raw_gap",
    # multi-core engine (ISSUE 11): qps(N)/(N*qps(1)) medians over
    # paired interleaved rounds — phase-immune like the other ratios.
    # NOTE the 1-core caveat (PERF §14): the hardware ceiling is ~1/N
    # there, so the recorded baseline, not an absolute bar, is the gate
    "loop_scaling_efficiency",
    "loop_scaling_efficiency_4loop",
    # kind-5 streaming lane (ISSUE 13): paired interleaved A/B of the
    # native stream transport vs the forced-Python lane at c=64
    "stream_native_vs_py",
    # SLO-tiered scheduler (ISSUE 17): all three are paired interleaved
    # A/B medians.  itl_gain = chunked-OFF loaded p99 / idle p99 (the
    # head-of-line stall a monolithic prefill inflicts — chunking keeps
    # the loaded p99 within noise of idle, so the gain is the whole
    # stall); victim_goodput = untiered/tiered interactive finish time
    # under batch contention; accept_rate = accepted draft tokens /
    # proposed (self-draft on the bench cfg is deterministic at 1.0 —
    # the verify pass is the identity ground truth either way)
    "slo_chunked_itl_gain",
    "slo_tier_victim_goodput",
    "spec_accept_rate",
    # inference-plane observability (ISSUE 18): 1.0 when the serving
    # telemetry's A/B overhead sits within the same-methodology
    # control noise (the raw lm_telemetry_*_pct keys are recorded
    # unscored — a pct next to an unknown noise floor gates nothing)
    "lm_telemetry_within_noise",
    # fleet observability (ISSUE 19): same shape as the serving-
    # telemetry gate one line up — the serving path pays a flag read
    # and a deque append, so the bar is "A/B median inside the
    # zero-effect control envelope", not an absolute pct
    "fleet_obs_within_noise",
)

# Recorded baselines for keys that predate any BENCH_r*.json capture —
# the session-box values recorded when the key landed.  Applied ONLY
# for keys absent from every --baseline file: the moment a driver
# capture carries the key, the capture's value replaces the recorded
# one outright (folding these into the best-of merge would pin slower
# boxes to this box's numbers forever).  New keys thus gate from day
# one instead of free-riding as "missing".
RECORDED_BASELINE = {
    # ISSUE 11 multi-core engine keys (1-core session box, 2026-08):
    "sweep_64b_pipelined_qps_1loop": 2049431.0,
    "sweep_64b_pipelined_qps_2loop": 2077149.0,
    "sweep_64b_pipelined_qps_4loop": 2039035.0,
    "loop_scaling_efficiency": 0.486,         # ~0.5 = 1-core ceiling
    "loop_scaling_efficiency_4loop": 0.244,   # ~0.25 = 1-core ceiling
    "sweep_64b_pipelined_4loop_p99_us": 460.8,
    # ISSUE 12 operability keys (session box, 2026-08): the victims'
    # p99 during a full 3-replica roll, and the 10k-idle-conn RSS
    # probe (client+server halves in one process — PERF §15)
    "drain_p99_victim_ms": 1.83,
    "conns_10k_rss_mb": 31.6,
    # ISSUE 13 streaming-lane keys (session box, 2026-08): c=64
    # sessions, 4 client processes; the A/B ratio is the native stream
    # transport vs the forced-Python lane, paired interleaved
    "stream_native_vs_py": 4.68,
    "stream_tokens_per_s": 3391.3,
    "stream_ttft_p99_ms": 319.66,
    "decode_stream_sessions": 64.0,
    # ISSUE 15 disaggregated prefill/decode keys (session box,
    # 2026-08): shm page-plane transfer, and the two-tier A/B at c=16
    # (disagg TTFT carries the handoff RPC; the ratio is paired).
    # Recorded at the WORSE of two runs (quiet: 9.11 GB/s / 28.4ms /
    # 1.52x; contended: 4.0 / 58.1 / 1.9) — conservative gates, the
    # guard exists to catch collapses
    "kv_transfer_gbps": 4.0,
    "disagg_ttft_p99_ms": 58.1,
    "disagg_vs_mono_ttft": 1.9,
    # ISSUE 16 paged-KV allocator keys (session box, 2026-08): the
    # sessions-per-box headline moves to the paged decode tier — 128
    # concurrent sessions on the SAME device byte budget as the 16
    # contiguous slots above (the overflow rides the host tier), so
    # the recorded bar moves 16 -> 128 with the bench.  Bytes/session
    # is near-deterministic (capped pool ÷ completed sessions); the
    # hit-TTFT is one decode step + RPC, recorded as measured
    "disagg_sessions_per_box": 128.0,
    "kv_bytes_per_session": 12288.0,
    "prefix_cache_hit_ttft_p99_ms": 17.7,
    # ISSUE 17 SLO-tiered scheduler keys (session box, 2026-08),
    # recorded at the WORSE of two runs of the final config (chunk
    # budget 16).  The loaded ITL p99 is stable (10.27/10.88); the
    # idle p99 is the noisy side of the pair (7.67-10.77 — p99 of a
    # 60-sample window is near-max statistics on a 1-core box), which
    # is why the gain ratio gates the contrast instead of an absolute
    # loaded/idle bar.  The contrast arms (chunked_off, spec plain,
    # untiered victim) are deliberately-degraded configs and are NOT
    # recorded — their ratios gate them
    "decode_itl_p99_ms": 10.88,
    "decode_itl_idle_p99_ms": 10.77,
    "slo_chunked_itl_gain": 120.5,
    "spec_decode_tokens_per_s": 2054.7,
    "spec_accept_rate": 1.0,
    "slo_tier_victim_ms": 588.2,
    "slo_tier_victim_goodput": 1.29,
    # ISSUE 18 observability gate (session box, 2026-08): the step
    # profiler + timelines are lock/alloc-free per sample by design,
    # so the bar is the boolean "within the control noise floor", not
    # an absolute pct (which would gate scheduler jitter, not code)
    "lm_telemetry_within_noise": 1.0,
    # ISSUE 19 fleet observability (session box, 2026-08): one report
    # push → visible on the registry's /fleet page over HTTP, end to
    # end (RPC ingest + page render + one poll round-trip).  Recorded
    # at the worse of two runs (11.6 / 19.4ms — the poll loop re-renders
    # the whole fleet page per probe, so this is an upper bound)
    "fleet_report_p99_ms": 19.4,
    "fleet_obs_within_noise": 1.0,
}

# keys pinned at EXACTLY zero: any non-zero value fails the gate
# regardless of tolerance (a failed request during a rolling restart is
# a correctness bug, not a perf regression) — the zero-base rule that
# exempts ratio denominators must not exempt these
PINNED_ZERO = ("rolling_restart_failed_rpcs",
               # a same-host KV handoff moving payload bytes through
               # the message path is a data-plane regression, not noise
               "disagg_handoff_copies",
               # a prefix-cache hit ALIASES the cached context pages
               # (refcounts move, bytes do not) — any copy during the
               # hit sessions means the cache degenerated to memcpy
               "prefix_alias_copies")

_HIGHER = ("_qps", "_gbps", "gbps", "_rps", "_tok_s", "tokens_per_s",
           "_tflops", "_speedup", "_frac", "_factor_inverse",
           "_sessions", "_sessions_per_box")
_LOWER = ("_us", "_ms", "_p50", "_p99", "_rss_mb",
          "_bytes_per_session")
# gap keys measure raw/cntl — LOWER is better (a shrinking gap is the
# win); amplification likewise
_LOWER_RATIOS = ("cntl_vs_raw_gap", "fanout_cntl_vs_raw_gap",
                 "retry_amplification_factor",
                 # paired two-tier/monolithic TTFT: the handoff's cost,
                 # shrinking is the win
                 "disagg_vs_mono_ttft")


def direction_of(key: str) -> Optional[int]:
    """+1 = higher is better, -1 = lower is better, None = unscored."""
    if key in _LOWER_RATIOS:
        return -1
    if key in WATCHED_RATIOS:
        return +1
    for suf in _LOWER:
        if key.endswith(suf):
            return -1
    for suf in _HIGHER:
        if key.endswith(suf):
            return +1
    return None


def _extract_record(text: str) -> Dict[str, float]:
    """Pull the flat metric dict out of bench output / a driver BENCH
    json.  Tolerates truncated captures (the driver keeps a bounded
    tail): the ``extra`` object is recovered by brace matching."""
    # 1. driver file: {"n":..., "tail": "...", "parsed": {...}}
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict):
        if isinstance(doc.get("parsed"), dict):
            rec = doc["parsed"]
            out = {k: v for k, v in rec.get("extra", {}).items()
                   if isinstance(v, (int, float))}
            if isinstance(rec.get("value"), (int, float)):
                out[rec.get("metric", "headline")] = rec["value"]
            return out
        if isinstance(doc.get("extra"), dict):
            out = {k: v for k, v in doc["extra"].items()
                   if isinstance(v, (int, float))}
            if isinstance(doc.get("value"), (int, float)):
                out[doc.get("metric", "headline")] = doc["value"]
            return out
        text = doc.get("tail", "") or ""
    # 2. a bench stdout line somewhere in the text
    for line in reversed(text.splitlines()):
        line = line.strip()
        if line.startswith('{"metric"'):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            out = {k: v for k, v in rec.get("extra", {}).items()
                   if isinstance(v, (int, float))}
            if isinstance(rec.get("value"), (int, float)):
                out[rec.get("metric", "headline")] = rec["value"]
            return out
    # 3. truncated head (the r05 shape): recover the extra dict by
    # brace-matching from '"extra": {'
    m = re.search(r'"extra":\s*\{', text)
    if m:
        depth = 0
        start = m.end() - 1
        for i in range(start, len(text)):
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
                if depth == 0:
                    try:
                        extra = json.loads(text[start:i + 1])
                    except ValueError:
                        break
                    return {k: v for k, v in extra.items()
                            if isinstance(v, (int, float))}
    return {}


def load_metrics(path: str) -> Dict[str, float]:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return _extract_record(f.read())


def compare(new: Dict[str, float], base: Dict[str, float],
            tolerance: float, ratio_tolerance: float,
            watch: Tuple[str, ...] = ()) -> Tuple[list, list]:
    """Returns (failures, rows).  A key fails when it moved past its
    band in the worse direction; unscored/missing keys only report."""
    failures = []
    rows = []
    keys = sorted(set(new) | set(base))
    for k in keys:
        if k in PINNED_ZERO:
            nv = new.get(k)
            if nv is None:
                rows.append((k, 0, nv, "missing", False))
            else:
                bad = nv != 0
                rows.append((k, 0, nv,
                             "REGRESSED" if bad else "ok", bad))
                if bad:
                    failures.append(k)
            continue
        d = direction_of(k)
        if d is None and k not in watch:
            continue
        if d is None:
            d = +1
        nv, bv = new.get(k), base.get(k)
        if nv is None or bv is None:
            rows.append((k, bv, nv, "missing", False))
            continue
        if bv == 0:
            rows.append((k, bv, nv, "zero-base", False))
            continue
        tol = ratio_tolerance if k in WATCHED_RATIOS \
            or k in _LOWER_RATIOS else tolerance
        if d > 0:
            bad = nv < bv * (1.0 - tol)
        else:
            bad = nv > bv * (1.0 + tol)
        verdict = "REGRESSED" if bad else "ok"
        rows.append((k, bv, nv, verdict, bad))
        if bad:
            failures.append(k)
    return failures, rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="perf_guard",
        description="fail when a bench run regressed past the band")
    ap.add_argument("new", help="bench output / BENCH_*.json of the run")
    ap.add_argument("--baseline", "-b", action="append", required=True,
                    help="recorded BENCH_*.json (repeatable: the best "
                         "recorded value per key is the bar)")
    ap.add_argument("--tolerance", type=float, default=0.5,
                    help="allowed fractional drop for throughput keys "
                         "(default 0.5 — the box swings ~2x by phase)")
    ap.add_argument("--ratio-tolerance", type=float, default=0.25,
                    help="band for paired-A/B ratio keys, which are "
                         "phase-immune (default 0.25)")
    ap.add_argument("--watch", action="append", default=[],
                    help="extra key to score (higher-is-better)")
    ap.add_argument("--check", action="store_true",
                    help="also run the static-analysis suite "
                         "(python -m brpc_tpu.tools.check): the "
                         "post-bench step then gates perf AND "
                         "contracts in one invocation")
    args = ap.parse_args(argv)

    check_rc = 0
    if args.check:
        # a suite ERROR must not masquerade as findings nor skip the
        # perf comparison below — same 0/1/2 contract as the check CLI
        try:
            from .check import run_all
            findings = run_all()
        except Exception as e:
            print(f"perf_guard --check: suite error: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            findings = None
            check_rc = 2
        if findings:
            for f in findings:
                print(f"{f.path}:{f.line}: [{f.analyzer}] {f.message}")
            print(f"perf_guard --check: {len(findings)} static "
                  "finding(s)", file=sys.stderr)
            check_rc = 1
        elif findings is not None:
            print("perf_guard --check: static suite clean")

    new = load_metrics(args.new)
    if not new:
        print(f"perf_guard: no metrics found in {args.new}",
              file=sys.stderr)
        return 2
    base: Dict[str, float] = {}
    for bp in args.baseline:
        for k, v in load_metrics(bp).items():
            d = direction_of(k)
            if k not in base:
                base[k] = v
            elif d == -1:
                base[k] = min(base[k], v)
            else:
                base[k] = max(base[k], v)
    # recorded day-one values only for keys no --baseline file carries
    # yet (see RECORDED_BASELINE comment: captures override outright)
    for k, v in RECORDED_BASELINE.items():
        base.setdefault(k, v)
    failures, rows = compare(new, base, args.tolerance,
                             args.ratio_tolerance, tuple(args.watch))
    w = max((len(r[0]) for r in rows), default=10)
    for k, bv, nv, verdict, _bad in rows:
        print(f"{k:<{w}}  base={bv!s:>12}  new={nv!s:>12}  {verdict}")
    if failures:
        print(f"perf_guard: {len(failures)} regression(s): "
              f"{', '.join(failures)}", file=sys.stderr)
        return 1
    print(f"perf_guard: {sum(1 for r in rows if r[3] == 'ok')} keys "
          "within band")
    return check_rc


if __name__ == "__main__":
    sys.exit(main())
