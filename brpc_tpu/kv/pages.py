"""KV-cache pages — first-class transferable objects with an explicit
RDMA-style lifecycle.

A serving session's KV-cache is not a blob to serialize: it is a set of
**pages** (one per layer cache array) that a prefill tier *exports*,
*describes* over the control plane, and a decode tier *imports* — the
payload itself moving as registered memory (the in-process/ICI fabric,
or a shm ring slot), never through the serialized message path.  This
module is the export registry: the sender-side bookkeeping that makes a
page a capability with a bounded lifetime instead of a leaked alias.

Lifecycle (mirrors ``transport/shm_ring``'s slot discipline):

    export    the page's device array is posted on the ICI fabric
              (``InProcessFabric.post`` — the "memory registration")
              and pinned in a FIXED page table under a fresh
              generation; the table is bounded, so a leak is visible
              as exhaustion, not as silent growth
    describe  ``(page_id, generation, nbytes)`` — 12 bytes on the wire
              per page; the generation makes every descriptor
              single-lifetime (a recycled page id cannot resolve an
              old descriptor)
    import    one-shot: resolves the descriptor through the registry
              and CONSUMES the fabric entry (``InProcessFabric.take``),
              so a second import of the same descriptor — or an import
              after the exporter released — fails LOUDLY with
              :class:`KvPageError` (surfaced as ERESPONSE by the
              handoff service, never "success with an empty cache")
    release   generation-checked: releasing a page twice, or with a
              stale generation, raises instead of freeing the table
              slot's NEXT tenant

Pages are tagged with an **owner** key at export (the client
connection whose session they belong to): a dying socket sweeps its
pages (``on_socket_closed``, wired into ``Socket.release`` next to the
shm sweep), and the drain plane waits for every outstanding exported
page to settle before the process exits (``drain_settle``, bounded by
the drain grace like the shm ring's).
"""

from __future__ import annotations

import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..butil.flags import define_flag, get_flag
from ..butil.logging_util import LOG

define_flag("kv_pages", 256,
            "size of the KV page export table (exported-but-unsettled "
            "pages; bounded so leaks surface as exhaustion)",
            validator=lambda v: isinstance(v, int) and 0 < v <= 65535)

_DESC_FMT = "<IIQ"          # page_id, generation, nbytes
DESC_BYTES = struct.calcsize(_DESC_FMT)


class KvPageError(Exception):
    """A KV page descriptor this process cannot honor — stale
    generation, double import, double free, or an unknown page.  A
    protocol violation, not a fallback shape: the handoff service
    answers ERESPONSE (the import side must fail loudly, never hand
    the decoder an empty cache)."""


class KvPageHandle:
    """Sender-side lease of one exported page (settle exactly once)."""

    __slots__ = ("page_id", "gen", "nbytes")

    def __init__(self, page_id: int, gen: int, nbytes: int):
        self.page_id = page_id
        self.gen = gen
        self.nbytes = nbytes

    def describe(self) -> bytes:
        return struct.pack(_DESC_FMT, self.page_id, self.gen,
                           self.nbytes)


def decode_desc(data: bytes) -> Tuple[int, int, int]:
    if len(data) != DESC_BYTES:
        raise KvPageError(f"malformed kv page descriptor "
                          f"({len(data)} bytes)")
    return struct.unpack(_DESC_FMT, data)


class _Rec:
    __slots__ = ("desc_id", "nbytes", "owner", "imported")

    def __init__(self, desc_id: int, nbytes: int, owner: Any):
        self.desc_id = desc_id
        self.nbytes = nbytes
        self.owner = owner
        self.imported = False


class KvPageStore:
    """The process's page export table (fixed size, generation-checked
    — the shm ring's slot model applied to device arrays)."""

    def __init__(self, npages: int):
        self.npages = int(npages)
        self._lock = threading.Lock()
        self._recs: List[Optional[_Rec]] = [None] * self.npages
        self._gen = [0] * self.npages
        self._free = list(range(self.npages))
        self.exported = 0            # lifetime counters (stats)
        self.imported = 0
        self.swept = 0

    # -- export ------------------------------------------------------------

    def export_array(self, array: Any, nbytes: int,
                     owner: Any = None) -> Optional[KvPageHandle]:
        """Register one page (a live device array) for transfer.  The
        array is posted on the in-process fabric — kept alive and
        addressable until imported, released, or swept.  Returns None
        when the table is full (the caller falls back under a NAMED
        reason — exhaustion is backpressure, not an error)."""
        from ..ici.fabric import in_process_fabric
        with self._lock:
            if not self._free:
                return None
            page_id = self._free.pop()
            self._gen[page_id] += 1
            gen = self._gen[page_id]
        desc_id = in_process_fabric().post(array, nbytes)
        with self._lock:
            self._recs[page_id] = _Rec(desc_id, nbytes, owner)
            self.exported += 1
        return KvPageHandle(page_id, gen, nbytes)

    # -- import (one-shot, loud) -------------------------------------------

    def import_page(self, page_id: int, gen: int, nbytes: int) -> Any:
        """Resolve a descriptor into its array, CONSUMING the fabric
        entry: the importer owns the array from here on.  Stale
        generation, unknown page, size mismatch, or a second import all
        raise :class:`KvPageError` — the loud-failure contract."""
        from ..ici.fabric import in_process_fabric
        with self._lock:
            rec = self._recs[page_id] \
                if 0 <= page_id < self.npages else None
            if rec is None or self._gen[page_id] != gen:
                raise KvPageError(
                    f"stale kv page import (page {page_id} gen {gen})")
            if rec.imported:
                raise KvPageError(
                    f"kv page {page_id} already imported")
            if rec.nbytes != nbytes:
                raise KvPageError(
                    f"kv page {page_id} size mismatch "
                    f"({nbytes} != {rec.nbytes})")
            desc_id = rec.desc_id
            rec.imported = True
        arr = in_process_fabric().take(desc_id)
        if arr is None:
            # released/swept between the rec check and the take — the
            # registry says live but the registration is gone: loud
            raise KvPageError(
                f"kv page {page_id} no longer registered")
        with self._lock:
            self.imported += 1
        return arr

    # -- release (generation-checked, loud on misuse) ----------------------

    def release(self, page_id: int, gen: int) -> None:
        """Settle one exported page (the sender's end-of-handoff).
        Double-free and stale-generation frees raise — a silent no-op
        here would free the table slot's NEXT tenant one day."""
        from ..ici.fabric import in_process_fabric
        with self._lock:
            rec = self._recs[page_id] \
                if 0 <= page_id < self.npages else None
            if rec is None or self._gen[page_id] != gen:
                raise KvPageError(
                    f"double/stale kv page free (page {page_id} "
                    f"gen {gen})")
            self._recs[page_id] = None
            self._free.append(page_id)
            desc_id, imported = rec.desc_id, rec.imported
        if not imported:
            # never imported: drop the fabric registration ourselves
            in_process_fabric().release(desc_id)

    def settle_handles(self, handles) -> None:
        """Release a handoff's whole page set (each exactly once)."""
        for h in handles:
            self.release(h.page_id, h.gen)

    # -- sweeps / drain ----------------------------------------------------

    def release_owner(self, owner: Any) -> int:
        """Reclaim every page tagged with ``owner`` (its connection
        died before the handoff settled).  Soft by design — the sweep
        races legitimate settles and must not throw at either."""
        from ..ici.fabric import in_process_fabric
        stale = []
        with self._lock:
            for page_id, rec in enumerate(self._recs):
                if rec is not None and rec.owner == owner:
                    self._recs[page_id] = None
                    self._free.append(page_id)
                    if not rec.imported:
                        stale.append(rec.desc_id)
                    self.swept += 1
        for desc_id in stale:
            in_process_fabric().release(desc_id)
        return len(stale)

    def outstanding(self) -> int:
        with self._lock:
            return self.npages - len(self._free)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"pages": self.npages,
                    "outstanding": self.npages - len(self._free),
                    "exported": self.exported,
                    "imported": self.imported,
                    "swept": self.swept}


# ---------------------------------------------------------------------------
# Process-wide registry (mirrors shm_ring's process_tx_ring shape)
# ---------------------------------------------------------------------------

_reg_lock = threading.Lock()
_store: Optional[KvPageStore] = None


def process_kv_store() -> KvPageStore:
    global _store
    with _reg_lock:
        if _store is None:
            _store = KvPageStore(int(get_flag("kv_pages")))
        return _store


def on_socket_closed(owner: Any) -> None:
    """Sweep pages exported for a dead connection (its handoff will
    never settle) — wired into ``Socket.release`` next to the shm
    sweep, so it runs on the owning loop and must stay non-blocking."""
    with _reg_lock:
        store = _store
    if store is not None:
        n = store.release_owner(owner)
        if n:
            LOG.info("kv page sweep: %d page(s) of dead owner %r", n,
                     owner)


def outstanding_pages() -> int:
    """Exported-but-unsettled pages — the drain plane's gauge (0 when
    the kv plane never engaged)."""
    with _reg_lock:
        store = _store
    return store.outstanding() if store is not None else 0


def drain_settle(deadline_mono_s: float) -> int:
    """Operability plane: wait — bounded by the drain-grace deadline —
    for every outstanding exported page to settle (handoff responses
    release them; dead-conn sweeps run from socket close).  Returns
    pages still outstanding at the deadline (0 = fully settled)."""
    import time as _time
    ev = threading.Event()
    while True:
        n = outstanding_pages()
        if n == 0:
            return 0
        if _time.monotonic() >= deadline_mono_s:
            return n
        ev.wait(0.005)     # timed: the drain path stays deadline-bound


def _reset_for_tests() -> None:
    global _store
    with _reg_lock:
        _store = None
